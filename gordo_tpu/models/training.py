"""
The JAX training engine: spec + arrays → trained params + history.

This replaces the reference's ``keras.Model.fit`` call inside its estimator
wrapper (gordo/machine/model/models.py:243-287). Design is TPU-first:

- **One device program per fit.** When callbacks can be compiled in (the
  common case — EarlyStopping becomes masked updates), the entire
  epochs×batches loop is a nested ``lax.scan`` under one ``jit``; the host
  dispatches once and reads back final params + per-epoch losses. No
  per-batch (or even per-epoch) host↔device ping-pong.
- **Static shapes.** Data is padded host-side to a whole number of batches
  with a weight mask; shuffling is a device-side ``jax.random.permutation``
  per epoch, so the compiled program is reused across epochs and across
  models with the same (spec, shape).
- **Keras-compatible semantics** where they matter for parity: the
  validation split is the *last* fraction of the data (taken before
  shuffling), shuffle applies to the training portion only, epoch "loss" is
  the sample-weighted mean, Adam defaults match Keras.

The fleet path (gordo_tpu/parallel/fleet.py) vmaps `_train_step`/`_epoch`
logic over a stacked model axis; both paths share these functions.
"""

import logging
from dataclasses import dataclass
from functools import lru_cache
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .. import telemetry
from ..ops.losses import resolve_loss, weighted_mean_loss
from .callbacks import Callback, EarlyStopping
from .nn import forward_fn_for, init_fn_for
from .spec import ModelSpec

logger = logging.getLogger(__name__)


def segmented_config() -> Optional[int]:
    """The opt-in segments-per-update for segmented LSTM training (env
    GORDO_TPU_LSTM_SEGMENTED: 0/unset = off, N = segments per update;
    see build_raw_segmented_fit_fn for the trade). Shared by the fleet
    trainer and the single-model estimator path."""
    from ..utils.env import env_int

    value = env_int("GORDO_TPU_LSTM_SEGMENTED", 0)
    return value if value > 0 else None


@dataclass(frozen=True)
class FitConfig:
    """Static (hashable) fit configuration — part of the compilation key."""

    epochs: int = 1
    batch_size: int = 32
    validation_split: float = 0.0
    shuffle: bool = True
    # (monitor, patience, min_delta, restore_best_weights) or None
    early_stopping: Optional[Tuple[str, int, float, bool]] = None


@dataclass
class History:
    """Keras-History-shaped fit record (consumed by get_metadata)."""

    history: Dict[str, List[float]]
    params: Dict[str, Any]
    epoch: List[int]


def split_fit_kwargs(kwargs: dict) -> Tuple[dict, dict]:
    """Split estimator kwargs into (fit-related, factory-related)."""
    fit_keys = {
        "epochs",
        "batch_size",
        "validation_split",
        "shuffle",
        "callbacks",
        "verbose",
        "initial_epoch",
        "seed",
    }
    fit_args = {k: v for k, v in kwargs.items() if k in fit_keys}
    rest = {k: v for k, v in kwargs.items() if k not in fit_keys}
    return fit_args, rest


def fit_config_from_kwargs(kwargs: dict) -> Tuple[FitConfig, List[Callback]]:
    """
    Build a FitConfig from Keras-style fit kwargs. EarlyStopping callbacks
    compile into the config; any other callbacks are returned for the
    host-loop path.
    """
    callbacks = list(kwargs.get("callbacks") or [])
    early_stopping = None
    early_stoppers: List[Callback] = []
    host_callbacks: List[Callback] = []
    for cb in callbacks:
        if isinstance(cb, EarlyStopping):
            early_stoppers.append(cb)
            early_stopping = (
                cb.monitor,
                cb.patience,
                cb.min_delta,
                cb.restore_best_weights,
            )
        elif isinstance(cb, Callback):
            host_callbacks.append(cb)
        else:
            raise TypeError(f"Unsupported callback: {cb!r}")
    if host_callbacks:
        # The host loop runs all callbacks; EarlyStopping must ride along
        # rather than being compiled into a program that never runs.
        host_callbacks = early_stoppers + host_callbacks
        early_stopping = None
    config = FitConfig(
        epochs=int(kwargs.get("epochs", 1)),
        batch_size=int(kwargs.get("batch_size", 32)),
        validation_split=float(kwargs.get("validation_split", 0.0)),
        shuffle=bool(kwargs.get("shuffle", True)),
        early_stopping=early_stopping,
    )
    return config, host_callbacks


def _tree_where(flag, a, b):
    return jax.tree_util.tree_map(
        lambda x, y: jnp.where(flag, x, y), a, b
    )


def _make_fit_loop(config: FitConfig, train_epoch, evaluate_val):
    """
    The shared epochs×early-stopping scaffold of every fused fit program
    (dense and windowed): scans ``train_epoch`` over per-epoch RNG keys
    with EarlyStopping compiled in as masked updates.

    ``train_epoch(params, opt_state, erng) -> (params, opt_state, loss)``
    and ``evaluate_val(params) -> val_loss`` (NaN when there is no
    validation data — see weighted_mean_loss) close over the training
    arrays; this function owns everything else.

    Returns ``fit_tail(params, opt_state, rng) -> (params, opt_state,
    losses[epochs], val_losses[epochs], epochs_ran)``.
    """
    es = config.early_stopping
    monitor_val = es is not None and es[0] == "val_loss"

    def fit_tail(params, opt_state, rng):
        def epoch_body(carry, erng):
            params, opt_state, best, best_params, wait, stopped = carry
            stopped_at_start = stopped
            new_params, new_opt, loss = train_epoch(params, opt_state, erng)
            # When already stopped, freeze state (masked update keeps one
            # compiled program; tiny models make the dead compute negligible).
            params = _tree_where(stopped, params, new_params)
            opt_state = _tree_where(stopped, opt_state, new_opt)
            val_loss = evaluate_val(params)
            if es is not None:
                if monitor_val:
                    # Per-member fallback: a fleet member with no validation
                    # rows gets NaN val_loss; monitor train loss instead.
                    monitor = jnp.where(jnp.isnan(val_loss), loss, val_loss)
                else:
                    monitor = loss
                improved = monitor < best - es[2]
                best = jnp.where(~stopped & improved, monitor, best)
                if es[3]:
                    best_params = _tree_where(
                        ~stopped & improved, params, best_params
                    )
                wait = jnp.where(stopped, wait, jnp.where(improved, 0, wait + 1))
                stopped = stopped | (wait >= jnp.maximum(es[1], 1))
            ran = ~stopped_at_start if es is not None else jnp.array(True)
            return (params, opt_state, best, best_params, wait, stopped), (
                loss,
                val_loss,
                ran,
            )

        rngs = jax.random.split(rng, config.epochs)
        init_carry = (
            params,
            opt_state,
            jnp.array(jnp.inf, jnp.float32),
            params,
            jnp.array(0, jnp.int32),
            jnp.array(False),
        )
        (params, opt_state, _, best_params, _, _), (losses, val_losses, ran) = (
            jax.lax.scan(epoch_body, init_carry, rngs)
        )
        if es is not None and es[3]:
            params = best_params
        return params, opt_state, losses, val_losses, jnp.sum(ran.astype(jnp.int32))

    return fit_tail


def _pad_to_batches(
    X: np.ndarray, y: np.ndarray, batch_size: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Pad to a whole number of batches; returns (X, y, weights, steps)."""
    n = X.shape[0]
    steps = max(1, -(-n // batch_size))
    total = steps * batch_size
    pad = total - n
    if pad:
        X = np.concatenate([X, np.repeat(X[-1:], pad, axis=0)], axis=0)
        y = np.concatenate([y, np.repeat(y[-1:], pad, axis=0)], axis=0)
    weights = np.concatenate(
        [np.ones(n, dtype=X.dtype), np.zeros(pad, dtype=X.dtype)]
    )
    return X, y, weights, steps


@lru_cache(maxsize=None)
def _eval_fn(spec: ModelSpec):
    forward = forward_fn_for(spec)
    per_sample = resolve_loss(spec.loss)

    @jax.jit
    def evaluate(params, X, y, w):
        out, _ = forward(spec, params, X)
        return weighted_mean_loss(per_sample(out, y), w)

    return evaluate


@lru_cache(maxsize=None)
def predict_fn(spec: ModelSpec):
    """Jitted forward pass for a spec (used by estimator.predict and server)."""
    forward = forward_fn_for(spec)

    @jax.jit
    def predict(params, X):
        return forward(spec, params, X)[0]

    return predict


@lru_cache(maxsize=None)
def build_raw_fit_fn(spec: ModelSpec, config: FitConfig):
    """
    The *unjitted* fused fit function for (spec, config):
    (params, opt_state, Xtr, ytr, wtr, Xval, yval, wval, rng) ->
    (params, opt_state, losses[epochs], val_losses[epochs], epochs_ran).

    Everything — ragged lengths, validation split, fold boundaries — is
    expressed through the weight vectors, so the same function serves the
    single-model path (jit) and the fleet path (jit∘vmap over a stacked
    model axis, sharded across the mesh).
    """
    forward = forward_fn_for(spec)
    per_sample = resolve_loss(spec.loss)
    tx = spec.optimizer.to_optax()

    def batch_loss(params, xb, yb, wb):
        out, penalty = forward(spec, params, xb)
        # Keras adds activity-regularization losses as the raw batch sum, not
        # averaged; padding rows (duplicates of the last sample) inflate the
        # final partial batch's penalty slightly — negligible at l1≈1e-4.
        return weighted_mean_loss(per_sample(out, yb), wb) + penalty

    grad_fn = jax.value_and_grad(batch_loss)

    def train_epoch(params, opt_state, Xtr, ytr, wtr, erng):
        n_total = Xtr.shape[0]
        steps = n_total // config.batch_size
        if config.shuffle:
            # One whole-array permutation per epoch, then contiguous batch
            # slices via scan-over-xs. Per-batch index gathers were the fleet
            # hot spot on TPU (measured 2.4× whole-fit slowdown at 256
            # models): 640 small gather kernels vs 20 large ones.
            perm = jax.random.permutation(erng, n_total)
            Xtr = jnp.take(Xtr, perm, axis=0)
            ytr = jnp.take(ytr, perm, axis=0)
            wtr = jnp.take(wtr, perm, axis=0)
        batches = (
            Xtr.reshape((steps, config.batch_size) + Xtr.shape[1:]),
            ytr.reshape((steps, config.batch_size) + ytr.shape[1:]),
            wtr.reshape(steps, config.batch_size),
        )

        def step(carry, batch):
            params, opt_state = carry
            xb, yb, wb = batch
            loss, grads = grad_fn(params, xb, yb, wb)
            updates, new_opt_state = tx.update(grads, opt_state, params)
            # An all-padding batch (possible for short members of a padded
            # fleet bucket) must be a true no-op: zero grads would still
            # advance Adam momentum and drift the params, and its NaN loss
            # must not poison the epoch sum.
            has_data = jnp.sum(wb) > 0
            params = _tree_where(
                has_data, optax.apply_updates(params, updates), params
            )
            opt_state = _tree_where(has_data, new_opt_state, opt_state)
            contribution = jnp.where(has_data, loss * jnp.sum(wb), 0.0)
            return (params, opt_state), contribution

        (params, opt_state), weighted_losses = jax.lax.scan(
            step, (params, opt_state), batches
        )
        epoch_loss = jnp.sum(weighted_losses) / jnp.maximum(jnp.sum(wtr), 1.0)
        return params, opt_state, epoch_loss

    def evaluate(params, X, y, w):
        out, _ = forward(spec, params, X)
        return weighted_mean_loss(per_sample(out, y), w)

    compute_dtype = jnp.dtype(spec.compute_dtype)

    def fit(params, opt_state, Xtr, ytr, wtr, Xval, yval, wval, rng):
        if compute_dtype != jnp.float32:
            # one on-device cast up front: the epoch scan then re-reads the
            # half-width copy from HBM every step (the bandwidth the tiny-
            # model regime is bound by), not the f32 staging buffer
            Xtr, ytr = Xtr.astype(compute_dtype), ytr.astype(compute_dtype)
            Xval, yval = Xval.astype(compute_dtype), yval.astype(compute_dtype)
        has_val = Xval.shape[0] > 0  # static branch: no-val fleets skip it

        fit_tail = _make_fit_loop(
            config,
            train_epoch=lambda p, o, erng: train_epoch(p, o, Xtr, ytr, wtr, erng),
            evaluate_val=lambda p: (
                evaluate(p, Xval, yval, wval)
                if has_val
                else jnp.array(jnp.nan, jnp.float32)
            ),
        )
        return fit_tail(params, opt_state, rng)

    return fit


@lru_cache(maxsize=None)
def build_raw_windowed_fit_fn(spec: ModelSpec, config: FitConfig):
    """
    The fused fit for windowed (LSTM) models with windows gathered ON
    DEVICE from the raw series, per batch:

    ``(params, opt_state, series[n, F], ytgt[nw, F], order[nv], wtr[nv],
    wval[nv], rng) -> (params, opt_state, losses, val_losses, epochs_ran)``

    The dense path pre-materializes ``[n_windows, lookback, F]`` windows —
    a ``lookback×`` HBM blowup that caps LSTM fleet size (1000 machines at
    lookback 120 ≈ 13 GB for the windows alone, over a v5e chip's HBM).
    Here only the ``[n, F]`` series and the ``[nw, F]`` aligned targets
    stay resident; each training step gathers its batch of windows from
    the series (``starts[:, None] + arange(lookback)``).

    - ``ytgt`` is aligned host-side via ``ops.windows.window_targets`` (so
      lookahead is already folded in): window ``j`` covers
      ``series[j : j+lookback]`` with target ``ytgt[j]``.
    - ``order`` maps virtual training slots to original window starts
      (the detector-level shuffle of fleet_build, plus padding slots that
      point at window 0 with zero weight).
    - ``wtr``/``wval`` are per-VIRTUAL-slot weights, exactly like the
      dense path's masks.

    Given the same virtual ordering and batch geometry, this trains
    bit-for-bit like the dense path on pre-materialized windows
    (tests/parallel/test_fleet_windowed.py asserts it).
    """
    forward = forward_fn_for(spec)
    per_sample = resolve_loss(spec.loss)
    tx = spec.optimizer.to_optax()
    lookback = spec.lookback_window

    def gather_windows(series, starts):
        idx = starts[:, None] + jnp.arange(lookback)[None, :]
        return series[idx]  # [B, lookback, F]

    def batch_loss(params, series, ytgt, starts, wb):
        xb = gather_windows(series, starts)
        yb = jnp.take(ytgt, starts, axis=0)
        out, penalty = forward(spec, params, xb)
        return weighted_mean_loss(per_sample(out, yb), wb) + penalty

    grad_fn = jax.value_and_grad(batch_loss)

    def train_epoch(params, opt_state, series, ytgt, order, wtr, erng):
        nv = order.shape[0]
        steps = nv // config.batch_size
        if config.shuffle:
            perm = jax.random.permutation(erng, nv)
            order_e = jnp.take(order, perm)
            wtr_e = jnp.take(wtr, perm)
        else:
            order_e, wtr_e = order, wtr
        starts_b = order_e.reshape(steps, config.batch_size)
        w_b = wtr_e.reshape(steps, config.batch_size)

        def step(carry, batch):
            params, opt_state = carry
            starts, wb = batch
            loss, grads = grad_fn(params, series, ytgt, starts, wb)
            updates, new_opt_state = tx.update(grads, opt_state, params)
            has_data = jnp.sum(wb) > 0
            params = _tree_where(
                has_data, optax.apply_updates(params, updates), params
            )
            opt_state = _tree_where(has_data, new_opt_state, opt_state)
            contribution = jnp.where(has_data, loss * jnp.sum(wb), 0.0)
            return (params, opt_state), contribution

        (params, opt_state), weighted_losses = jax.lax.scan(
            step, (params, opt_state), (starts_b, w_b)
        )
        epoch_loss = jnp.sum(weighted_losses) / jnp.maximum(jnp.sum(wtr), 1.0)
        return params, opt_state, epoch_loss

    def evaluate(params, series, ytgt, order, w):
        # Batched scan, not one full-window forward: validation memory must
        # stay bounded for the same reason training's does.
        nv = order.shape[0]
        steps = nv // config.batch_size

        def step(acc, batch):
            starts, wb = batch
            xb = gather_windows(series, starts)
            yb = jnp.take(ytgt, starts, axis=0)
            out, _ = forward(spec, params, xb)
            losses = per_sample(out, yb)
            return (acc[0] + jnp.sum(losses * wb), acc[1] + jnp.sum(wb)), None

        (total, wsum), _ = jax.lax.scan(
            step,
            (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            (
                order.reshape(steps, config.batch_size),
                w.reshape(steps, config.batch_size),
            ),
        )
        return jnp.where(wsum > 0, total / wsum, jnp.nan)

    compute_dtype = jnp.dtype(spec.compute_dtype)

    def fit(params, opt_state, series, ytgt, order, wtr, wval, rng):
        if compute_dtype != jnp.float32:
            series, ytgt = series.astype(compute_dtype), ytgt.astype(compute_dtype)
        fit_tail = _make_fit_loop(
            config,
            train_epoch=lambda p, o, erng: train_epoch(
                p, o, series, ytgt, order, wtr, erng
            ),
            evaluate_val=lambda p: evaluate(p, series, ytgt, order, wval),
        )
        return fit_tail(params, opt_state, rng)

    return fit


@lru_cache(maxsize=None)
def build_raw_segmented_fit_fn(
    spec: ModelSpec, config: FitConfig, segments_per_update: int
):
    """
    Segmented (stateful-scan) fit for windowed LSTM models:

    ``(params, opt_state, series[n, F], ytgt[nw, F], wtr[nv], wval[nv],
    rng) -> (params, opt_state, losses, val_losses, epochs_ran)``

    The window-restart path (build_raw_windowed_fit_fn) re-runs the
    recurrence from zero state for every stride-1 window: a batch of B
    windows costs ``B×lookback`` cell applications for ``B+lookback-1``
    distinct timesteps — a ~``lookback×`` FLOP/HBM redundancy (reference
    semantics: Keras stateless LSTM over materialized windows,
    gordo/machine/model/models.py:713-793).

    Here each Adam update still covers the SAME B consecutive windows as
    the unshuffled windowed path, but computes them as
    ``segments_per_update`` (G) parallel segments of ``L = B/G``
    consecutive windows: one recurrence pass of ``L+lookback-1`` steps
    per segment yields every window output in the segment via
    :func:`nn.forward_lstm_sequence`. Cell applications per update drop
    from ``B×lookback`` to ``B + G×(lookback-1)``; sequential depth
    rises from ``lookback`` to ``L+lookback-1``. ``G=B`` (L=1) is
    bit-equivalent to the windowed path (tests assert it); small ``G``
    trades depth for a ~``lookback×`` FLOP cut.

    Semantics difference (the reason this is opt-in): within a segment,
    window ``j`` at position ``p`` sees hidden state warmed by the
    ``p-lookback+1`` preceding segment steps instead of starting cold —
    the first window of each segment is exactly cold, later ones
    approximate it (LSTM state forgets geometrically). Training is
    therefore TBPTT-like; serving still scores cold windows. Parity is
    gated at the anomaly-surface level like TF parity
    (compat/tf_parity.py), not bit-level.

    Requires ``config.shuffle == False`` (the product LSTM path pins
    this, matching the reference's unshuffled timeseries generator) and
    identity window order — segments must be consecutive windows.
    """
    if config.shuffle:
        raise ValueError("segmented LSTM training requires shuffle=False")
    from .nn import forward_lstm_sequence

    per_sample = resolve_loss(spec.loss)
    tx = spec.optimizer.to_optax()
    lookback = spec.lookback_window
    G = segments_per_update
    B = config.batch_size
    if B % G:
        raise ValueError(f"batch_size {B} not divisible by segments {G}")
    L = B // G
    span = L + lookback - 1  # timesteps one segment must read

    def update_loss(params, series, ytgt, starts, w):
        # starts: [G] window-start heads of this update's segments;
        # w: [G, L] per-window weights (0 for padding)
        n = series.shape[0]
        t_idx = jnp.minimum(starts[:, None] + jnp.arange(span)[None, :], n - 1)
        segs = series[t_idx]  # [G, span, F]
        out_seq = forward_lstm_sequence(
            spec, params, jnp.transpose(segs, (1, 0, 2))
        )  # [span, G, F_out]
        outs = jnp.transpose(out_seq[lookback - 1 :], (1, 0, 2))  # [G, L, Fo]
        w_idx = jnp.minimum(
            starts[:, None] + jnp.arange(L)[None, :], ytgt.shape[0] - 1
        )
        targets = ytgt[w_idx]  # [G, L, F_out]
        losses = per_sample(
            outs.reshape(B, -1), targets.reshape(B, -1)
        )
        return weighted_mean_loss(losses, w.reshape(B))

    grad_fn = jax.value_and_grad(update_loss)

    def train_epoch(params, opt_state, series, ytgt, wtr, erng):
        del erng  # shuffle=False: epoch order is the window order
        nv = wtr.shape[0]
        K = nv // B  # updates per epoch, same count as the windowed path
        heads = (
            jnp.arange(K)[:, None] * B + jnp.arange(G)[None, :] * L
        )  # [K, G]
        w_b = wtr.reshape(K, G, L)

        def step(carry, batch):
            params, opt_state = carry
            starts, wb = batch
            loss, grads = grad_fn(params, series, ytgt, starts, wb)
            updates, new_opt_state = tx.update(grads, opt_state, params)
            has_data = jnp.sum(wb) > 0
            params = _tree_where(
                has_data, optax.apply_updates(params, updates), params
            )
            opt_state = _tree_where(has_data, new_opt_state, opt_state)
            contribution = jnp.where(has_data, loss * jnp.sum(wb), 0.0)
            return (params, opt_state), contribution

        (params, opt_state), weighted_losses = jax.lax.scan(
            step, (params, opt_state), (heads, w_b)
        )
        epoch_loss = jnp.sum(weighted_losses) / jnp.maximum(jnp.sum(wtr), 1.0)
        return params, opt_state, epoch_loss

    def evaluate(params, series, ytgt, wval):
        nv = wval.shape[0]
        K = nv // B
        heads = jnp.arange(K)[:, None] * B + jnp.arange(G)[None, :] * L
        w_b = wval.reshape(K, G, L)

        def step(acc, batch):
            starts, wb = batch
            loss = update_loss(params, series, ytgt, starts, wb)
            wsum = jnp.sum(wb)
            # an all-padding batch yields NaN mean loss; NaN*0 is NaN,
            # so the guard (not the weight) must zero its contribution
            contribution = jnp.where(wsum > 0, loss * wsum, 0.0)
            return (acc[0] + contribution, acc[1] + wsum), None

        (total, wsum), _ = jax.lax.scan(
            step,
            (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            (heads, w_b),
        )
        return jnp.where(wsum > 0, total / wsum, jnp.nan)

    compute_dtype = jnp.dtype(spec.compute_dtype)

    def fit(params, opt_state, series, ytgt, wtr, wval, rng):
        if compute_dtype != jnp.float32:
            series, ytgt = series.astype(compute_dtype), ytgt.astype(compute_dtype)
        fit_tail = _make_fit_loop(
            config,
            train_epoch=lambda p, o, erng: train_epoch(
                p, o, series, ytgt, wtr, erng
            ),
            evaluate_val=lambda p: evaluate(p, series, ytgt, wval),
        )
        return fit_tail(params, opt_state, rng)

    return fit


@lru_cache(maxsize=None)
def _fit_program(spec: ModelSpec, config: FitConfig):
    """Jitted single-model fused fit program for (spec, config)."""
    return jax.jit(build_raw_fit_fn(spec, config))


@lru_cache(maxsize=None)
def _segmented_fit_program(spec: ModelSpec, config: FitConfig, segments: int):
    """Jitted single-model segmented fit program."""
    return jax.jit(build_raw_segmented_fit_fn(spec, config, segments))


def fit_single_segmented(
    spec: ModelSpec,
    series: np.ndarray,
    targets: np.ndarray,
    config: FitConfig,
    seed: int = 42,
    segments: int = 4,
) -> Tuple[Any, History]:
    """
    Single-model segmented (stateful-scan) LSTM fit: the estimator-path
    twin of the fleet's segmented program. Takes the RAW ``series[n, F]``
    and aligned ``targets[nw, F]`` (ops.windows.window_targets) — the
    host never materializes the ``lookback×`` window blowup the dense
    single-model path pays. Validation split is the Keras-style tail
    fraction over the window axis, exactly like :func:`fit_single` over
    materialized windows. See :func:`build_raw_segmented_fit_fn` for the
    semantics trade vs window-restart training.
    """
    if config.shuffle:
        raise ValueError("segmented LSTM training requires shuffle=False")
    series = np.asarray(series, np.float32)
    targets = np.asarray(targets, np.float32)
    nw = len(targets)
    batch_size = config.batch_size
    if batch_size % segments or nw < batch_size:
        raise ValueError(
            f"segments={segments} needs batch_size divisible by it and at "
            f"least one full batch of windows (nw={nw}, batch={batch_size})"
        )
    nv = -(-nw // batch_size) * batch_size
    n_val = int(nw * config.validation_split)
    wtr = np.zeros(nv, np.float32)
    wtr[: nw - n_val] = 1.0
    wval = np.zeros(nv, np.float32)
    if n_val:
        wval[nw - n_val : nw] = 1.0

    rng = jax.random.PRNGKey(seed)
    rng, init_rng = jax.random.split(rng)
    params = init_fn_for(spec)(init_rng, spec)
    opt_state = spec.optimizer.to_optax().init(params)

    fit = _segmented_fit_program(spec, config, segments)
    with telemetry.program_span(
        "fit_single_segmented",
        (spec, config, segments, series.shape, targets.shape),
        shape=str(tuple(series.shape)),
        spec=type(spec).__name__,
    ):
        params, _, losses, val_losses, epochs_ran = fit(
            params, opt_state, series, targets, wtr, wval, rng
        )
        # one coalesced d2h readback — per-element float() would pay the
        # fixed per-transfer latency once PER EPOCH on tunneled
        # accelerators. Inside the span: the readback waits on the
        # program, so the span times real device work, not dispatch.
        losses, val_losses, epochs_ran = jax.device_get(
            (losses, val_losses, epochs_ran)
        )
    epochs_ran = int(epochs_ran)
    history = {"loss": [float(l) for l in losses[:epochs_ran]]}
    if n_val:
        history["val_loss"] = [float(l) for l in val_losses[:epochs_ran]]
    return params, History(
        history=history,
        params={
            "epochs": config.epochs,
            # train-only, like fit_single over materialized windows
            "steps": (nw - n_val + batch_size - 1) // batch_size,
            "verbose": 0,
            "metrics": list(history),
            "segmented": segments,
        },
        epoch=list(range(epochs_ran)),
    )


def fit_single(
    spec: ModelSpec,
    X: np.ndarray,
    y: np.ndarray,
    config: FitConfig,
    seed: int = 42,
    host_callbacks: Optional[List[Callback]] = None,
    initial_params=None,
) -> Tuple[Any, History]:
    """
    Train one model described by ``spec`` on host arrays ``(X, y)``.

    Returns (params pytree, History). ``host_callbacks`` forces the per-epoch
    host loop; otherwise the whole fit is a single device program.
    """
    n = X.shape[0]
    n_val = int(n * config.validation_split)
    Xtr_raw, ytr_raw = X[: n - n_val], y[: n - n_val]
    Xval_raw, yval_raw = X[n - n_val :], y[n - n_val :]

    batch_size = min(config.batch_size, max(1, len(Xtr_raw)))
    if batch_size != config.batch_size:
        config = FitConfig(
            epochs=config.epochs,
            batch_size=batch_size,
            validation_split=config.validation_split,
            shuffle=config.shuffle,
            early_stopping=config.early_stopping,
        )

    Xtr, ytr, wtr, _ = _pad_to_batches(
        np.asarray(Xtr_raw, np.float32), np.asarray(ytr_raw, np.float32), batch_size
    )
    Xval = np.asarray(Xval_raw, np.float32)
    yval = np.asarray(yval_raw, np.float32)
    wval = np.ones(len(Xval), np.float32)

    rng = jax.random.PRNGKey(seed)
    rng, init_rng = jax.random.split(rng)
    params = (
        initial_params
        if initial_params is not None
        else init_fn_for(spec)(init_rng, spec)
    )
    tx = spec.optimizer.to_optax()
    opt_state = tx.init(params)

    if host_callbacks:
        return _fit_host_loop(
            spec, config, params, opt_state, Xtr, ytr, wtr, Xval, yval, wval,
            rng, host_callbacks,
        )

    fit = _fit_program(spec, config)
    with telemetry.program_span(
        "fit_single",
        (spec, config, Xtr.shape, Xval.shape),
        shape=str(tuple(Xtr.shape)),
        spec=type(spec).__name__,
    ):
        params, _, losses, val_losses, epochs_ran = fit(
            params, opt_state, Xtr, ytr, wtr, Xval, yval, wval, rng
        )
        # one coalesced d2h readback — per-element float() would pay the
        # fixed per-transfer latency once PER EPOCH on tunneled
        # accelerators. Inside the span: the readback waits on the
        # program, so the span times real device work, not dispatch.
        losses, val_losses, epochs_ran = jax.device_get(
            (losses, val_losses, epochs_ran)
        )
    epochs_ran = int(epochs_ran)
    history = {"loss": [float(l) for l in losses[:epochs_ran]]}
    if n_val:
        history["val_loss"] = [float(l) for l in val_losses[:epochs_ran]]
    return params, History(
        history=history,
        params={
            "epochs": config.epochs,
            "steps": len(Xtr) // batch_size,
            "verbose": 0,
            "metrics": list(history),
        },
        epoch=list(range(epochs_ran)),
    )


def _fit_host_loop(
    spec, config, params, opt_state, Xtr, ytr, wtr, Xval, yval, wval, rng, callbacks
):
    """Per-epoch host loop for custom callbacks: one jitted epoch at a
    time. Callbacks may stop training (on_epoch_end -> True) or request a
    learning-rate change (``consume_lr_request`` protocol —
    ReduceLROnPlateau); an LR change swaps in the one-epoch program
    compiled for the new rate (lru-cached per rate) while Adam's moment
    state carries over unchanged."""
    from dataclasses import replace as dc_replace

    single_epoch_config = FitConfig(
        epochs=1,
        batch_size=config.batch_size,
        validation_split=0.0,
        shuffle=config.shuffle,
        early_stopping=None,
    )
    evaluate = _eval_fn(spec)
    empty = np.zeros((0,) + Xtr.shape[1:], np.float32)
    empty_y = np.zeros((0,) + ytr.shape[1:], np.float32)
    empty_w = np.zeros((0,), np.float32)

    history: Dict[str, List[float]] = {"loss": []}
    if len(Xval):
        history["val_loss"] = []
    for cb in callbacks:
        cb.on_train_begin()
    epochs_ran = 0
    current_spec = spec
    for epoch in range(config.epochs):
        fit_one = _fit_program(current_spec, single_epoch_config)
        rng, erng = jax.random.split(rng)
        params, opt_state, losses, _, _ = fit_one(
            params, opt_state, Xtr, ytr, wtr, empty, empty_y, empty_w, erng
        )
        logs = {
            "loss": float(losses[0]),
            "lr": current_spec.optimizer.learning_rate,
        }
        if len(Xval):
            logs["val_loss"] = float(evaluate(params, Xval, yval, wval))
            history["val_loss"].append(logs["val_loss"])
        history["loss"].append(logs["loss"])
        epochs_ran += 1
        # run every callback (Keras semantics), then stop/LR decisions
        stop_requests = [cb.on_epoch_end(epoch, logs) for cb in callbacks]
        new_lr = None
        for cb in callbacks:
            request = getattr(cb, "consume_lr_request", None)
            if callable(request):
                requested = request()
                if requested is not None:
                    new_lr = requested
        if new_lr is not None and new_lr != current_spec.optimizer.learning_rate:
            logger.info("Host loop: learning rate -> %g (epoch %d)", new_lr, epoch)
            current_spec = dc_replace(
                current_spec,
                optimizer=dc_replace(
                    current_spec.optimizer, learning_rate=float(new_lr)
                ),
            )
        if any(stop_requests):
            break
    return params, History(
        history=history,
        params={
            "epochs": config.epochs,
            "steps": len(Xtr) // config.batch_size,
            "verbose": 0,
            "metrics": list(history),
        },
        epoch=list(range(epochs_ran)),
    )
