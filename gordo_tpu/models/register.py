"""
Model-architecture factory registry.

Reference parity: gordo/machine/model/register.py:10-75 — a class-level dict
``{model_type: {kind: builder_fn}}`` filled by the ``register_model_builder``
decorator; builders must accept ``n_features`` as their first argument.

In gordo-tpu a builder returns a static :mod:`gordo_tpu.models.spec`
ModelSpec (not a live Keras model): specs are hashable, which is what lets
the fleet trainer bucket thousands of machines into a handful of XLA
compilations.
"""

import inspect
from typing import Callable, Dict


class register_model_builder:
    """
    Decorator registering an architecture factory for a model type.

    Example
    -------
    >>> @register_model_builder(type="DemoModel")
    ... def my_arch(n_features: int, **kwargs):
    ...     return None
    >>> "my_arch" in register_model_builder.factories["DemoModel"]
    True
    """

    factories: Dict[str, Dict[str, Callable]] = {}

    def __init__(self, type: str):
        self.type = type

    def __call__(self, build_fn: Callable) -> Callable:
        self._validate_func(build_fn)
        self.factories.setdefault(self.type, {})[build_fn.__name__] = build_fn
        return build_fn

    @staticmethod
    def _validate_func(func: Callable):
        params = list(inspect.signature(func).parameters)
        if not params or params[0] != "n_features":
            raise ValueError(
                f"Model builder function {func.__name__!r} must take "
                f"'n_features' as its first parameter, got {params[:1]}"
            )
