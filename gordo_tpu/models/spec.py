"""
Static model specifications — the TPU-first replacement for "a compiled Keras
model object".

Where the reference's factories return a live ``keras.Sequential``
(gordo/machine/model/factories/*.py), gordo-tpu factories return a frozen
**ModelSpec**. The spec is:

- *static*: pure data (tuples, floats, strings) → safely closed over by
  ``jit``; no retracing surprises;
- *hashable*: the fleet trainer groups thousands of machines by spec so each
  distinct architecture compiles exactly once (SURVEY.md §7 step 7,
  "compilation buckets");
- *declarative*: the training engine (models/training.py) turns a spec into
  init/forward/loss functions.
"""

from dataclasses import dataclass, field, fields
from typing import Any, Dict, Optional, Tuple, Union


def _freeze_kwargs(kwargs: Optional[Dict[str, Any]]) -> Tuple[Tuple[str, Any], ...]:
    if not kwargs:
        return ()
    return tuple(sorted(kwargs.items()))


@dataclass(frozen=True)
class OptimizerSpec:
    """
    Optimizer configuration. Defaults mirror Keras' Adam
    (learning_rate=1e-3, beta_1=0.9, beta_2=0.999, epsilon=1e-7) so that
    configs written for the reference train equivalently.
    """

    name: str = "Adam"
    learning_rate: float = 0.001
    kwargs: Tuple[Tuple[str, Any], ...] = ()

    @classmethod
    def from_config(
        cls,
        optimizer: Union[str, "OptimizerSpec", None] = "Adam",
        optimizer_kwargs: Optional[Dict[str, Any]] = None,
    ) -> "OptimizerSpec":
        if isinstance(optimizer, OptimizerSpec):
            return optimizer
        optimizer_kwargs = dict(optimizer_kwargs or {})
        lr = optimizer_kwargs.pop(
            "learning_rate", optimizer_kwargs.pop("lr", 0.001)
        )
        return cls(
            name=optimizer or "Adam",
            learning_rate=float(lr),
            kwargs=_freeze_kwargs(optimizer_kwargs),
        )

    def to_optax(self):
        import optax

        kwargs = dict(self.kwargs)
        name = self.name.lower()
        if name == "adam":
            return optax.adam(
                learning_rate=self.learning_rate,
                b1=kwargs.get("beta_1", 0.9),
                b2=kwargs.get("beta_2", 0.999),
                eps=kwargs.get("epsilon", 1e-7),
            )
        if name == "adamw":
            return optax.adamw(
                learning_rate=self.learning_rate,
                b1=kwargs.get("beta_1", 0.9),
                b2=kwargs.get("beta_2", 0.999),
                eps=kwargs.get("epsilon", 1e-7),
                weight_decay=kwargs.get("weight_decay", 1e-4),
            )
        if name == "sgd":
            return optax.sgd(
                learning_rate=self.learning_rate,
                momentum=kwargs.get("momentum", 0.0),
                nesterov=kwargs.get("nesterov", False),
            )
        if name == "rmsprop":
            return optax.rmsprop(
                learning_rate=self.learning_rate,
                decay=kwargs.get("rho", 0.9),
                eps=kwargs.get("epsilon", 1e-7),
                momentum=kwargs.get("momentum", 0.0),
            )
        raise ValueError(f"Unsupported optimizer {self.name!r}")


class ModelSpec:
    """Marker base for architecture specs; concrete specs are frozen dataclasses."""

    def to_dict(self) -> dict:
        out: Dict[str, Any] = {"spec_type": type(self).__name__}
        for f in fields(self):  # type: ignore[arg-type]
            value = getattr(self, f.name)
            if isinstance(value, OptimizerSpec):
                value = {
                    "name": value.name,
                    "learning_rate": value.learning_rate,
                    **dict(value.kwargs),
                }
            out[f.name] = value
        return out


@dataclass(frozen=True)
class FeedForwardSpec(ModelSpec):
    """
    A feedforward (dense) autoencoder/regressor: ``dims[i]`` hidden units
    with ``activations[i]``, then an output layer of ``n_features_out`` with
    ``out_activation``. ``l1_activity[i]`` adds an L1 activity penalty on
    layer ``i``'s output to the loss (the reference puts l1(1e-4) on all
    non-first encoder layers — factories/feedforward_autoencoder.py:75-84).
    """

    n_features: int
    n_features_out: int
    dims: Tuple[int, ...]
    activations: Tuple[str, ...]
    out_activation: str = "linear"
    l1_activity: Tuple[float, ...] = ()
    optimizer: OptimizerSpec = field(default_factory=OptimizerSpec)
    loss: str = "mse"
    compute_dtype: str = "float32"
    #: serving precision from the config surface ("" inherits the
    #: GORDO_TPU_SERVE_PRECISION knob): "f32", "bf16" or "int8" — read
    #: only by the serve engine's precision ladder, never by training.
    #: A plain class-level default keeps pre-precision pickled specs
    #: loading (attribute access falls back to the class default).
    precision: str = ""

    def __post_init__(self):
        if len(self.dims) != len(self.activations):
            raise ValueError(
                f"dims ({len(self.dims)}) and activations "
                f"({len(self.activations)}) must have equal length"
            )
        if self.l1_activity and len(self.l1_activity) != len(self.dims):
            raise ValueError("l1_activity must match dims length when given")


@dataclass(frozen=True)
class LSTMSpec(ModelSpec):
    """
    A stacked LSTM many-to-one network over a ``lookback_window`` of
    timesteps: every LSTM layer returns sequences except the last, followed
    by a Dense output head (reference architecture:
    factories/lstm_autoencoder.py:78-97).
    """

    n_features: int
    n_features_out: int
    lookback_window: int
    dims: Tuple[int, ...]
    activations: Tuple[str, ...]
    out_activation: str = "linear"
    optimizer: OptimizerSpec = field(default_factory=OptimizerSpec)
    loss: str = "mse"
    compute_dtype: str = "float32"
    #: serving precision from the config surface (see FeedForwardSpec;
    #: LSTMs serve unbatched today, so this is carried, not yet used)
    precision: str = ""

    def __post_init__(self):
        if len(self.dims) != len(self.activations):
            raise ValueError(
                f"dims ({len(self.dims)}) and activations "
                f"({len(self.activations)}) must have equal length"
            )
        if not self.dims:
            raise ValueError("LSTM spec needs at least one layer")


# ---------------------------------------------------------------------------
# Raw layer-list definitions (the KerasRawModelRegressor analog): config
# files can describe a Sequential stack of Dense layers which compiles down
# to a FeedForwardSpec.
# ---------------------------------------------------------------------------


@dataclass
class Dense:
    units: int
    activation: str = "linear"
    l1_activity: float = 0.0
    # Accepted for Keras-config compatibility; the input dim is inferred at
    # fit time from the data.
    input_shape: Optional[Tuple[int, ...]] = None
    input_dim: Optional[int] = None

    def get_params(self, deep: bool = False) -> dict:
        return {
            "units": self.units,
            "activation": self.activation,
            "l1_activity": self.l1_activity,
        }


class Sequential:
    """
    Layer-list container recognized by the serializer (the analog of
    ``tensorflow.keras.Sequential`` in raw-spec configs —
    serializer/from_definition.py special-cases it via
    ``_serializer_layers_container``).
    """

    _serializer_layers_container = True

    def __init__(self, layers, optimizer="Adam", optimizer_kwargs=None, loss="mse"):
        self.layers = list(layers)
        self.optimizer = optimizer
        self.optimizer_kwargs = optimizer_kwargs or {}
        self.loss = loss

    def get_params(self, deep: bool = False) -> dict:
        return {
            "layers": self.layers,
            "optimizer": self.optimizer,
            "optimizer_kwargs": self.optimizer_kwargs,
            "loss": self.loss,
        }

    def compile_spec(self, n_features: int) -> FeedForwardSpec:
        """Compile the layer list into a FeedForwardSpec for ``n_features``
        inputs; the final Dense layer becomes the output head."""
        dense_layers = [layer for layer in self.layers if isinstance(layer, Dense)]
        if len(dense_layers) != len(self.layers):
            raise ValueError(
                "Only Dense layers are supported in raw Sequential specs; got "
                f"{[type(l).__name__ for l in self.layers]}"
            )
        if not dense_layers:
            raise ValueError("Sequential spec needs at least one Dense layer")
        hidden, head = dense_layers[:-1], dense_layers[-1]
        return FeedForwardSpec(
            n_features=n_features,
            n_features_out=head.units,
            dims=tuple(layer.units for layer in hidden),
            activations=tuple(layer.activation for layer in hidden),
            out_activation=head.activation,
            l1_activity=tuple(layer.l1_activity for layer in hidden)
            if any(layer.l1_activity for layer in hidden)
            else (),
            optimizer=OptimizerSpec.from_config(self.optimizer, self.optimizer_kwargs),
            loss=self.loss,
        )
