"""
The GordoBase contract every model in the framework honors.

Reference parity: gordo/machine/model/base.py:10-35 — the builder, server
and serializer only rely on this surface plus sklearn's fit/predict.
"""

import abc
from typing import Optional, Union

import numpy as np
import pandas as pd


class GordoBase(abc.ABC):
    @abc.abstractmethod
    def __init__(self, **kwargs):
        ...

    @abc.abstractmethod
    def get_params(self, deep: bool = False) -> dict:
        """Parameters this model was constructed with."""

    @abc.abstractmethod
    def score(
        self,
        X: Union[np.ndarray, pd.DataFrame],
        y: Union[np.ndarray, pd.DataFrame],
        sample_weight: Optional[np.ndarray] = None,
    ) -> float:
        """Score the model; channels into builder CV metrics."""

    @abc.abstractmethod
    def get_metadata(self) -> dict:
        """Any model-specific metadata (fit history, thresholds, ...)."""
