"""
±inf imputation transformer.

Reference parity: gordo/machine/model/transformers/imputer.py:12-127 — fill
positive/negative infinities per feature, either with the train-time
per-column max/min nudged by ``delta`` ("minmax" strategy) or with the
dtype's extreme values ("extremes").
"""

from typing import Optional

import numpy as np
import pandas as pd
from sklearn.base import BaseEstimator, TransformerMixin


class InfImputer(BaseEstimator, TransformerMixin):
    def __init__(
        self,
        inf_fill_value: Optional[float] = None,
        neg_inf_fill_value: Optional[float] = None,
        strategy: str = "minmax",
        delta: float = 2.0,
    ):
        if strategy not in ("minmax", "extremes"):
            raise ValueError(f"Unknown strategy {strategy!r}")
        self.inf_fill_value = inf_fill_value
        self.neg_inf_fill_value = neg_inf_fill_value
        self.strategy = strategy
        self.delta = delta

    def fit(self, X, y=None):
        X = np.asarray(X.values if isinstance(X, pd.DataFrame) else X)
        if self.strategy == "extremes":
            info = np.finfo(X.dtype) if np.issubdtype(X.dtype, np.floating) else np.finfo(np.float64)
            self._fill_values = np.full(X.shape[1], info.max)
            self._neg_fill_values = np.full(X.shape[1], info.min)
        else:
            masked = np.ma.masked_invalid(X)
            self._fill_values = masked.max(axis=0).filled(0.0) + self.delta
            self._neg_fill_values = masked.min(axis=0).filled(0.0) - self.delta
        return self

    def transform(self, X, y=None):
        values = np.array(X.values if isinstance(X, pd.DataFrame) else X, copy=True)
        for col in range(values.shape[1]):
            pos = self.inf_fill_value
            neg = self.neg_inf_fill_value
            if pos is None:
                pos = self._fill_values[col]
            if neg is None:
                neg = self._neg_fill_values[col]
            column = values[:, col]
            column[np.isposinf(column)] = pos
            column[np.isneginf(column)] = neg
        if isinstance(X, pd.DataFrame):
            return pd.DataFrame(values, columns=X.columns, index=X.index)
        return values
