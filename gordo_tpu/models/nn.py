"""
Network init/forward as pure JAX functions over explicit param pytrees.

Rather than translating Keras ``Sequential`` objects, each
:mod:`gordo_tpu.models.spec` ModelSpec maps to an ``(init, forward)`` pair of
pure functions. Everything is vmap/shard_map-friendly: the fleet trainer
vmaps ``init`` over per-model RNG keys and ``forward`` over stacked param
pytrees with zero code changes here.

Initialization parity with Keras (so reference configs converge the same
way): Dense kernels glorot_uniform + zero bias; LSTM input kernels
glorot_uniform, recurrent kernels orthogonal, zero bias with unit forget
gate bias.

Dtype contract (``spec.compute_dtype``): mixed precision in the standard
sense — parameters and optimizer moments always live in float32 (Adam
updates are ~1e-4 of the param magnitude, far below bf16's 8-bit
mantissa ULP; storing params in bf16 silently drops most updates and
stalls training — measured: EV −0.02 vs 0.70 on the bf16 test fixture),
while matmuls/activations cast to the compute dtype per use and the
OUTPUT, losses and thresholds are always float32.
"""

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..ops.activations import resolve_activation
from .spec import FeedForwardSpec, LSTMSpec

Params = Dict[str, Dict[str, jnp.ndarray]]

_glorot = jax.nn.initializers.glorot_uniform()
_orthogonal = jax.nn.initializers.orthogonal()


def _lstm_unroll() -> int:
    """Unroll factor for the recurrent scan (GORDO_TPU_LSTM_UNROLL,
    default 4): the LSTM fleet is per-scan-step overhead-bound (see the
    roofline in docs/architecture.md), so fusing several timesteps into
    one scan iteration amortizes the per-step cost without changing the
    math."""
    from ..utils.env import env_int

    return max(1, env_int("GORDO_TPU_LSTM_UNROLL", 4))


def init_feedforward(rng: jax.Array, spec: FeedForwardSpec) -> Params:
    """Initialize params for a FeedForwardSpec (always float32 — see the
    module docstring's dtype contract)."""
    dtype = jnp.float32
    params: Params = {}
    in_dim = spec.n_features
    for i, units in enumerate(spec.dims):
        rng, key = jax.random.split(rng)
        params[f"dense_{i}"] = {
            "W": _glorot(key, (in_dim, units), dtype),
            "b": jnp.zeros((units,), dtype),
        }
        in_dim = units
    rng, key = jax.random.split(rng)
    params["out"] = {
        "W": _glorot(key, (in_dim, spec.n_features_out), dtype),
        "b": jnp.zeros((spec.n_features_out,), dtype),
    }
    return params


def forward_feedforward(
    spec: FeedForwardSpec, params: Params, x: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """
    Forward pass on ``x`` of shape ``[batch, n_features]``.

    Returns ``(output, activity_penalty)`` where the penalty is the summed L1
    activity regularization (zero when the spec has none) to be added to the
    training loss. XLA fuses the elementwise activations into the matmuls, so
    the whole stack is a handful of MXU ops.

    Dtype contract: compute runs in ``spec.compute_dtype`` (bf16 halves
    the HBM traffic the tiny-model regime is bound by — see
    docs/architecture.md roofline); the OUTPUT and the penalty are always
    float32, so losses, thresholds and the sklearn-facing predict keep
    full precision regardless of compute dtype.
    """
    dtype = jnp.dtype(spec.compute_dtype)

    def cast(leaf) -> jnp.ndarray:
        return leaf.astype(dtype) if leaf.dtype != dtype else leaf

    penalty = jnp.zeros((), jnp.float32)
    h = cast(x)
    for i in range(len(spec.dims)):
        layer = params[f"dense_{i}"]
        h = resolve_activation(spec.activations[i])(
            h @ cast(layer["W"]) + cast(layer["b"])
        )
        if spec.l1_activity and spec.l1_activity[i]:
            penalty = penalty + spec.l1_activity[i] * jnp.sum(
                jnp.abs(h), dtype=jnp.float32
            )
    out = h @ cast(params["out"]["W"]) + cast(params["out"]["b"])
    return resolve_activation(spec.out_activation)(out).astype(jnp.float32), penalty


def init_lstm(rng: jax.Array, spec: LSTMSpec) -> Params:
    """Initialize params for an LSTMSpec (stacked LSTM + Dense head);
    always float32 like init_feedforward."""
    dtype = jnp.float32
    params: Params = {}
    in_dim = spec.n_features
    for i, units in enumerate(spec.dims):
        rng, kx, kh = jax.random.split(rng, 3)
        bias = jnp.zeros((4 * units,), dtype)
        # Unit forget-gate bias (Keras unit_forget_bias=True); gate order is
        # (input, forget, cell, output).
        bias = bias.at[units : 2 * units].set(1.0)
        params[f"lstm_{i}"] = {
            "Wx": _glorot(kx, (in_dim, 4 * units), dtype),
            "Wh": _orthogonal(kh, (units, 4 * units), dtype),
            "b": bias,
        }
        in_dim = units
    rng, key = jax.random.split(rng)
    params["out"] = {
        "W": _glorot(key, (in_dim, spec.n_features_out), dtype),
        "b": jnp.zeros((spec.n_features_out,), dtype),
    }
    return params


def _lstm_layer(
    layer: Dict[str, jnp.ndarray], x_seq: jnp.ndarray, activation: str
) -> jnp.ndarray:
    """
    Run one LSTM layer over ``x_seq`` of shape ``[time, batch, features]``,
    returning the full hidden sequence ``[time, batch, units]``.

    The configured ``activation`` applies to both the candidate cell update
    and the output transform (Keras LSTM semantics); gates use sigmoid.
    Compute dtype follows ``x_seq`` (the caller casts); f32 master params
    are cast at use.
    """
    act = resolve_activation(activation)
    dtype = x_seq.dtype
    Wx, Wh = layer["Wx"].astype(dtype), layer["Wh"].astype(dtype)
    b = layer["b"].astype(dtype)
    units = layer["Wh"].shape[0]
    batch = x_seq.shape[1]
    h0 = jnp.zeros((batch, units), x_seq.dtype)
    c0 = jnp.zeros((batch, units), x_seq.dtype)

    # Hoist the input projection out of the scan: one big [T*B, F] @ [F, 4H]
    # matmul keeps the MXU busy instead of T small ones.
    x_proj = x_seq @ Wx + b

    def step(carry, xp_t):
        h, c = carry
        gates = xp_t + h @ Wh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * act(g)
        h_new = o * act(c_new)
        return (h_new, c_new), h_new

    _, h_seq = jax.lax.scan(step, (h0, c0), x_proj, unroll=_lstm_unroll())
    return h_seq


def forward_lstm(
    spec: LSTMSpec, params: Params, x: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """
    Forward pass on windows ``x`` of shape ``[batch, lookback, n_features]``
    → ``[batch, n_features_out]`` (many-to-one: last timestep's hidden state
    feeds the Dense head). Returns ``(output, activity_penalty=0)``.
    Same dtype contract as :func:`forward_feedforward`: compute in
    ``spec.compute_dtype``, float32 out.
    """
    dtype = jnp.dtype(spec.compute_dtype)
    if x.dtype != dtype:
        x = x.astype(dtype)
    h_seq = jnp.transpose(x, (1, 0, 2))  # [time, batch, features] for scan
    for i in range(len(spec.dims)):
        h_seq = _lstm_layer(params[f"lstm_{i}"], h_seq, spec.activations[i])
    last_h = h_seq[-1]
    out = last_h @ params["out"]["W"].astype(dtype) + params["out"]["b"].astype(
        dtype
    )
    return (
        resolve_activation(spec.out_activation)(out).astype(jnp.float32),
        jnp.zeros((), jnp.float32),
    )


def forward_lstm_sequence(
    spec: LSTMSpec, params: Params, x_seq: jnp.ndarray
) -> jnp.ndarray:
    """
    Run the stacked LSTM over ``x_seq`` of shape ``[time, batch,
    n_features]`` and emit the Dense-head output at EVERY timestep:
    ``[time, batch, n_features_out]``.

    This is the segmented-training forward (training.py
    build_raw_segmented_fit_fn): one recurrence pass over a span of the
    series yields the many-to-one output of every window ending inside
    the span, instead of re-running the first ``lookback-1`` steps of
    each stride-1 window from scratch. The output at time ``t`` equals
    :func:`forward_lstm` on a window ending at ``t`` whose hidden state
    was warmed by the span's earlier steps (identical when the span
    starts exactly ``lookback`` steps before ``t``). Same dtype
    contract: compute in ``spec.compute_dtype``, float32 out.
    """
    dtype = jnp.dtype(spec.compute_dtype)
    if x_seq.dtype != dtype:
        x_seq = x_seq.astype(dtype)
    h_seq = x_seq
    for i in range(len(spec.dims)):
        h_seq = _lstm_layer(params[f"lstm_{i}"], h_seq, spec.activations[i])
    out = h_seq @ params["out"]["W"].astype(dtype) + params["out"]["b"].astype(
        dtype
    )
    return resolve_activation(spec.out_activation)(out).astype(jnp.float32)


def init_fn_for(spec) -> "object":
    if isinstance(spec, FeedForwardSpec):
        return init_feedforward
    if isinstance(spec, LSTMSpec):
        return init_lstm
    raise TypeError(f"No init function for spec type {type(spec).__name__}")


def forward_fn_for(spec) -> "object":
    if isinstance(spec, FeedForwardSpec):
        return forward_feedforward
    if isinstance(spec, LSTMSpec):
        return forward_lstm
    raise TypeError(f"No forward function for spec type {type(spec).__name__}")
