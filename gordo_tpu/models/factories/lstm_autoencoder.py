"""
LSTM architecture factories (autoencoder + forecast heads share them).

Same three registered kinds as the reference
(gordo/machine/model/factories/lstm_autoencoder.py), each registered for
both LSTM estimator types (its double-decorator at lines 15-16). Returns a
static :class:`~gordo_tpu.models.spec.LSTMSpec`: stacked LSTM layers (all
return sequences except the last) feeding a Dense output head.
"""

from typing import Any, Dict, Optional, Tuple, Union

from ..register import register_model_builder
from ..spec import LSTMSpec, OptimizerSpec
from .utils import check_dim_func_len, hourglass_calc_dims


@register_model_builder(type="JaxLSTMAutoEncoder")
@register_model_builder(type="JaxLSTMForecast")
def lstm_model(
    n_features: int,
    n_features_out: Optional[int] = None,
    lookback_window: int = 1,
    encoding_dim: Tuple[int, ...] = (256, 128, 64),
    encoding_func: Tuple[str, ...] = ("tanh", "tanh", "tanh"),
    decoding_dim: Tuple[int, ...] = (64, 128, 256),
    decoding_func: Tuple[str, ...] = ("tanh", "tanh", "tanh"),
    out_func: str = "linear",
    optimizer: Union[str, OptimizerSpec] = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    compile_kwargs: Optional[Dict[str, Any]] = None,
    compute_dtype: str = "float32",
    precision: str = "",
    **kwargs,
) -> LSTMSpec:
    """Fully-specified stacked-LSTM network over a lookback window.
    ``compute_dtype="bfloat16"`` runs the recurrence in bf16 (losses and
    outputs stay float32 — models/nn.py dtype contract). ``precision``
    declares the serving precision (carried on the spec; LSTMs serve
    unbatched today)."""
    n_features_out = n_features_out or n_features
    check_dim_func_len("encoding", encoding_dim, encoding_func)
    check_dim_func_len("decoding", decoding_dim, decoding_func)
    compile_kwargs = compile_kwargs or {}
    return LSTMSpec(
        n_features=n_features,
        n_features_out=n_features_out,
        lookback_window=lookback_window,
        dims=tuple(encoding_dim) + tuple(decoding_dim),
        activations=tuple(encoding_func) + tuple(decoding_func),
        out_activation=out_func,
        optimizer=OptimizerSpec.from_config(optimizer, optimizer_kwargs),
        loss=compile_kwargs.get("loss", "mse"),
        compute_dtype=compute_dtype,
        precision=precision,
    )


@register_model_builder(type="JaxLSTMAutoEncoder")
@register_model_builder(type="JaxLSTMForecast")
def lstm_symmetric(
    n_features: int,
    n_features_out: Optional[int] = None,
    lookback_window: int = 1,
    dims: Tuple[int, ...] = (256, 128, 64),
    funcs: Tuple[str, ...] = ("tanh", "tanh", "tanh"),
    out_func: str = "linear",
    optimizer: Union[str, OptimizerSpec] = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    compile_kwargs: Optional[Dict[str, Any]] = None,
    **kwargs,
) -> LSTMSpec:
    """Symmetric stacked LSTM: ``dims`` encoding, reversed decoding."""
    if len(dims) == 0:
        raise ValueError("Parameter dims must have len > 0")
    return lstm_model(
        n_features,
        n_features_out,
        lookback_window=lookback_window,
        encoding_dim=tuple(dims),
        decoding_dim=tuple(dims)[::-1],
        encoding_func=tuple(funcs),
        decoding_func=tuple(funcs)[::-1],
        out_func=out_func,
        optimizer=optimizer,
        optimizer_kwargs=optimizer_kwargs,
        compile_kwargs=compile_kwargs,
        **kwargs,
    )


@register_model_builder(type="JaxLSTMAutoEncoder")
@register_model_builder(type="JaxLSTMForecast")
def lstm_hourglass(
    n_features: int,
    n_features_out: Optional[int] = None,
    lookback_window: int = 1,
    encoding_layers: int = 3,
    compression_factor: float = 0.5,
    func: str = "tanh",
    out_func: str = "linear",
    optimizer: Union[str, OptimizerSpec] = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    compile_kwargs: Optional[Dict[str, Any]] = None,
    **kwargs,
) -> LSTMSpec:
    """
    Hourglass stacked LSTM.

    >>> spec = lstm_hourglass(10)
    >>> spec.dims
    (8, 7, 5, 5, 7, 8)
    >>> lstm_hourglass(10, compression_factor=0.2).dims
    (7, 5, 2, 2, 5, 7)
    """
    dims = hourglass_calc_dims(compression_factor, encoding_layers, n_features)
    return lstm_symmetric(
        n_features,
        n_features_out,
        lookback_window=lookback_window,
        dims=dims,
        funcs=tuple([func] * len(dims)),
        out_func=out_func,
        optimizer=optimizer,
        optimizer_kwargs=optimizer_kwargs,
        compile_kwargs=compile_kwargs,
        **kwargs,
    )
