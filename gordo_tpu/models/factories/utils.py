"""Layer-geometry helpers (reference: gordo/machine/model/factories/utils.py)."""

import math
from typing import Tuple


def hourglass_calc_dims(
    compression_factor: float, encoding_layers: int, n_features: int
) -> Tuple[int, ...]:
    """
    Encoder layer sizes tapering linearly from ``n_features`` down to
    ``ceil(compression_factor * n_features)`` over ``encoding_layers`` steps
    (decoder mirrors them).

    >>> hourglass_calc_dims(0.5, 3, 10)
    (8, 7, 5)
    >>> hourglass_calc_dims(0.2, 3, 10)
    (7, 5, 2)
    >>> hourglass_calc_dims(0.5, 1, 10)
    (5,)
    >>> hourglass_calc_dims(0.5, 3, 5)
    (4, 4, 3)
    """
    if not 0 <= compression_factor <= 1:
        raise ValueError("compression_factor must satisfy 0 <= cf <= 1")
    if encoding_layers < 1:
        raise ValueError("encoding_layers must be >= 1")
    smallest = max(min(math.ceil(compression_factor * n_features), n_features), 1)
    slope = (n_features - smallest) / encoding_layers
    return tuple(
        round(n_features - step * slope) for step in range(1, encoding_layers + 1)
    )


def check_dim_func_len(prefix: str, dims: Tuple[int, ...], funcs: Tuple[str, ...]):
    """Dims and activation-function tuples must pair up one-to-one."""
    if len(dims) != len(funcs):
        raise ValueError(
            f"Length of {prefix}_dim ({len(dims)}) and {prefix}_func "
            f"({len(funcs)}) must be equal"
        )
