"""
Feedforward autoencoder architecture factories.

Same three registered kinds and geometry as the reference
(gordo/machine/model/factories/feedforward_autoencoder.py): explicit dims,
symmetric, and hourglass. Each returns a static
:class:`~gordo_tpu.models.spec.FeedForwardSpec` instead of a compiled Keras
model; the reference's l1(1e-4) activity regularizer on non-first encoder
layers (its lines 75-84) becomes the spec's ``l1_activity`` tuple.
"""

from typing import Any, Dict, Optional, Tuple, Union

from ..register import register_model_builder
from ..spec import FeedForwardSpec, OptimizerSpec
from .utils import check_dim_func_len, hourglass_calc_dims

L1_ACTIVITY_DEFAULT = 1e-4


@register_model_builder(type="JaxAutoEncoder")
def feedforward_model(
    n_features: int,
    n_features_out: Optional[int] = None,
    encoding_dim: Tuple[int, ...] = (256, 128, 64),
    encoding_func: Tuple[str, ...] = ("tanh", "tanh", "tanh"),
    decoding_dim: Tuple[int, ...] = (64, 128, 256),
    decoding_func: Tuple[str, ...] = ("tanh", "tanh", "tanh"),
    out_func: str = "linear",
    optimizer: Union[str, OptimizerSpec] = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    compile_kwargs: Optional[Dict[str, Any]] = None,
    compute_dtype: str = "float32",
    precision: str = "",
    **kwargs,
) -> FeedForwardSpec:
    """
    Fully-specified feedforward AE: encoder layers then decoder layers, with
    an L1 activity penalty on every encoder layer except the first.
    ``compute_dtype="bfloat16"`` runs params + activations in bf16 (losses
    and outputs stay float32 — models/nn.py dtype contract).
    ``precision`` declares the SERVING precision ("f32"/"bf16"/"int8";
    "" inherits ``GORDO_TPU_SERVE_PRECISION``) — training ignores it,
    the serve engine's precision ladder reads it per spec.
    """
    n_features_out = n_features_out or n_features
    check_dim_func_len("encoding", encoding_dim, encoding_func)
    check_dim_func_len("decoding", decoding_dim, decoding_func)

    dims = tuple(encoding_dim) + tuple(decoding_dim)
    activations = tuple(encoding_func) + tuple(decoding_func)
    l1 = tuple(
        L1_ACTIVITY_DEFAULT if 0 < i < len(encoding_dim) else 0.0
        for i in range(len(dims))
    )
    compile_kwargs = compile_kwargs or {}
    return FeedForwardSpec(
        n_features=n_features,
        n_features_out=n_features_out,
        dims=dims,
        activations=activations,
        out_activation=out_func,
        l1_activity=l1 if any(l1) else (),
        optimizer=OptimizerSpec.from_config(optimizer, optimizer_kwargs),
        loss=compile_kwargs.get("loss", "mse"),
        compute_dtype=compute_dtype,
        precision=precision,
    )


@register_model_builder(type="JaxAutoEncoder")
def feedforward_symmetric(
    n_features: int,
    n_features_out: Optional[int] = None,
    dims: Tuple[int, ...] = (256, 128, 64),
    funcs: Tuple[str, ...] = ("tanh", "tanh", "tanh"),
    optimizer: Union[str, OptimizerSpec] = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    compile_kwargs: Optional[Dict[str, Any]] = None,
    **kwargs,
) -> FeedForwardSpec:
    """Symmetric AE: ``dims`` for the encoder, reversed for the decoder."""
    if len(dims) == 0:
        raise ValueError("Parameter dims must have len > 0")
    return feedforward_model(
        n_features,
        n_features_out,
        encoding_dim=tuple(dims),
        decoding_dim=tuple(dims)[::-1],
        encoding_func=tuple(funcs),
        decoding_func=tuple(funcs)[::-1],
        optimizer=optimizer,
        optimizer_kwargs=optimizer_kwargs,
        compile_kwargs=compile_kwargs,
        **kwargs,
    )


@register_model_builder(type="JaxAutoEncoder")
def feedforward_hourglass(
    n_features: int,
    n_features_out: Optional[int] = None,
    encoding_layers: int = 3,
    compression_factor: float = 0.5,
    func: str = "tanh",
    optimizer: Union[str, OptimizerSpec] = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    compile_kwargs: Optional[Dict[str, Any]] = None,
    **kwargs,
) -> FeedForwardSpec:
    """
    Hourglass AE: layer sizes taper linearly to ``ceil(compression_factor *
    n_features)`` and mirror back out.

    >>> spec = feedforward_hourglass(10)
    >>> spec.dims
    (8, 7, 5, 5, 7, 8)
    >>> spec.n_features_out
    10
    >>> feedforward_hourglass(10, compression_factor=0.2).dims
    (7, 5, 2, 2, 5, 7)
    >>> feedforward_hourglass(10, encoding_layers=1).dims
    (5, 5)
    """
    dims = hourglass_calc_dims(compression_factor, encoding_layers, n_features)
    return feedforward_symmetric(
        n_features,
        n_features_out,
        dims=dims,
        funcs=tuple([func] * len(dims)),
        optimizer=optimizer,
        optimizer_kwargs=optimizer_kwargs,
        compile_kwargs=compile_kwargs,
        **kwargs,
    )
