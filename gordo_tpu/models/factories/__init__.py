from . import feedforward_autoencoder, lstm_autoencoder  # noqa: F401  (registration)
from .feedforward_autoencoder import (
    feedforward_hourglass,
    feedforward_model,
    feedforward_symmetric,
)
from .lstm_autoencoder import lstm_hourglass, lstm_model, lstm_symmetric

__all__ = [
    "feedforward_model",
    "feedforward_symmetric",
    "feedforward_hourglass",
    "lstm_model",
    "lstm_symmetric",
    "lstm_hourglass",
]
