"""
Build telemetry: span recording, compile/run attribution, and the live
build-progress surface (see recorder.py and progress.py module docs).

Import surface is intentionally small and stdlib-only — the training hot
path imports this package, so it must never pull in server or metrics
dependencies.
"""

from .device import (
    DEVICE_TELEMETRY_ENV,
    device_sampling_enabled,
    emit_device_utilization,
    memory_snapshot,
    note_program_execution,
    program_cache_counters,
    utilization_snapshot,
)
from .fleet_health import (
    FLEET_HEALTH_ENV,
    FLEET_HEALTH_FILE,
    NULL_LEDGER,
    FleetHealthLedger,
    fleet_status_document,
    health_enabled,
    ledger_for,
    ledger_summaries,
    load_health,
    render_fleet_status,
)
from .progress import (
    HEARTBEAT_ENV,
    BuildProgress,
    eta_seconds,
    load_status,
    render_status,
)
from .recorder import (
    KEEP_ENV,
    MAX_BYTES_ENV,
    NULL_RECORDER,
    TELEMETRY_ENV,
    TRACE_DIR_ENV,
    NullRecorder,
    SpanRecorder,
    activate,
    enabled,
    get_recorder,
    program_span,
    reset_seen_programs,
    seen_program,
)
from .serving import (
    SERVE_TRACE_FILE,
    export_request_trace,
    reset_serve_recorder,
    serve_recorder,
    serve_trace_path,
)
from .tracing import (
    TRACEPARENT_HEADER,
    TraceContext,
    bind_trace,
    current_trace_id,
    format_traceparent,
    install_trace_log_stamping,
    new_span_id,
    new_trace_id,
    parse_traceparent,
)

__all__ = [
    "BuildProgress",
    "DEVICE_TELEMETRY_ENV",
    "FLEET_HEALTH_ENV",
    "FLEET_HEALTH_FILE",
    "FleetHealthLedger",
    "HEARTBEAT_ENV",
    "KEEP_ENV",
    "MAX_BYTES_ENV",
    "NULL_LEDGER",
    "NULL_RECORDER",
    "NullRecorder",
    "SERVE_TRACE_FILE",
    "SpanRecorder",
    "TELEMETRY_ENV",
    "TRACEPARENT_HEADER",
    "TRACE_DIR_ENV",
    "TraceContext",
    "activate",
    "bind_trace",
    "current_trace_id",
    "device_sampling_enabled",
    "emit_device_utilization",
    "enabled",
    "eta_seconds",
    "export_request_trace",
    "fleet_status_document",
    "format_traceparent",
    "get_recorder",
    "health_enabled",
    "install_trace_log_stamping",
    "ledger_for",
    "ledger_summaries",
    "load_health",
    "load_status",
    "memory_snapshot",
    "new_span_id",
    "new_trace_id",
    "note_program_execution",
    "parse_traceparent",
    "program_cache_counters",
    "program_span",
    "render_fleet_status",
    "render_status",
    "reset_seen_programs",
    "reset_serve_recorder",
    "seen_program",
    "serve_recorder",
    "serve_trace_path",
    "utilization_snapshot",
]
