"""
Build telemetry: span recording, compile/run attribution, and the live
build-progress surface (see recorder.py and progress.py module docs).

Import surface is intentionally small and stdlib-only — the training hot
path imports this package, so it must never pull in server or metrics
dependencies.
"""

from .progress import (
    HEARTBEAT_ENV,
    BuildProgress,
    eta_seconds,
    load_status,
    render_status,
)
from .recorder import (
    NULL_RECORDER,
    TELEMETRY_ENV,
    TRACE_DIR_ENV,
    NullRecorder,
    SpanRecorder,
    activate,
    enabled,
    get_recorder,
    program_span,
    reset_seen_programs,
    seen_program,
)

__all__ = [
    "BuildProgress",
    "HEARTBEAT_ENV",
    "NULL_RECORDER",
    "NullRecorder",
    "SpanRecorder",
    "TELEMETRY_ENV",
    "TRACE_DIR_ENV",
    "activate",
    "enabled",
    "eta_seconds",
    "get_recorder",
    "load_status",
    "program_span",
    "render_status",
    "reset_seen_programs",
    "seen_program",
]
