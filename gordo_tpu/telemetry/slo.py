"""
The fleet SLO engine: objectives as data, burn-rate alerting as a
state machine, rollups as the evidence.

PRs 3/7/9 emit telemetry; always-on scoring fleets are *operated*
against objectives — "are we inside SLO, and how fast are we burning
error budget" — not raw spans. This module renders that judgment:

- **objectives are declared, not coded**: a ``slos.toml`` (shipped like
  ``analysis/contracts.toml``, overridable per deployment via
  ``GORDO_TPU_SLO_CONFIG`` or a file beside the telemetry sinks) names
  each SLO's objective (``availability`` / ``latency`` over the request
  plane; ``stream_freshness`` / ``stream_integrity`` over the streaming
  plane's rollup row accounting), target and window;
- **evaluation runs over rollups** (telemetry/aggregate.py), never the
  raw span corpus: one incremental aggregation pass, then window merges
  — asking "last 6h burn rate" costs a few hundred small JSON reads,
  not a 256MiB re-parse;
- **alerting is the multi-window fast/slow burn-rate pattern** (the SRE
  workbook's): an alert trips only when the long window AND its short
  confirmation window both burn above threshold, so a stale incident
  cannot page forever and a blip cannot page at all. Alert lifecycle is
  an explicit persisted state machine — ``pending → firing → resolved``
  — atomically journaled to ``slo_state.json`` so a restarted process
  (or the lifecycle supervisor, which holds promotions while a page
  alert fires) reads the same truth;
- surfaces: ``gordo-tpu slo status|check`` (check exits non-zero while
  firing, mirroring ``bench-check``), the ``/gordo/v0/<project>/slo``
  route, a section in :func:`fleet_status_document`, and bounded
  Prometheus gauges (``gordo_slo_*`` — label cardinality is the
  declared SLO count, never fleet or traffic size).

Stdlib-only, like the whole telemetry package.
"""

import json
import logging
import os
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .aggregate import (
    RollupStore,
    histogram_percentile,
    store_for,
    summarize_rollup,
)
from .recorder import _iso, enabled

try:  # Python >= 3.11
    import tomllib
except ImportError:  # pragma: no cover - exercised on 3.10 images
    tomllib = None

logger = logging.getLogger(__name__)

#: the persisted alert state machine, beside the rollups
SLO_STATE_FILE = "slo_state.json"
#: a deployment's own objectives, beside the telemetry sinks
SLO_CONFIG_FILE = "slos.toml"
#: explicit config override (path to a slos.toml)
SLO_CONFIG_ENV = "GORDO_TPU_SLO_CONFIG"
#: /metrics-driven re-evaluation throttle for watched directories
#: (seconds; 0 = scrapes report the cached status only)
SCRAPE_REFRESH_ENV = "GORDO_TPU_SLO_SCRAPE_REFRESH"
DEFAULT_SCRAPE_REFRESH = 60.0

DEFAULT_SLOS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), SLO_CONFIG_FILE
)

#: alert states, in escalation order (the Prometheus gauge exports the
#: index; ``resolved`` maps back to 0 — it is an annotation, not a page)
ALERT_STATES = ("inactive", "pending", "firing", "resolved")

_DURATION_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([smhdw])\s*$")
_DURATION_UNITS = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0, "w": 604800.0}


def parse_duration(value: Any) -> float:
    """``"30d"`` / ``"1h"`` / ``"90m"`` / a bare number of seconds →
    seconds. Raises ValueError on anything else."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value)
    match = _DURATION_RE.match(str(value))
    if not match:
        raise ValueError(f"unparseable duration: {value!r}")
    return float(match.group(1)) * _DURATION_UNITS[match.group(2)]


# -- config -------------------------------------------------------------------


@dataclass(frozen=True)
class SloSpec:
    """One declared objective."""

    name: str
    #: "availability" | "latency" | "stream_freshness" | "stream_integrity"
    objective: str
    target: float
    window: str  # the declared spelling ("30d")
    window_s: float
    threshold_ms: Optional[float] = None
    description: str = ""

    @property
    def budget(self) -> float:
        """The error budget: the bad fraction the target tolerates."""
        return max(1e-9, 1.0 - self.target)


@dataclass(frozen=True)
class BurnRule:
    """One multi-window burn-rate alert rule (fast or slow)."""

    name: str  # "fast" | "slow"
    severity: str  # "page" | "ticket"
    window: str  # declared spelling ("1h")
    window_s: float
    threshold: float
    confirmation_s: float  # the short confirmation window


@dataclass
class SloConfig:
    slos: List[SloSpec] = field(default_factory=list)
    rules: List[BurnRule] = field(default_factory=list)
    source: str = DEFAULT_SLOS_PATH


def _parse_toml_subset(text: str) -> Dict:
    """Minimal TOML reader for ``slos.toml`` on 3.10 images (no
    ``tomllib``; installs are off the table — the same shim pattern as
    ``analysis/contracts.py``). Supports ``[table]`` / ``[[array]]``
    headers and scalar ``key = value`` lines (strings, numbers, TOML
    booleans)."""
    doc: Dict = {}
    current: Dict = doc
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        array_header = re.fullmatch(r"\[\[([\w.\-]+)\]\]", line)
        table_header = re.fullmatch(r"\[([\w.\-]+)\]", line)
        if array_header:
            parts = array_header.group(1).split(".")
            node = doc
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            entries = node.setdefault(parts[-1], [])
            current = {}
            entries.append(current)
            continue
        if table_header:
            parts = table_header.group(1).split(".")
            node = doc
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            current = node.setdefault(parts[-1], {})
            continue
        match = re.match(r"([\w\-]+)\s*=\s*(.*)$", line)
        if not match:
            raise ValueError(f"slos.toml subset parser: bad line {line!r}")
        key, value = match.group(1), match.group(2).strip()
        if not value.startswith(("'", '"')):
            value = value.split("#", 1)[0].strip()
        if value == "true":
            current[key] = True
        elif value == "false":
            current[key] = False
        else:
            import ast as _ast

            try:
                current[key] = _ast.literal_eval(value)
            except (SyntaxError, ValueError) as exc:
                # literal_eval raises SyntaxError on typos like `0..99`;
                # the CLI/route error contract is ValueError
                raise ValueError(
                    f"slos.toml: bad value for {key!r}: {value!r} ({exc})"
                ) from exc
    return doc


def _read_toml(path: str) -> Dict:
    if tomllib is not None:
        with open(path, "rb") as handle:
            return tomllib.load(handle)
    with open(path, encoding="utf-8") as handle:
        return _parse_toml_subset(handle.read())


def resolve_config_path(directory: Optional[str] = None) -> str:
    """Config resolution: ``GORDO_TPU_SLO_CONFIG`` > a ``slos.toml``
    beside the telemetry sinks > the packaged defaults."""
    from ..utils.env import env_str

    override = env_str(SLO_CONFIG_ENV, None)
    if override:
        return override
    if directory:
        local = os.path.join(directory, SLO_CONFIG_FILE)
        if os.path.exists(local):
            return local
    return DEFAULT_SLOS_PATH


def load_slo_config(
    directory: Optional[str] = None, path: Optional[str] = None
) -> SloConfig:
    """Parse the resolved ``slos.toml`` into typed specs + burn rules.
    Malformed SLO entries raise ``ValueError`` — objectives are a
    contract, not advisory telemetry."""
    source = path or resolve_config_path(directory)
    doc = _read_toml(source)
    slos: List[SloSpec] = []
    for entry in doc.get("slo") or []:
        name = str(entry.get("name") or "").strip()
        objective = str(entry.get("objective") or "").strip()
        if not name or objective not in (
            "availability",
            "latency",
            "stream_freshness",
            "stream_integrity",
        ):
            raise ValueError(
                f"slos.toml: every [[slo]] needs a name and an objective "
                f"of availability|latency|stream_freshness|stream_integrity "
                f"(got {entry!r})"
            )
        target = float(entry.get("target", 0.0))
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"slos.toml: {name}: target must be in (0, 1), got {target}"
            )
        threshold_ms = entry.get("threshold_ms")
        if objective in ("latency", "stream_freshness") and (
            threshold_ms is None
        ):
            raise ValueError(
                f"slos.toml: {name}: {objective} objectives need "
                f"threshold_ms"
            )
        window = str(entry.get("window", "30d"))
        slos.append(
            SloSpec(
                name=name,
                objective=objective,
                target=target,
                window=window,
                window_s=parse_duration(window),
                threshold_ms=(
                    float(threshold_ms) if threshold_ms is not None else None
                ),
                description=str(entry.get("description", "")),
            )
        )
    if len({slo.name for slo in slos}) != len(slos):
        raise ValueError("slos.toml: duplicate SLO names")
    burn = doc.get("burn") or {}
    divisor = max(1.0, float(burn.get("confirmation_divisor", 12)))
    rules: List[BurnRule] = []
    for rule_name, default_window, default_threshold, default_severity in (
        ("fast", "1h", 14.4, "page"),
        ("slow", "6h", 6.0, "ticket"),
    ):
        window = str(burn.get(f"{rule_name}_window", default_window))
        window_s = parse_duration(window)
        rules.append(
            BurnRule(
                name=rule_name,
                severity=str(
                    burn.get(f"{rule_name}_severity", default_severity)
                ),
                window=window,
                window_s=window_s,
                threshold=float(
                    burn.get(f"{rule_name}_threshold", default_threshold)
                ),
                confirmation_s=window_s / divisor,
            )
        )
    return SloConfig(slos=slos, rules=rules, source=source)


# -- the math -----------------------------------------------------------------


def histogram_fraction_over(
    histogram: Dict[str, Any], threshold_ms: float
) -> float:
    """Fraction of observations strictly above ``threshold_ms``,
    linearly interpolated inside the containing bucket."""
    total = histogram.get("count", 0)
    if not total:
        return 0.0
    edges = histogram.get("buckets_ms") or []
    counts = histogram.get("counts") or []
    over = 0.0
    lower = 0.0
    for i, count in enumerate(counts):
        upper = edges[i] if i < len(edges) else float("inf")
        if lower >= threshold_ms:
            over += count
        elif upper > threshold_ms and count:
            if upper == float("inf"):
                over += count
            else:
                inside = (upper - threshold_ms) / (upper - lower)
                over += count * max(0.0, min(1.0, inside))
        lower = upper if upper != float("inf") else lower
    return min(1.0, over / total)


def bad_fraction(spec: SloSpec, rollup: Dict[str, Any]) -> Tuple[float, int]:
    """(bad event fraction, total events) for ``spec`` over one merged
    rollup. Sampled traces keep ratios unbiased — counts are estimates,
    fractions are the contract (docs/observability.md).

    Stream objectives read the rollup's ``stream`` section instead of
    the request plane: *freshness* is the rows-weighted fraction of the
    ingest→scored lag histogram above ``threshold_ms``; *integrity* is
    the shed+failed row fraction of everything ingested. Zero stream
    traffic is (0.0, 0) — silence never burns budget."""
    if spec.objective in ("stream_freshness", "stream_integrity"):
        stream = rollup.get("stream") or {}
        if spec.objective == "stream_freshness":
            lag = stream.get("lag_ms") or {}
            total = int(lag.get("count", 0))
            if not total:
                return 0.0, 0
            return (
                histogram_fraction_over(lag, float(spec.threshold_ms)),
                total,
            )
        rows_in = int(stream.get("rows_in", 0))
        if not rows_in:
            return 0.0, 0
        bad = int(stream.get("rows_shed", 0)) + int(
            stream.get("rows_failed", 0)
        )
        return min(1.0, bad / rows_in), rows_in
    requests = rollup.get("requests") or {}
    total = int(requests.get("count", 0))
    if not total:
        return 0.0, 0
    if spec.objective == "availability":
        return int(requests.get("errors", 0)) / total, total
    latency = rollup.get("latency_ms") or {}
    return histogram_fraction_over(latency, float(spec.threshold_ms)), total


def burn_rate(spec: SloSpec, fraction: float) -> float:
    """How many error budgets per SLO window this bad-fraction pace
    spends: 1.0 = exactly on budget, 14.4 = the whole month's budget in
    ~2 days."""
    return round(fraction / spec.budget, 4)


# -- the alert state machine --------------------------------------------------


def advance_alert_state(previous: Optional[str], exceeded: bool) -> str:
    """One evaluation step of the pending → firing → resolved machine:

    - ``inactive``/``resolved`` + exceeded → ``pending`` (one more
      confirming evaluation away from a page);
    - ``pending`` + exceeded → ``firing``;
    - ``firing`` + exceeded → ``firing`` (pages don't flap);
    - ``pending`` + calm → ``inactive`` (the blip never paged);
    - ``firing`` + calm → ``resolved`` (the page is annotated closed);
    - ``resolved`` + calm → ``inactive``.
    """
    if exceeded:
        return "firing" if previous in ("pending", "firing") else "pending"
    if previous == "firing":
        return "resolved"
    return "inactive"


def _load_state(path: str) -> Dict[str, Any]:
    try:
        with open(path) as handle:
            doc = json.load(handle)
    except (OSError, ValueError):
        return {"version": 1, "alerts": {}}
    if not isinstance(doc, dict) or not isinstance(doc.get("alerts"), dict):
        return {"version": 1, "alerts": {}}
    return doc


def _write_state(path: str, doc: Dict[str, Any]) -> None:
    # stage + os.replace in this function (the telemetry atomic-write
    # contract): alert state is load-bearing — the lifecycle supervisor
    # gates promotions on it — so a torn write must be unobservable
    tmp = os.path.join(
        os.path.dirname(path) or ".",
        f".{os.path.basename(path)}.tmp-{os.getpid()}",
    )
    with open(tmp, "w") as handle:
        json.dump(doc, handle, sort_keys=True)
    os.replace(tmp, path)


def state_path(directory: str) -> str:
    return os.path.join(os.path.normpath(directory), SLO_STATE_FILE)


def load_alert_states(directory: str) -> Dict[str, Dict[str, Any]]:
    """The persisted alert records for ``directory`` (empty when the
    engine has never evaluated there)."""
    return dict(_load_state(state_path(directory)).get("alerts") or {})


#: a persisted 'firing' record older than this no longer holds
#: lifecycle promotions: once the evaluator stops running, nothing can
#: ever resolve the alert, and a dead evaluator must not freeze the
#: fleet's self-healing forever (two hours >> any sane scrape refresh)
STALE_ALERT_HOLD_S = 2 * 3600.0


def firing_alerts(
    directory: str,
    severity: Optional[str] = None,
    max_age_s: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Persisted alerts currently ``firing`` (optionally filtered by
    severity) — what the lifecycle supervisor consults before an
    auto-promotion, without running an evaluation of its own. With
    ``max_age_s``, a state document whose last evaluation is older
    than the bound is treated as silence, not as an eternal page: a
    stopped evaluator can never resolve anything, so its stale
    'firing' must not hold promotions forever (a warning is logged)."""
    state = _load_state(state_path(directory))
    alerts = state.get("alerts") or {}
    if max_age_s is not None and alerts:
        from .aggregate import parse_span_time

        updated = parse_span_time(state.get("updated_at"))
        if updated is not None and time.time() - updated > max_age_s:
            if any(a.get("state") == "firing" for a in alerts.values()):
                logger.warning(
                    "slo state in %s last evaluated %s — too stale to "
                    "hold promotions; run `gordo-tpu slo status` (or "
                    "keep the server scraping) to refresh it",
                    directory,
                    state.get("updated_at"),
                )
            return []
    found = []
    for alert_id, record in sorted(alerts.items()):
        if record.get("state") != "firing":
            continue
        if severity is not None and record.get("severity") != severity:
            continue
        found.append({"id": alert_id, **record})
    return found


# -- evaluation ---------------------------------------------------------------


def slo_directory(anchor: Optional[str] = None) -> Optional[str]:
    """Where the serving telemetry (and therefore the rollups and SLO
    state) live: ``GORDO_TPU_TELEMETRY_DIR`` when configured, else the
    caller's anchor (a build dir carries its own sinks)."""
    from ..utils.env import env_str

    from .recorder import TRACE_DIR_ENV

    return env_str(TRACE_DIR_ENV, None) or anchor


#: per-directory evaluation locks: the alert state machine is a
#: read-modify-write of slo_state.json, and two concurrent evaluations
#: (the scrape thread racing a /slo request) could otherwise step one
#: logical evaluation twice (pending -> firing in milliseconds) or lose
#: a firing write the lifecycle gate depends on
_eval_locks_guard = threading.Lock()
_eval_locks: Dict[str, threading.Lock] = {}


def _eval_lock(directory: str) -> threading.Lock:
    with _eval_locks_guard:
        lock = _eval_locks.get(directory)
        if lock is None:
            lock = _eval_locks[directory] = threading.Lock()
        return lock


def evaluate(
    directory: str,
    config: Optional[SloConfig] = None,
    now: Optional[float] = None,
    store: Optional[RollupStore] = None,
    aggregate_first: bool = True,
) -> Dict[str, Any]:
    """
    One SLO evaluation over ``directory``'s rollups: aggregate any new
    spans (incremental), compute per-SLO budgets and multi-window burn
    rates, advance the persisted alert state machine, and return the
    full status document (the shape ``gordo-tpu slo status --as-json``
    prints and the /slo route serves). Serialized per directory — see
    :data:`_eval_locks`.
    """
    directory = os.path.normpath(directory)
    config = config or load_slo_config(directory)
    # the SHARED per-directory store: its instance lock serializes a
    # scrape-thread evaluation against a concurrent /slo route one —
    # two fresh stores would double-fold the same new spans
    store = store or store_for(directory)
    with _eval_lock(directory):
        return _evaluate_locked(
            directory, config, now, store, aggregate_first
        )


def _evaluate_locked(
    directory: str,
    config: SloConfig,
    now: Optional[float],
    store: RollupStore,
    aggregate_first: bool,
) -> Dict[str, Any]:
    aggregation = store.aggregate() if aggregate_first else None
    now = time.time() if now is None else float(now)

    state_file = state_path(directory)
    state = _load_state(state_file)
    alerts_state: Dict[str, Any] = state.get("alerts") or {}

    slos_doc: List[Dict[str, Any]] = []
    alerts_doc: List[Dict[str, Any]] = []
    #: merged rollups are cached per distinct window length — the fast
    #: and slow rules of every SLO share the same four merges
    merged_cache: Dict[float, Dict[str, Any]] = {}

    def merged(seconds: float) -> Dict[str, Any]:
        if seconds not in merged_cache:
            merged_cache[seconds] = store.merged(
                since=now - seconds, until=now
            )
        return merged_cache[seconds]

    for spec in config.slos:
        window_rollup = merged(spec.window_s)
        fraction, total = bad_fraction(spec, window_rollup)
        consumed = min(1.0, fraction / spec.budget)
        burn_rates: Dict[str, float] = {}
        for rule in config.rules:
            long_fraction, _ = bad_fraction(spec, merged(rule.window_s))
            short_fraction, _ = bad_fraction(
                spec, merged(rule.confirmation_s)
            )
            long_burn = burn_rate(spec, long_fraction)
            short_burn = burn_rate(spec, short_fraction)
            burn_rates[rule.window] = long_burn
            exceeded = (
                long_burn > rule.threshold and short_burn > rule.threshold
            )
            alert_id = f"{spec.name}:{rule.name}"
            previous = alerts_state.get(alert_id) or {}
            previous_state = previous.get("state")
            next_state = advance_alert_state(previous_state, exceeded)
            record = {
                "slo": spec.name,
                "rule": rule.name,
                "severity": rule.severity,
                "state": next_state,
                "since": (
                    previous.get("since")
                    if next_state == previous_state
                    else _iso(now)
                ),
                "last_transition": (
                    previous.get("last_transition")
                    if next_state == previous_state
                    else _iso(now)
                ),
                "burn_rate": long_burn,
                "confirmation_burn_rate": short_burn,
                "threshold": rule.threshold,
                "window": rule.window,
                "confirmation_s": rule.confirmation_s,
            }
            alerts_state[alert_id] = record
            alerts_doc.append({"id": alert_id, **record})
        entry = {
            "name": spec.name,
            "objective": spec.objective,
            "description": spec.description,
            "target": spec.target,
            "window": spec.window,
            "threshold_ms": spec.threshold_ms,
            "requests": total,
            "bad_fraction": round(fraction, 6),
            "budget": {
                "total_ratio": round(spec.budget, 6),
                "consumed_ratio": round(consumed, 6),
                "remaining_ratio": round(1.0 - consumed, 6),
            },
            "burn_rates": burn_rates,
        }
        if spec.objective == "latency":
            entry["latency_p95_ms"] = histogram_percentile(
                window_rollup.get("latency_ms") or {}, 0.95
            )
        elif spec.objective == "stream_freshness":
            entry["lag_p95_ms"] = histogram_percentile(
                (window_rollup.get("stream") or {}).get("lag_ms") or {},
                0.95,
            )
        slos_doc.append(entry)

    # alerts for SLOs no longer declared are dropped, not zombie-fired
    declared = {f"{s.name}:{r.name}" for s in config.slos for r in config.rules}
    alerts_state = {
        key: value for key, value in alerts_state.items() if key in declared
    }
    state.update(
        {
            "version": 1,
            "alerts": alerts_state,
            "updated_at": _iso(now),
            "config_source": config.source,
        }
    )
    try:
        os.makedirs(directory, exist_ok=True)
        _write_state(state_file, state)
    except OSError as exc:
        logger.warning("slo state not persisted: %r", exc)

    firing = sum(1 for a in alerts_doc if a["state"] == "firing")
    pending = sum(1 for a in alerts_doc if a["state"] == "pending")
    doc = {
        "version": 1,
        "directory": directory,
        "generated_at": _iso(now),
        "config": {
            "source": config.source,
            "rules": [
                {
                    "name": rule.name,
                    "severity": rule.severity,
                    "window": rule.window,
                    "threshold": rule.threshold,
                    "confirmation_s": rule.confirmation_s,
                }
                for rule in config.rules
            ],
        },
        "slos": slos_doc,
        "alerts": alerts_doc,
        "firing": firing,
        "pending": pending,
        "ok": firing == 0,
        "recent": summarize_rollup(merged(3600.0)),
    }
    if aggregation is not None:
        doc["aggregation"] = aggregation
    note_status(directory, doc, now=now)
    return doc


#: the package-level spelling (``telemetry.evaluate_slos``); inside
#: this module the short name reads better
evaluate_slos = evaluate


def evaluate_cached(
    directory: str,
    config: Optional[SloConfig] = None,
    max_age_s: Optional[float] = None,
) -> Dict[str, Any]:
    """:func:`evaluate`, throttled: return the cached status when one
    younger than ``max_age_s`` exists (default: the scrape-refresh
    knob). The /slo route and the scrape collector both go through
    here, so an external poller cannot turn a read surface into
    write amplification — or drive the pending→firing confirmation
    step faster than the refresh cadence."""
    directory = os.path.normpath(directory)
    if max_age_s is None:
        max_age_s = scrape_refresh_seconds()
    if max_age_s > 0:
        with _registry_lock:
            entry = _statuses.get(directory)
        if entry is not None and time.time() - entry[1] < max_age_s:
            return entry[0]
    return evaluate(directory, config=config)


# -- the process-global status registry (Prometheus exposition) ---------------

_registry_lock = threading.Lock()
#: directory -> (status doc, evaluated-at epoch) — what the scrape-time
#: SloCollector exports; populated by every evaluate()
_statuses: Dict[str, Tuple[Dict[str, Any], float]] = {}
#: directories the serving process asked to keep fresh at scrape time
_watched: set = set()


def note_status(
    directory: str, doc: Dict[str, Any], now: Optional[float] = None
) -> None:
    with _registry_lock:
        _statuses[os.path.normpath(directory)] = (
            doc,
            time.time() if now is None else float(now),
        )


def watch(directory: Optional[str]) -> None:
    """Mark ``directory`` for scrape-time SLO refresh (the server calls
    this at boot for its anchor's telemetry dir)."""
    if directory and enabled():
        with _registry_lock:
            _watched.add(os.path.normpath(directory))


def reset_statuses() -> None:
    """Drop cached statuses and watches (tests only)."""
    with _registry_lock:
        _statuses.clear()
        _watched.clear()


def scrape_refresh_seconds() -> float:
    from ..utils.env import env_float

    value = env_float(SCRAPE_REFRESH_ENV, DEFAULT_SCRAPE_REFRESH)
    return max(0.0, value if value is not None else DEFAULT_SCRAPE_REFRESH)


def scrape_statuses() -> Dict[str, Dict[str, Any]]:
    """directory -> latest status doc for the Prometheus collector,
    re-evaluating watched directories whose cache is older than
    ``GORDO_TPU_SLO_SCRAPE_REFRESH`` (0 = cached only — scrapes never
    pay an aggregation)."""
    refresh = scrape_refresh_seconds()
    with _registry_lock:
        watched = set(_watched)
        cached = dict(_statuses)
    if refresh > 0:
        for directory in sorted(watched):
            try:
                evaluate_cached(directory, max_age_s=refresh)
            except Exception:  # noqa: BLE001 - scrapes must never fail
                # on a broken sink; the stale cache (if any) still reports
                logger.debug("scrape-time slo refresh failed", exc_info=True)
        with _registry_lock:
            cached = dict(_statuses)
    return {directory: doc for directory, (doc, _) in cached.items()}


def slo_section(directory: str) -> Optional[Dict[str, Any]]:
    """The compact SLO section for :func:`fleet_status_document`: alert
    states + headline budgets from the cached status when this process
    evaluated recently, else from the persisted state machine alone
    (cheap — one small JSON read, no aggregation)."""
    directory = os.path.normpath(directory)
    with _registry_lock:
        entry = _statuses.get(directory)
    if entry is not None:
        doc = entry[0]
        return {
            "firing": doc.get("firing", 0),
            "pending": doc.get("pending", 0),
            "ok": doc.get("ok", True),
            "alerts": doc.get("alerts"),
            "budgets": {
                slo["name"]: slo["budget"]["remaining_ratio"]
                for slo in doc.get("slos") or []
            },
            "evaluated_at": doc.get("generated_at"),
        }
    state = _load_state(state_path(directory))
    alerts = state.get("alerts") or {}
    if not alerts:
        return None
    firing = sum(1 for a in alerts.values() if a.get("state") == "firing")
    pending = sum(1 for a in alerts.values() if a.get("state") == "pending")
    return {
        "firing": firing,
        "pending": pending,
        "ok": firing == 0,
        "alerts": [
            {"id": alert_id, **record}
            for alert_id, record in sorted(alerts.items())
        ],
        "budgets": None,
        "evaluated_at": state.get("updated_at"),
    }


# -- rendering ----------------------------------------------------------------

_STATE_MARKS = {
    "inactive": "ok",
    "pending": "PENDING",
    "firing": "FIRING",
    "resolved": "resolved",
}


def render_slo_status(doc: Dict[str, Any]) -> str:
    """Human rendering of the status document (the ``slo status``
    table view)."""
    lines: List[str] = [
        f"SLO status: {doc.get('directory', '-')}  "
        f"(evaluated {doc.get('generated_at', '?')})"
    ]
    for slo in doc.get("slos") or []:
        budget = slo.get("budget") or {}
        burn = ", ".join(
            f"{window}={rate:g}x"
            for window, rate in (slo.get("burn_rates") or {}).items()
        )
        threshold = (
            f" (<= {slo['threshold_ms']:g}ms)"
            if slo.get("threshold_ms") is not None
            else ""
        )
        unit = (
            "row(s)"
            if str(slo.get("objective", "")).startswith("stream")
            else "request(s)"
        )
        lines.append(
            f"  {slo['name']}: {slo['objective']}{threshold} "
            f"target {slo['target']:.4%} over {slo['window']} — "
            f"budget remaining {budget.get('remaining_ratio', 0) * 100:.1f}%"
            f" ({slo.get('requests', 0)} {unit}, burn {burn or '-'})"
        )
    alerts = doc.get("alerts") or []
    active = [a for a in alerts if a.get("state") != "inactive"]
    lines.append(
        f"alerts: {doc.get('firing', 0)} firing, "
        f"{doc.get('pending', 0)} pending"
    )
    for alert in active:
        lines.append(
            f"  [{_STATE_MARKS.get(alert['state'], alert['state'])}] "
            f"{alert['id']} ({alert['severity']}): burn "
            f"{alert.get('burn_rate', 0):g}x over {alert['window']} "
            f"(threshold {alert.get('threshold', 0):g}x, since "
            f"{alert.get('since', '?')})"
        )
    verdict = "inside SLO" if doc.get("ok") else "BURNING — page is firing"
    lines.append(f"result: {verdict}")
    return "\n".join(lines)
