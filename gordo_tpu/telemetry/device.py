"""
Device-utilization telemetry: measured HBM occupancy and compile-cache
hit accounting.

Everything the planner (PR 5) says about device memory is a *prediction*
from spec geometry, and everything the compile-cache work (PR 5) does is
invisible once it works — until now nothing measured either. This module
closes both gaps:

- :func:`memory_snapshot` reads ``Device.memory_stats()`` off every
  local device (``bytes_in_use`` / ``peak_bytes_in_use`` / the backend's
  limit) and aggregates them into one JSON-able dict. The fleet builder
  emits it as a ``device_utilization`` event at phase boundaries (the
  measured counterpart of the FleetPlan's predicted HBM), and the
  Prometheus device collector reads it at scrape time. Backends without
  the stats (older CPU jaxlib) degrade to ``{"available": False}`` —
  callers never branch on platform.
- :func:`note_program_execution` is the process-wide compile-vs-cache-hit
  counter pair, fed by the two places that know: the build side's
  :func:`~gordo_tpu.telemetry.recorder.program_span` (first call per
  signature = compile, later = hit — the jit cache's own semantics) and
  the serving engine's fused-program bookkeeping. The persistent
  compile-cache directory (``GORDO_TPU_COMPILE_CACHE``), when
  ``parallel/mesh.py`` configures one, is inventoried by
  :func:`persistent_cache_info` (entries + bytes on disk).

The counters and snapshots here are stdlib data; only the memory probe
touches jax, lazily, so importing this module stays free on hosts
without an accelerator stack.
"""
# gt-lint: file-disable=jax-stdlib-only -- this module IS the telemetry
# package's Device.memory_stats() wrapper; the jax import stays lazy and
# failure-isolated so the package still imports (and the counters still
# work) on hosts without jax

import os
import threading
from typing import Any, Dict, Optional

#: master switch for the (slightly costly) device memory probe; the
#: counters are a few ns and stay on with telemetry itself
DEVICE_TELEMETRY_ENV = "GORDO_TPU_DEVICE_TELEMETRY"

#: memory_stats() keys aggregated across local devices (keys a backend
#: does not report simply contribute nothing)
_MEMORY_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_sampling_enabled() -> bool:
    """Memory sampling on? (telemetry master switch AND
    ``GORDO_TPU_DEVICE_TELEMETRY``, both default-on)."""
    from ..utils.env import env_bool
    from .recorder import enabled

    return enabled() and env_bool(DEVICE_TELEMETRY_ENV, True)


# -- compile-cache hit/miss counters -----------------------------------------

_counter_lock = threading.Lock()
#: kind -> {"compiles": n, "cache_hits": n}; ``build`` is fed by
#: program_span's first-call attribution, ``serve`` by the engine's
#: fused-program set
_program_counters: Dict[str, Dict[str, int]] = {}


def note_program_execution(
    compiled: bool, kind: str = "build", precision: Optional[str] = None
) -> None:
    """Count one jit-program execution: ``compiled=True`` for a
    cache-miss (trace+compile happened inside the call), False for a
    steady-state cache-hit run. ``precision`` (the serve engine's
    precision ladder: ``f32``/``bf16``/``int8``) additionally buckets
    the count per serving precision, so the compile-cache console can
    answer "did the bf16 ladder actually warm" per axis."""
    with _counter_lock:
        counters = _program_counters.get(kind)
        if counters is None:
            counters = _program_counters[kind] = {
                "compiles": 0,
                "cache_hits": 0,
            }
        counters["compiles" if compiled else "cache_hits"] += 1
        if precision:
            by_precision = counters.setdefault("by_precision", {})
            sub = by_precision.setdefault(
                precision, {"compiles": 0, "cache_hits": 0}
            )
            sub["compiles" if compiled else "cache_hits"] += 1


def program_cache_counters() -> Dict[str, Dict[str, Any]]:
    """Snapshot of the per-kind compile/cache-hit counters, each with a
    derived ``hit_rate`` (None until anything executed); the serve
    kind's per-precision sub-counters ride along under
    ``by_precision``."""
    with _counter_lock:
        snapshot = {}
        for kind, counters in _program_counters.items():
            copied = dict(counters)
            if "by_precision" in copied:
                copied["by_precision"] = {
                    prec: dict(sub)
                    for prec, sub in copied["by_precision"].items()
                }
            snapshot[kind] = copied
    for counters in snapshot.values():
        total = counters["compiles"] + counters["cache_hits"]
        counters["hit_rate"] = (
            round(counters["cache_hits"] / total, 4) if total else None
        )
    return snapshot


def reset_program_counters() -> None:
    """Zero the counters (tests only — production keeps them for the
    life of the process, like the jit caches they describe)."""
    with _counter_lock:
        _program_counters.clear()


# -- persistent compile cache -------------------------------------------------

_cache_dir_lock = threading.Lock()
_persistent_cache_dir: Optional[str] = None


def note_compile_cache_dir(path: Optional[str]) -> None:
    """Record the persistent compile-cache directory
    ``parallel/mesh.configure_compile_cache`` actually configured (the
    env knob alone does not mean the configure call succeeded)."""
    global _persistent_cache_dir
    with _cache_dir_lock:
        _persistent_cache_dir = path


def persistent_cache_info() -> Optional[Dict[str, Any]]:
    """Inventory of the persistent compile cache (entry count + bytes),
    or None when no cache directory is configured. Best-effort: a
    vanished directory reports zero entries, never raises."""
    with _cache_dir_lock:
        cache_dir = _persistent_cache_dir
    if cache_dir is None:
        from ..utils.env import env_str

        cache_dir = env_str("GORDO_TPU_COMPILE_CACHE", None)
    if not cache_dir:
        return None
    entries = 0
    total_bytes = 0
    try:
        with os.scandir(cache_dir) as it:
            for entry in it:
                try:
                    if entry.is_file():
                        entries += 1
                        total_bytes += entry.stat().st_size
                except OSError:
                    continue
    except OSError:
        pass
    return {"path": cache_dir, "entries": entries, "bytes": total_bytes}


# -- device memory ------------------------------------------------------------


def memory_snapshot() -> Optional[Dict[str, Any]]:
    """
    Aggregate ``Device.memory_stats()`` over the local devices:
    ``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit`` summed
    across devices, plus the per-device maxima (the number an HBM-cap
    planner compares against) and how many devices actually reported.

    Returns None when sampling is disabled or jax is unavailable;
    ``{"available": False, ...}`` when the backend has no stats (the
    distinction callers render differently: "off" vs "not measurable").
    """
    if not device_sampling_enabled():
        return None
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 - no jax / broken backend: telemetry
        # must degrade, never take the caller down
        return None
    doc: Dict[str, Any] = {
        "devices": len(devices),
        "measured_devices": 0,
        "available": False,
    }
    totals = {key: 0 for key in _MEMORY_KEYS}
    maxima = {key: 0 for key in _MEMORY_KEYS}
    for device in devices:
        try:
            stats = device.memory_stats()
        except Exception:  # noqa: BLE001 - per-device isolation
            stats = None
        if not stats:
            continue
        doc["measured_devices"] += 1
        for key in _MEMORY_KEYS:
            value = stats.get(key)
            if value is None and key == "peak_bytes_in_use":
                # some backends spell peak differently; fall back to
                # in-use so the field is never silently absent
                value = stats.get("bytes_in_use")
            if value is None:
                continue
            value = int(value)
            totals[key] += value
            maxima[key] = max(maxima[key], value)
    if doc["measured_devices"]:
        doc["available"] = True
        for key in _MEMORY_KEYS:
            doc[key] = totals[key]
            doc[f"max_{key}"] = maxima[key]
        limit = totals.get("bytes_limit") or 0
        if limit:
            doc["utilization"] = round(totals["bytes_in_use"] / limit, 4)
    return doc


def utilization_snapshot() -> Dict[str, Any]:
    """The full device-telemetry document: memory + compile-cache
    counters + persistent-cache inventory (each section None/absent when
    unavailable). This is what the ``device_utilization`` events and the
    fleet-status surface carry."""
    doc: Dict[str, Any] = {"compile_cache": program_cache_counters()}
    memory = memory_snapshot()
    if memory is not None:
        doc["memory"] = memory
    persistent = persistent_cache_info()
    if persistent is not None:
        doc["persistent_cache"] = persistent
    return doc


def emit_device_utilization(recorder: Any, **attributes: Any) -> Optional[dict]:
    """Emit one ``device_utilization`` event onto ``recorder`` (memory +
    cache counters flattened to event attributes) and return the
    snapshot, or None when sampling is off/unavailable. The fleet
    builder calls this at phase boundaries — a handful of samples per
    build, not per program."""
    memory = memory_snapshot()
    if memory is None:
        return None
    counters = program_cache_counters().get("build") or {}
    recorder.event(
        "device_utilization",
        **attributes,
        **{f"memory_{k}": v for k, v in memory.items()},
        compiles=counters.get("compiles", 0),
        cache_hits=counters.get("cache_hits", 0),
    )
    return memory
