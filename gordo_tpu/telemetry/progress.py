"""
Live fleet-build progress: the ``build_status.json`` heartbeat.

The reference operator watched a build with ``argo get`` — per-machine
phase, counts and durations straight from the pod DAG. The chip-fan-out
build's equivalent is this compact document, atomically rewritten beside
the build journal on every phase transition and machine completion, so
*any* moment of the build has a current, parseable status on disk:

- the ``gordo-tpu build-status <output-dir>`` CLI renders it (per-phase
  table, progress bar, ETA from the completed-machine rate),
- the model server serves it verbatim from
  ``/gordo/v0/<project>/build-status``,
- dashboards can poll the file over whatever volume carries the
  artifacts.

Writes are throttled by ``GORDO_TPU_TELEMETRY_HEARTBEAT`` (seconds
between machine-completion writes; default 0.5). The throttle is what
makes the surface free at any scale: an atomic replace costs ~1ms, so
per-completion writes would tax a toy build measurably while a real
heartbeat is at most ~2 writes/second no matter how many thousand
machines are landing. ``0`` opts into exact per-completion durability
(the fault-injection drills use it so the status is never behind the
journal). First entry of each phase and the final state always write.
"""

import contextlib
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .recorder import _iso

logger = logging.getLogger(__name__)

HEARTBEAT_ENV = "GORDO_TPU_TELEMETRY_HEARTBEAT"
DEFAULT_HEARTBEAT_SECONDS = 0.5

#: canonical names of the telemetry files written beside the artifacts.
#: They live HERE (not serializer.py) because this package must stay
#: stdlib-only importable from the training hot path; the serializer's
#: artifact-discovery predicates re-export them.
BUILD_STATUS_FILE = "build_status.json"
BUILD_TRACE_FILE = "build_trace.jsonl"


class BuildProgress:
    """
    Counter/phase tracker that heartbeats ``<output_dir>/build_status.json``.

    Thread-safe: the dump pool reports completions concurrently. With
    ``output_dir=None`` the counters still track (and feed the Prometheus
    gauges via the builder) but nothing is written.
    """

    def __init__(
        self,
        output_dir: Optional[str],
        project: str = "",
        total: int = 0,
        phase_seconds: Optional[Dict[str, float]] = None,
        heartbeat_seconds: Optional[float] = None,
    ):
        self.path = (
            os.path.join(output_dir, BUILD_STATUS_FILE)
            if output_dir is not None
            else None
        )
        if output_dir is not None:
            try:
                os.makedirs(output_dir, exist_ok=True)
            except OSError:
                self.path = None  # advisory: never fail the build
        self.project = project
        self.total = total
        self.completed = 0
        self.failed = 0
        self.resumed = 0
        self.cached = 0
        self.degraded = 0
        self.state = "running"
        self.started_at = time.time()
        #: reference to the builder's live phase_seconds dict — snapshot
        #: at every write so the doc carries the fine-grained breakdown
        self.phase_seconds = phase_seconds if phase_seconds is not None else {}
        if heartbeat_seconds is None:
            from ..utils.env import env_float

            heartbeat_seconds = env_float(HEARTBEAT_ENV, DEFAULT_HEARTBEAT_SECONDS)
        self.heartbeat_seconds = max(0.0, heartbeat_seconds)
        self._phase: Optional[str] = None
        self._phase_order: List[str] = []
        self._lock = threading.Lock()
        self._write_lock = threading.Lock()  # serializes write+rename
        self._last_write = 0.0

    # -- build lifecycle ----------------------------------------------------

    def phase(self, name: str) -> None:
        """Enter a build phase. Phases re-enter freely (the CV loop
        interleaves train/predict/score once per bucket chunk); only the
        FIRST entry of each phase forces a write — re-entries ride the
        heartbeat throttle, so a thousand-chunk CV costs one forced
        write, not a thousand ~ms atomic replaces."""
        with self._lock:
            changed = self._phase != name
            self._phase = name
            first_entry = name not in self._phase_order
            if first_entry:
                self._phase_order.append(name)
        if first_entry:
            self.write(force=True)
        elif changed:
            self.write(min_interval=self.PHASE_REENTRY_INTERVAL)

    def machine_completed(self, name: str = "") -> None:
        with self._lock:
            self.completed += 1
        self.write()

    def machine_failed(self, name: str = "") -> None:
        with self._lock:
            self.failed += 1
        self.write()

    def finish(self, state: str = "complete") -> None:
        with self._lock:
            self.state = state
            self._phase = None
        self.write(force=True)

    # -- the document -------------------------------------------------------

    def document(self) -> Dict[str, Any]:
        with self._lock:
            now = time.time()
            phases = {
                name: {
                    "seconds": round(
                        float(self.phase_seconds.get(name, 0.0)), 6
                    ),
                    "status": "running" if name == self._phase else "done",
                }
                for name in self._phase_order
            }
            return {
                "version": 1,
                "project": self.project,
                "state": self.state,
                "phase": self._phase,
                "started_at": _iso(self.started_at),
                "updated_at": _iso(now),
                "elapsed_sec": round(now - self.started_at, 3),
                "machines": {
                    "total": self.total,
                    "completed": self.completed,
                    "failed": self.failed,
                    "resumed": self.resumed,
                    "cached": self.cached,
                    "degraded": self.degraded,
                },
                "phases": phases,
            }

    #: floor on how often phase RE-entries rewrite the doc — the CV loop
    #: cycles train/predict/score once per bucket chunk, and each atomic
    #: replace costs ~1ms; machine completions are not floored (their
    #: durability mirrors the journal's per-machine event append)
    PHASE_REENTRY_INTERVAL = 0.2

    def write(
        self, force: bool = False, min_interval: Optional[float] = None
    ) -> None:
        """Atomically replace the status file (best-effort: the build
        must never fail because its progress doc could not land).
        ``min_interval`` raises the throttle floor for this call only."""
        if self.path is None:
            return
        interval = self.heartbeat_seconds
        if min_interval is not None:
            interval = max(interval, min_interval)
        now = time.time()
        with self._write_lock:
            with self._lock:
                if not force and now - self._last_write < interval:
                    return
                self._last_write = now
            doc = self.document()
            # Dotted staging-convention name, like the journal's flush:
            # an interrupted write leaves a file every discovery path
            # already classifies as a staging leftover. The write+rename
            # happens under _write_lock (a dedicated lock so document()
            # can take _lock): the dump pool reports completions from 8
            # threads sharing this one pid-named tmp path, and an
            # unlocked open(tmp, "w") would truncate a sibling's
            # in-flight write — renaming torn JSON into the status file.
            tmp = f"{os.path.join(os.path.dirname(self.path), '.' + BUILD_STATUS_FILE)}.tmp-{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump(doc, f, default=str)
                os.replace(tmp, self.path)
            except OSError as exc:
                logger.debug("build_status heartbeat not written: %r", exc)
                with contextlib.suppress(OSError):
                    os.remove(tmp)


def load_status(output_dir: str) -> Optional[Dict[str, Any]]:
    """The build-status document from ``output_dir``, or None when no
    build has written one (or it is unreadable)."""
    try:
        with open(os.path.join(output_dir, BUILD_STATUS_FILE)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def eta_seconds(doc: Dict[str, Any]) -> Optional[float]:
    """ETA from the completed-machine rate, or None while no machine has
    completed (training phases finish machines in bulk at dump time, so
    the estimate firms up as artifacts start landing)."""
    machines = doc.get("machines") or {}
    completed = int(machines.get("completed") or 0)
    elapsed = float(doc.get("elapsed_sec") or 0.0)
    if doc.get("state") != "running" or completed <= 0 or elapsed <= 0:
        return None
    remaining = (
        int(machines.get("total") or 0)
        - completed
        - int(machines.get("resumed") or 0)
        - int(machines.get("failed") or 0)
    )
    if remaining <= 0:
        return 0.0
    return remaining * elapsed / completed


def render_status(doc: Dict[str, Any]) -> str:
    """Human rendering of a build-status document (the ``build-status``
    CLI's output): header, progress bar + ETA, per-phase table."""
    machines = doc.get("machines") or {}
    total = int(machines.get("total") or 0)
    completed = int(machines.get("completed") or 0)
    resumed = int(machines.get("resumed") or 0)
    failed = int(machines.get("failed") or 0)
    done = completed + resumed
    state = doc.get("state", "unknown")
    phase = doc.get("phase")
    lines = [
        f"Project:  {doc.get('project') or '-'}",
        f"State:    {state}" + (f" (phase: {phase})" if phase else ""),
        f"Started:  {doc.get('started_at', '-')}  "
        f"(elapsed {doc.get('elapsed_sec', 0):.0f}s)",
        f"Machines: {done}/{total} done"
        + (f" ({resumed} resumed)" if resumed else "")
        + (f", {failed} failed" if failed else "")
        + (
            f", {machines.get('degraded')} degraded"
            if machines.get("degraded")
            else ""
        ),
    ]
    if total:
        frac = min(1.0, (done + failed) / total)
        width = 30
        fill = int(round(frac * width))
        bar = "#" * fill + "." * (width - fill)
        eta = eta_seconds(doc)
        eta_text = f"   ETA ~{eta:.0f}s" if eta is not None else ""
        lines.append(f"Progress: [{bar}] {frac * 100:3.0f}%{eta_text}")
    phases = doc.get("phases") or {}
    if phases:
        lines.append("Phases:")
        name_width = max(len(name) for name in phases)
        lines.append(f"  {'phase'.ljust(name_width)}  {'seconds':>9}  status")
        for name, entry in phases.items():
            lines.append(
                f"  {name.ljust(name_width)}  "
                f"{float(entry.get('seconds', 0.0)):9.2f}  "
                f"{entry.get('status', '')}"
            )
    return "\n".join(lines)
