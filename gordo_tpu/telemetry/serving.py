"""
The serving-side trace surface: one process-shared ``serve_trace.jsonl``.

The build side has had a span trace since PR 3 (``build_trace.jsonl``);
this module gives the *serving* side its equivalent, with one crucial
difference: a server handles thousands of concurrent requests, so there
is no single recorder wrapping "the work" — instead

- every request gets a cheap **in-memory** recorder on its context
  (``RequestContext.timing``, no file handle per request) carrying the
  request's own W3C trace id;
- at response finalization the request's finished stage spans plus one
  synthesized ``request`` root span are emitted into the process-shared
  sink recorder this module owns (:func:`serve_recorder`) in one pass;
- the micro-batching engine records its batch spans into the same sink,
  each carrying OTel ``links`` back to the request spans it coalesced —
  so queue-wait/stack/device/scatter are attributable per request.

The sink lives at ``$GORDO_TPU_TELEMETRY_DIR/serve_trace.jsonl`` and
rotates by size (``GORDO_TPU_TELEMETRY_MAX_BYTES``); with telemetry off
(``GORDO_TPU_TELEMETRY=0``) or no trace dir configured, everything here
short-circuits to the :data:`~gordo_tpu.telemetry.NULL_RECORDER` and no
file is ever created — the master-switch contract the serve hot path is
tested against.
"""

import atexit
import os
import random
import threading
from typing import Any, Dict, Optional

from ..utils.postfork import register_postfork_reset
from .recorder import (
    NULL_RECORDER,
    TRACE_DIR_ENV,
    SpanRecorder,
    enabled,
    rand_hex,
    worker_sink_path,
)

#: the serving-side JSONL trace beside ``build_trace.jsonl`` — batch
#: spans (the engine), request spans and stage spans (the server)
SERVE_TRACE_FILE = "serve_trace.jsonl"

#: head-sampling rate for request-trace export, in [0, 1]. Every request
#: still GETS a trace id (headers, logs, RED metrics see all traffic);
#: this gates only which requests' spans are written to
#: ``serve_trace.jsonl``. Sampling is how the trace stays affordable at
#: production request rates — the RED histograms carry the full
#: population statistics, the trace carries attributable exemplars.
#: Overridden per request by an incoming ``traceparent`` sampled flag
#: (a sampled upstream trace always exports) and by ``?profile=1``.
TRACE_SAMPLE_RATE_ENV = "GORDO_TPU_TRACE_SAMPLE_RATE"
DEFAULT_TRACE_SAMPLE_RATE = 0.05

_lock = threading.Lock()
_recorder: Optional[SpanRecorder] = None
_atexit_registered = False


def _reset_after_fork() -> None:
    """Drop the inherited recorder in a freshly forked worker: its sink
    path froze the PARENT's pid (``worker_sink_path``) and its writer
    thread does not exist on this side of the fork — every span the
    child enqueued would silently never reach disk. The child is
    single-threaded here and the inherited lock may have been
    snapshotted mid-acquire, so rebind without locking; dropping (not
    closing) also avoids double-flushing the parent's file handle."""
    global _recorder, _lock
    _lock = threading.Lock()
    # gt-lint: disable=lock-guard -- post-fork child is single-threaded;
    # the inherited module lock may be frozen in an acquired state, so
    # taking it here could deadlock the new worker at boot
    _recorder = None


register_postfork_reset(_reset_after_fork, name="telemetry.serving.recorder")


#: (raw env string, parsed rate) — the parse is cached per distinct env
#: value so the hot path pays one getenv + one string compare
_rate_cache: tuple = (None, DEFAULT_TRACE_SAMPLE_RATE)


def trace_sample_rate() -> float:
    global _rate_cache
    from ..utils.env import env_raw

    raw = env_raw(TRACE_SAMPLE_RATE_ENV)
    cached_raw, cached_rate = _rate_cache
    if raw == cached_raw:
        return cached_rate
    # slow path only when the env value changed: the shared warn-and-
    # fall-back parser, clamped to a fraction
    from ..utils.env import env_float

    rate = min(
        1.0,
        max(0.0, env_float(TRACE_SAMPLE_RATE_ENV, DEFAULT_TRACE_SAMPLE_RATE)),
    )
    _rate_cache = (raw, rate)
    return rate


def sample_trace() -> bool:
    """The head-sampling coin flip for a locally-originated trace."""
    rate = trace_sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return random.random() < rate


def serve_trace_path() -> Optional[str]:
    """Where the serving trace would land, or None when telemetry is off
    or no ``GORDO_TPU_TELEMETRY_DIR`` is configured (the serving path,
    unlike a build, has no natural output directory to default to)."""
    from ..utils.env import env_str

    trace_dir = env_str(TRACE_DIR_ENV, None)
    if not enabled() or not trace_dir:
        return None
    # under a multi-worker server each process appends to its own
    # `serve_trace-<pid>.jsonl` — N workers sharing one append-mode file
    # interleave safely but RACE on rotation (two workers renaming the
    # same generation chain drop each other's spans); readers merge the
    # variants (trace_analysis.serve_trace_bases / the aggregator)
    return worker_sink_path(os.path.join(trace_dir, SERVE_TRACE_FILE))


def serve_recorder() -> Any:
    """The process-shared serving trace recorder (created on first use,
    one per sink path), or :data:`NULL_RECORDER` when tracing is off —
    callers can branch on ``.enabled`` to skip span construction
    entirely on the request hot path."""
    global _recorder
    path = serve_trace_path()
    if path is None:
        return NULL_RECORDER
    # lock-free steady-state path: the recorder only changes when the
    # telemetry env does, and this runs several times per request/batch
    # — serializing every request thread on the module lock is exactly
    # the class of hot-path cost this PR budgets away
    recorder = _recorder
    if recorder is not None and recorder.sink_path == path:
        return recorder
    global _atexit_registered
    with _lock:
        if _recorder is None or _recorder.sink_path != path:
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
            except OSError:
                return NULL_RECORDER
            if _recorder is not None:
                _recorder.close()
            # async sink: request threads enqueue, a writer thread does
            # the json+IO — the ≤2% scoring-overhead budget does not fit
            # a synchronous write+flush per span at request rate
            _recorder = SpanRecorder(
                sink_path=path, service="gordo-tpu-serve", async_sink=True
            )
            if not _atexit_registered:
                # the daemon writer dies with the interpreter; without
                # this, the last ~50ms of queued spans (including the
                # final requests before a SIGTERM) never reach disk
                _atexit_registered = True
                atexit.register(_close_at_exit)
        return _recorder


def _close_at_exit() -> None:
    with _lock:
        recorder = _recorder
    if recorder is not None:
        try:
            recorder.close()
        except Exception:  # noqa: BLE001 - interpreter is going down
            pass


def reset_serve_recorder() -> None:
    """Close and drop the shared recorder (tests, reload)."""
    global _recorder
    with _lock:
        if _recorder is not None:
            _recorder.close()
        _recorder = None


def export_request_trace(
    timing: SpanRecorder,
    *,
    span_id: str,
    parent_id: Optional[str],
    start: float,
    duration_s: float,
    attributes: Dict[str, Any],
    error: Optional[str] = None,
    profile: Optional[dict] = None,
) -> None:
    """
    Flush one finished request into the shared serving trace: the
    request's stage spans (recorded in-memory on ``timing`` with the
    request's trace id and ``default_parent_id = span_id``, so they
    already nest correctly), one ``request`` root span synthesized from
    the supplied interval, and — when the request was profiled — one
    ``profile`` span carrying the sampling profiler's aggregated
    self-time frames.

    No-ops (without constructing anything) when the serving sink is off.
    The request thread pays one list copy and one queue append; the
    ``request``/``profile`` span dicts are materialized on the sink's
    writer thread (:meth:`SpanRecorder.emit_deferred`) — dict assembly
    and ISO timestamp formatting are off the request's GIL time.
    """
    sink = serve_recorder()
    if not sink.enabled:
        return
    stage_spans = timing.finished()

    def build() -> list:
        end = start + max(0.0, duration_s)
        request_span = timing._span_dict(
            "request",
            span_id,
            parent_id,
            start,
            end,
            attributes,
            None,
            kind="server",
        )
        if error:
            request_span["status"] = {
                "status_code": "ERROR",
                "description": error,
            }
        spans = stage_spans
        if profile:
            spans = spans + [
                timing._span_dict(
                    "profile",
                    rand_hex(16),
                    span_id,
                    end - profile.get("duration_ms", 0.0) / 1000.0,
                    end,
                    profile,
                    None,
                )
            ]
        return spans + [request_span]

    sink.emit_deferred(build)
