"""
Offline analysis of the JSONL span traces (``serve_trace.jsonl`` /
``build_trace.jsonl``): the library behind ``gordo-tpu trace``.

The traces answer "where does the time go" only if something aggregates
them; this module turns a span stream into:

- **per-span-name latency distributions** (count, p50/p95/p99, total) —
  the serve trace's ``request``/``serve_batch``/stage spans, the build
  trace's ``build_phase``/``device_program`` spans;
- **the request breakdown**: for every ``request`` span, its child
  stage spans are joined back by ``(trace_id, parent_id)`` and the
  aggregate reports per-stage percentiles, each stage's share of median
  request walltime, and the **attribution coverage** — the fraction of
  request walltime the instrumented stages explain (the serving
  observability acceptance bar is ≥0.9; anything below means the
  pipeline has un-instrumented host work);
- **the critical path** of the median-ish request: its own stages,
  longest first;
- **top self-time frames** aggregated across ``profile`` spans (the
  sampling profiler's output), by (stage, function).

Everything is computed from span dicts alone — the analyses run on any
trace the :class:`~gordo_tpu.telemetry.SpanRecorder` wrote, rotated
generations included. Stdlib-only, like the whole telemetry package.
"""

import json
import os
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

#: serving stage spans whose parent is the request span; anything else
#: under a request (events, nested helper spans) is excluded from the
#: stage breakdown so shares stay a partition of walltime. The
#: streaming-plane spans are root spans with their own breakdown
#: (:func:`stream_breakdown`), never request stages.
_NON_STAGE_NAMES = (
    "request",
    "profile",
    "stream_ingest",
    "stream_score",
    "stream_emit",
)


def trace_bases(directory: str, base_name: str) -> List[str]:
    """Every base sink path for one logical trace in ``directory``: the
    shared spelling plus the per-worker ``<stem>-<pid>`` variants the
    worker-sink split writes (rotated generations ride each base)."""
    from .aggregate import sink_bases

    return sink_bases(directory, base_name)


def iter_trace_files(
    path: str,
    include_rotated: bool = True,
    since_ts: Optional[float] = None,
    window_index: Optional[Dict[str, Dict[str, Any]]] = None,
) -> List[str]:
    """The physical files of one trace sink, oldest first — the rollup
    reader's generation discovery (``aggregate.generation_files``, a
    directory listing rather than a ``.1``-exists probe walk: mid-
    rotation the ``.1`` slot is briefly empty while higher generations
    still hold bytes, and a probe walk goes blind to the whole chain).
    With ``since_ts``, rotated generations are skipped wholesale when
    provably pre-cutoff: by the rollup manifest's span-time window
    (``window_index``, keyed by basename — ``aggregate.sink_window_
    index``; authoritative when the generation was read ``complete``),
    else by mtime — a generation's mtime is its LAST write, so every
    span in it is older than the cutoff. This is what keeps ``gordo-tpu
    trace --since`` from re-parsing a week-old 256MiB corpus."""
    from .aggregate import generation_files

    if include_rotated:
        paths = generation_files(path)
    else:
        paths = [path] if os.path.exists(path) else []
    if since_ts is None:
        return paths
    kept = []
    for trace_path in paths:
        if trace_path != path:  # the live file always stays
            entry = (window_index or {}).get(os.path.basename(trace_path))
            if entry and entry.get("complete"):
                max_ts = entry.get("max_ts")
                if max_ts is not None and float(max_ts) < since_ts:
                    continue
                kept.append(trace_path)
                continue
            try:
                if os.path.getmtime(trace_path) < since_ts:
                    continue
            except OSError:
                continue
        kept.append(trace_path)
    return kept


def _span_end_ts(span: dict) -> Optional[float]:
    from .aggregate import parse_span_time

    return parse_span_time(span.get("end_time"))


def read_trace(
    path: str,
    include_rotated: bool = True,
    since_ts: Optional[float] = None,
    until_ts: Optional[float] = None,
    window_index: Optional[Dict[str, Dict[str, Any]]] = None,
) -> Iterator[dict]:
    """Yield span dicts from a JSONL trace file, oldest first across
    rotated generations (``p.N`` ... ``p.1``, then ``p``). Unparseable
    lines (a crash mid-write leaves at most one) are skipped. With a
    time window, spans ending outside [since_ts, until_ts] are dropped
    and pre-cutoff generations are never opened at all."""
    for trace_path in iter_trace_files(
        path, include_rotated, since_ts, window_index=window_index
    ):
        try:
            handle = open(trace_path)
        except OSError:
            continue  # rotated away between discovery and open
        with handle:
            for line in handle:
                line = line.strip()
                if not line:
                    continue
                try:
                    span = json.loads(line)
                except ValueError:
                    continue
                if not (isinstance(span, dict) and "name" in span):
                    continue
                if since_ts is not None or until_ts is not None:
                    end_ts = _span_end_ts(span)
                    if end_ts is None:
                        continue
                    if since_ts is not None and end_ts < since_ts:
                        continue
                    if until_ts is not None and end_ts > until_ts:
                        continue
                yield span


def read_traces(
    paths: List[str],
    since_ts: Optional[float] = None,
    until_ts: Optional[float] = None,
    window_index: Optional[Dict[str, Dict[str, Any]]] = None,
) -> Iterator[dict]:
    """Spans from several sink bases (N workers' traces), deduplicated
    by ``(trace_id, span_id)`` — the merge contract shared with the
    rollup reducer."""
    seen: set = set()
    for path in paths:
        for span in read_trace(
            path,
            since_ts=since_ts,
            until_ts=until_ts,
            window_index=window_index,
        ):
            context = span.get("context") or {}
            key = (context.get("trace_id", ""), context.get("span_id", ""))
            if key != ("", ""):
                if key in seen:
                    continue
                seen.add(key)
            yield span


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile of ``values`` (must be sorted)."""
    if not values:
        return 0.0
    rank = max(0, min(len(values) - 1, int(round(q * (len(values) - 1)))))
    return values[rank]


def _distribution(durations: List[float]) -> Dict[str, float]:
    durations = sorted(durations)
    return {
        "count": len(durations),
        "p50_ms": round(percentile(durations, 0.50), 3),
        "p95_ms": round(percentile(durations, 0.95), 3),
        "p99_ms": round(percentile(durations, 0.99), 3),
        "total_ms": round(sum(durations), 3),
    }


def summarize_spans(spans: Iterable[dict]) -> Dict[str, Dict[str, float]]:
    """Per-span-name duration distributions, skipping point events."""
    by_name: Dict[str, List[float]] = {}
    for span in spans:
        if span.get("kind") == "event":
            continue
        by_name.setdefault(span["name"], []).append(
            float(span.get("duration_ms", 0.0))
        )
    return {
        name: _distribution(durations)
        for name, durations in sorted(by_name.items())
    }


def request_breakdown(spans: Iterable[dict]) -> Optional[Dict[str, Any]]:
    """
    The per-stage attribution of the trace's ``request`` spans:

    ``stages`` maps stage name → distribution + ``share_of_p50`` (the
    stage's median as a fraction of the median request walltime);
    ``attribution_coverage`` is the summed share — how much of a median
    request the instrumented stages explain; ``critical_path`` lists the
    median request's own stages, longest first. None when the trace
    holds no request spans.
    """
    requests: List[dict] = []
    children: Dict[Tuple[str, str], List[dict]] = {}
    for span in spans:
        if span.get("kind") == "event":
            continue
        context = span.get("context") or {}
        if span["name"] == "request":
            requests.append(span)
        elif (
            span["name"] not in _NON_STAGE_NAMES
            and span.get("parent_id")
        ):
            children.setdefault(
                (context.get("trace_id", ""), span["parent_id"]), []
            ).append(span)
    if not requests:
        return None

    walltimes = sorted(float(r.get("duration_ms", 0.0)) for r in requests)
    p50_wall = percentile(walltimes, 0.50)
    stage_durations: Dict[str, List[float]] = {}
    # attribution coverage is computed PER REQUEST (own stages summed
    # over own walltime, then the median ratio) — aggregating means
    # against a median walltime overstates coverage whenever the
    # latency distribution is skewed, which under concurrency it
    # always is
    coverage_ratios: List[float] = []
    for request in requests:
        context = request.get("context") or {}
        trace_id = context.get("trace_id", "")
        own = children.get((trace_id, context.get("span_id", "")), [])
        for stage in own:
            stage_durations.setdefault(stage["name"], []).append(
                float(stage.get("duration_ms", 0.0))
            )
            # one level of nesting: spans recorded while a stage was
            # open (the micro-batcher's queue_wait / batch_* intervals
            # land inside `inference`) surface as stages of their own —
            # informational sub-segments, excluded from coverage below
            # (their time is already inside their parent stage's)
            stage_context = stage.get("context") or {}
            for nested in children.get(
                (trace_id, stage_context.get("span_id", "")), []
            ):
                stage_durations.setdefault(nested["name"], []).append(
                    float(nested.get("duration_ms", 0.0))
                )
        wall = float(request.get("duration_ms", 0.0))
        if wall > 0:
            explained = sum(
                float(stage.get("duration_ms", 0.0)) for stage in own
            )
            coverage_ratios.append(min(1.0, explained / wall))
    coverage = percentile(sorted(coverage_ratios), 0.50)

    stages: Dict[str, Dict[str, float]] = {}
    for name, durations in sorted(stage_durations.items()):
        dist = _distribution(durations)
        # the stage's conditional median over the median request
        # walltime — how much of a typical request this stage explains
        # when it occurs (queue_wait occurs only for batched requests)
        dist["share_of_p50"] = round(
            dist["p50_ms"] / p50_wall if p50_wall > 0 else 0.0, 4
        )
        stages[name] = dist

    # the critical path of the median request: the request whose
    # walltime sits at p50, its own stages longest-first
    median_request = min(
        requests,
        key=lambda r: abs(float(r.get("duration_ms", 0.0)) - p50_wall),
    )
    context = median_request.get("context") or {}
    own = children.get(
        (context.get("trace_id", ""), context.get("span_id", "")), []
    )
    critical_path = [
        {
            "stage": stage["name"],
            "duration_ms": round(float(stage.get("duration_ms", 0.0)), 3),
        }
        for stage in sorted(
            own, key=lambda s: float(s.get("duration_ms", 0.0)), reverse=True
        )
    ]

    return {
        "requests": len(requests),
        "walltime_p50_ms": round(p50_wall, 3),
        "walltime_p95_ms": round(percentile(walltimes, 0.95), 3),
        "walltime_p99_ms": round(percentile(walltimes, 0.99), 3),
        "stages": stages,
        "attribution_coverage": round(coverage, 4),
        "critical_path": critical_path,
    }


def stream_breakdown(spans: Iterable[dict]) -> Optional[Dict[str, Any]]:
    """
    The streaming plane's per-session critical path: for every stream id
    seen in the trace, the ``stream_ingest`` → ``stream_score`` →
    ``stream_emit`` stage distributions, the freshness numbers the score
    spans carry (ingest→scored lag p50/max), device time vs the cost
    model's prediction, and the row/shed accounting summed from span
    attributes. ``linked_ingests`` counts the OTel links score spans
    carry back to the ingests they drained — the fraction of flushes a
    trace reader can walk end-to-end. None when the trace holds no
    streaming spans.
    """
    stage_names = ("stream_ingest", "stream_score", "stream_emit")
    by_stream: Dict[str, Dict[str, Any]] = {}
    for span in spans:
        name = span.get("name")
        if name not in stage_names:
            continue
        attributes = span.get("attributes") or {}
        stream_id = str(attributes.get("stream") or "-")
        entry = by_stream.setdefault(
            stream_id,
            {
                "durations": {stage: [] for stage in stage_names},
                "device_ms": [],
                "predicted_device_ms": [],
                "lag_p50_ms": [],
                "lag_max_ms": 0.0,
                "rows_in": 0,
                "rows_scored": 0,
                "rows_failed": 0,
                "rows_shed": 0,
                "windows": 0,
                "events": 0,
                "linked_ingests": 0,
            },
        )
        entry["durations"][name].append(
            float(span.get("duration_ms", 0.0))
        )
        if name == "stream_ingest":
            entry["rows_in"] += int(attributes.get("rows", 0) or 0)
        elif name == "stream_score":
            scored = attributes.get("rows_scored")
            if scored is None:
                scored = attributes.get("rows", 0)
            entry["rows_scored"] += int(scored or 0)
            entry["rows_failed"] += int(
                attributes.get("rows_failed", 0) or 0
            )
            entry["rows_shed"] += int(attributes.get("shed", 0) or 0)
            entry["windows"] += int(attributes.get("windows", 0) or 0)
            entry["linked_ingests"] += len(span.get("links") or [])
            device = attributes.get("device_ms")
            if device is not None:
                entry["device_ms"].append(float(device))
            predicted = attributes.get("predicted_device_ms")
            if predicted is not None and float(predicted) >= 0.0:
                entry["predicted_device_ms"].append(float(predicted))
            lag_p50 = attributes.get("lag_p50_ms")
            if lag_p50 is not None:
                entry["lag_p50_ms"].append(float(lag_p50))
            lag_max = attributes.get("lag_max_ms")
            if lag_max is not None:
                entry["lag_max_ms"] = max(
                    entry["lag_max_ms"], float(lag_max)
                )
        else:
            entry["events"] += int(attributes.get("events", 0) or 0)
    if not by_stream:
        return None

    streams: Dict[str, Dict[str, Any]] = {}
    for stream_id, entry in sorted(by_stream.items()):
        stages = {
            stage: _distribution(durations)
            for stage, durations in entry["durations"].items()
            if durations
        }
        lag_p50s = sorted(entry["lag_p50_ms"])
        device = sorted(entry["device_ms"])
        predicted = sorted(entry["predicted_device_ms"])
        # the session's median critical path, in pipeline order: what
        # one row pays from ingest acceptance to the emitted event
        critical_path = [
            {
                "stage": stage,
                "p50_ms": stages[stage]["p50_ms"],
            }
            for stage in stage_names
            if stage in stages
        ]
        streams[stream_id] = {
            "stages": stages,
            "flushes": stages.get("stream_score", {}).get("count", 0),
            "rows_in": entry["rows_in"],
            "rows_scored": entry["rows_scored"],
            "rows_failed": entry["rows_failed"],
            "rows_shed": entry["rows_shed"],
            "windows": entry["windows"],
            "events": entry["events"],
            "linked_ingests": entry["linked_ingests"],
            "lag_p50_ms": round(percentile(lag_p50s, 0.50), 3),
            "lag_max_ms": round(entry["lag_max_ms"], 3),
            "device_p50_ms": round(percentile(device, 0.50), 3),
            "predicted_device_p50_ms": (
                round(percentile(predicted, 0.50), 3)
                if predicted
                else None
            ),
            "critical_path": critical_path,
        }
    return {
        "streams": streams,
        "totals": {
            "rows_in": sum(s["rows_in"] for s in streams.values()),
            "rows_scored": sum(
                s["rows_scored"] for s in streams.values()
            ),
            "rows_failed": sum(
                s["rows_failed"] for s in streams.values()
            ),
            "rows_shed": sum(s["rows_shed"] for s in streams.values()),
            "flushes": sum(s["flushes"] for s in streams.values()),
        },
    }


def prediction_accuracy(
    spans: Iterable[dict],
) -> Optional[Dict[str, Dict[str, Any]]]:
    """Predicted-vs-actual device time per program population: every
    span carrying both a measured ``device_ms`` and the cost model's
    ``predicted_device_ms`` stamp (serve batches, stream flushes) is one
    scored pair. ``error_p50``/``error_p95`` are relative-error
    percentiles (|predicted − actual| / actual); ``bias`` is the median
    predicted/actual ratio — above 1.0 the model over-predicts, below it
    under-predicts. The ``-1.0`` predicted sentinel (estimator
    unavailable) is excluded, so accuracy never averages in the spans
    that had no prediction at all."""
    by_key: Dict[str, Dict[str, list]] = {}
    for span in spans:
        attributes = span.get("attributes") or {}
        try:
            device = float(attributes.get("device_ms"))
            predicted = float(attributes.get("predicted_device_ms"))
        except (TypeError, ValueError):
            continue
        if device <= 0.0 or predicted < 0.0:
            continue
        key = str(attributes.get("program") or span["name"])
        entry = by_key.setdefault(key, {"ratios": [], "errors": []})
        entry["ratios"].append(predicted / device)
        entry["errors"].append(abs(predicted - device) / device)
    if not by_key:
        return None
    out: Dict[str, Dict[str, Any]] = {}
    for key, entry in sorted(by_key.items()):
        errors = sorted(entry["errors"])
        ratios = sorted(entry["ratios"])
        out[key] = {
            "count": len(errors),
            "error_p50": round(percentile(errors, 0.50), 4),
            "error_p95": round(percentile(errors, 0.95), 4),
            "bias": round(percentile(ratios, 0.50), 4),
        }
    return out


def top_profile_frames(
    spans: Iterable[dict], max_frames: int = 25
) -> List[Dict[str, Any]]:
    """Self-time frames aggregated across every ``profile`` span in the
    trace, by (stage, function), heaviest first."""
    totals: Dict[Tuple[str, str], Dict[str, float]] = {}
    for span in spans:
        if span["name"] != "profile":
            continue
        for frame in (span.get("attributes") or {}).get("frames", []):
            key = (frame.get("stage", "-"), frame.get("function", "?"))
            entry = totals.setdefault(key, {"self_ms": 0.0, "samples": 0})
            entry["self_ms"] += float(frame.get("self_ms", 0.0))
            entry["samples"] += int(frame.get("samples", 0))
    ranked = sorted(
        totals.items(), key=lambda kv: kv[1]["self_ms"], reverse=True
    )
    return [
        {
            "stage": stage,
            "function": function,
            "self_ms": round(entry["self_ms"], 3),
            "samples": entry["samples"],
        }
        for (stage, function), entry in ranked[:max_frames]
    ]


def analyze_trace(
    path: Any,
    since_ts: Optional[float] = None,
    until_ts: Optional[float] = None,
    window_index: Optional[Dict[str, Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """The full analysis document for one trace (a file path, or a list
    of sink bases to read-merge — the per-worker variants of one
    logical trace): span summaries, the request breakdown, the stream-
    session breakdown, and the aggregated profile — the JSON shape
    ``gordo-tpu trace --as-json``
    prints and the tests golden-check. ``since_ts``/``until_ts``
    restrict the analysis to a time window (``--since``/``--last``);
    ``window_index`` (``aggregate.sink_window_index``) lets rotated
    generations be skipped by recorded span window, not just mtime."""
    paths = [path] if isinstance(path, str) else list(path)
    spans = list(
        read_traces(
            paths,
            since_ts=since_ts,
            until_ts=until_ts,
            window_index=window_index,
        )
    )
    doc = {
        "trace": paths[0] if len(paths) == 1 else paths,
        "spans_read": len(spans),
        "span_summary": summarize_spans(spans),
        "request_breakdown": request_breakdown(spans),
        "stream_breakdown": stream_breakdown(spans),
        "prediction_accuracy": prediction_accuracy(spans),
        "profile_frames": top_profile_frames(spans),
    }
    if since_ts is not None or until_ts is not None:
        doc["window"] = {"since_ts": since_ts, "until_ts": until_ts}
    return doc


# -- rendering ---------------------------------------------------------------


def _table(rows: List[List[str]], header: List[str]) -> str:
    widths = [
        max(len(str(row[i])) for row in [header] + rows)
        for i in range(len(header))
    ]
    lines = [
        "  ".join(str(cell).ljust(widths[i]) for i, cell in enumerate(row))
        for row in [header, ["-" * w for w in widths]] + rows
    ]
    return "\n".join(line.rstrip() for line in lines)


def render_analysis(doc: Dict[str, Any]) -> str:
    """Human-readable rendering of :func:`analyze_trace`'s document."""
    trace = doc["trace"]
    if isinstance(trace, list):
        trace = ", ".join(trace)
    out: List[str] = [f"trace: {trace}  ({doc['spans_read']} spans)"]
    window = doc.get("window")
    if window:
        out.append(
            f"window: since_ts={window.get('since_ts')} "
            f"until_ts={window.get('until_ts')}"
        )

    summary = doc.get("span_summary") or {}
    if summary:
        out.append("\nSpan latency (ms):")
        out.append(
            _table(
                [
                    [
                        name,
                        dist["count"],
                        dist["p50_ms"],
                        dist["p95_ms"],
                        dist["p99_ms"],
                    ]
                    for name, dist in summary.items()
                ],
                ["span", "count", "p50", "p95", "p99"],
            )
        )

    breakdown = doc.get("request_breakdown")
    if breakdown:
        out.append(
            f"\nRequests: {breakdown['requests']}  "
            f"walltime p50={breakdown['walltime_p50_ms']}ms "
            f"p95={breakdown['walltime_p95_ms']}ms "
            f"p99={breakdown['walltime_p99_ms']}ms"
        )
        out.append("\nPer-stage breakdown:")
        out.append(
            _table(
                [
                    [
                        name,
                        dist["p50_ms"],
                        dist["p95_ms"],
                        f"{dist['share_of_p50'] * 100:.1f}%",
                    ]
                    for name, dist in breakdown["stages"].items()
                ],
                ["stage", "p50", "p95", "share of p50"],
            )
        )
        coverage = breakdown["attribution_coverage"]
        out.append(
            f"\nattribution coverage: {coverage * 100:.1f}% of median "
            "request walltime explained by instrumented stages"
        )
        if breakdown["critical_path"]:
            path_text = "  >  ".join(
                f"{step['stage']} {step['duration_ms']}ms"
                for step in breakdown["critical_path"]
            )
            out.append(f"critical path (median request): {path_text}")

    stream = doc.get("stream_breakdown")
    if stream:
        totals = stream.get("totals") or {}
        out.append(
            f"\nStream sessions: {len(stream.get('streams') or {})}  "
            f"flushes={totals.get('flushes', 0)} "
            f"rows in={totals.get('rows_in', 0)} "
            f"scored={totals.get('rows_scored', 0)} "
            f"failed={totals.get('rows_failed', 0)} "
            f"shed={totals.get('rows_shed', 0)}"
        )
        out.append(
            _table(
                [
                    [
                        stream_id,
                        entry["flushes"],
                        entry["rows_scored"],
                        entry["lag_p50_ms"],
                        entry["lag_max_ms"],
                        entry["device_p50_ms"],
                        (
                            entry["predicted_device_p50_ms"]
                            if entry["predicted_device_p50_ms"] is not None
                            else "-"
                        ),
                        entry["linked_ingests"],
                    ]
                    for stream_id, entry in (
                        stream.get("streams") or {}
                    ).items()
                ],
                [
                    "stream",
                    "flushes",
                    "rows",
                    "lag p50",
                    "lag max",
                    "device p50",
                    "pred p50",
                    "links",
                ],
            )
        )
        for stream_id, entry in (stream.get("streams") or {}).items():
            if entry.get("critical_path"):
                path_text = "  >  ".join(
                    f"{step['stage']} {step['p50_ms']}ms"
                    for step in entry["critical_path"]
                )
                out.append(
                    f"critical path ({stream_id}, median): {path_text}"
                )

    accuracy = doc.get("prediction_accuracy")
    if accuracy:
        out.append("\nPrediction accuracy (cost model vs measured device ms):")
        out.append(
            _table(
                [
                    [
                        program,
                        entry["count"],
                        f"{entry['error_p50'] * 100:.1f}%",
                        f"{entry['error_p95'] * 100:.1f}%",
                        entry["bias"],
                    ]
                    for program, entry in accuracy.items()
                ],
                ["program", "pairs", "err p50", "err p95", "bias"],
            )
        )

    frames = doc.get("profile_frames") or []
    if frames:
        out.append("\nTop self-time frames (sampling profiler):")
        out.append(
            _table(
                [
                    [f["stage"], f["function"], f["self_ms"], f["samples"]]
                    for f in frames[:15]
                ],
                ["stage", "function", "self ms", "samples"],
            )
        )
    return "\n".join(out)
