"""
The per-member fleet health ledger and the joined fleet-status surface.

The paper's operating premise is thousands of models watching machines
for months — and after PRs 3/6/7 an operator can watch a *build* (the
``build_status.json`` heartbeat), a *request* (serve traces + RED
metrics) and a *lifecycle cycle* (state.json), but still cannot answer
the fleet question: *which of my machines are degraded, drifting or
quarantined right now, and is the device actually full?* This module is
that answer:

- :class:`FleetHealthLedger` — one rolling health record per machine:
  serving counts (requests/errors/rows + a running residual mean),
  the latest drift verdict (the PR 6 windows' feature-shift σ and
  residual ratio), build provenance (revision, final loss,
  degraded/bisected flags from ``BuildMetadata.robustness``), and
  quarantine state. Fed by the serve path (``app._finalize`` + the
  fleet route), the fleet builder's span listener, and the lifecycle
  supervisor; persisted as atomic, heartbeat-throttled
  ``fleet_health.json`` snapshots beside the artifacts.
- Per-machine detail lives HERE, never in Prometheus labels (the PR 8
  cardinality contract): the scrape side gets bounded aggregates only —
  machines-by-state counts and a health-score histogram
  (``server/prometheus/metrics.py`` reads :func:`ledger_summaries` at
  scrape time).
- :func:`fleet_status_document` — the one joined operator view:
  ``build_status.json`` + ``fleet_plan.json`` (with the measured
  padding/HBM actuals the builder records back into the ledger) +
  lifecycle ``state.json``/``quarantine.json`` + the health ledger +
  device utilization, rendered by ``gordo-tpu fleet-status`` and served
  at ``/gordo/v0/<project>/fleet-health``.

Stdlib-only, like the rest of the package: the device-memory section is
*injected* by callers (``telemetry/device.py`` owns the jax probe).
"""

import contextlib
import datetime
import heapq
import json
import logging
import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Union

from ..utils.postfork import register_postfork_reset
from .recorder import _iso, enabled, worker_sink_path

logger = logging.getLogger(__name__)

#: the ledger snapshot written beside the artifacts (a builder dropping,
#: like build_status.json — serializer.is_builder_dropping knows it)
FLEET_HEALTH_FILE = "fleet_health.json"

#: the sharded snapshot layout beside it: past the monolithic-comfort
#: threshold the ledger splits its persistence into bounded per-shard
#: files under ``fleet_health.d/`` (``fleet_health-<pid>.d/`` per worker
#: — same worker-variant grammar as the sinks), so one noisy machine's
#: flush rewrites ONE shard, not 10k records. ``summary.json`` inside
#: the dir is the bounded read path: folded fleet summary + top-K
#: offenders, rewritten on every flush.
FLEET_HEALTH_SHARD_DIR = "fleet_health.d"
FLEET_HEALTH_SUMMARY_FILE = "summary.json"

#: shard count override: 0 (default) sizes adaptively — one shard while
#: the fleet fits a monolithic snapshot, then the next power of two of
#: ``machines / _SHARD_TARGET_MACHINES`` — any positive value pins it
HEALTH_SHARDS_ENV = "GORDO_TPU_HEALTH_SHARDS"
#: adaptive target: shards sized so a dirty-shard flush rewrites about
#: this many records regardless of fleet size (10k members -> 32 shards)
_SHARD_TARGET_MACHINES = 512
_MAX_SHARDS = 64
#: cached per-shard summaries go stale as breaker age-out cutoffs pass;
#: refresh untouched shards after this many seconds
_SUMMARY_MAX_AGE_S = 60.0
#: offenders kept per shard summary (consumers slice their own top-K)
_OFFENDER_CAP = 32

#: fleet-status bounding: past this many machines the joined document
#: stops inlining per-machine records by default (summary + top-K
#: offenders instead) — also the hard cap on one ``?machines=`` page
FLEET_STATUS_MAX_MACHINES_ENV = "GORDO_TPU_FLEET_STATUS_MAX_MACHINES"
DEFAULT_FLEET_STATUS_MAX_MACHINES = 500
#: offender rows carried by the bounded fleet-status health section
FLEET_STATUS_TOP_K_ENV = "GORDO_TPU_FLEET_STATUS_TOP_K"
DEFAULT_FLEET_STATUS_TOP_K = 10

#: master switch for the ledger (rides the telemetry master switch too)
FLEET_HEALTH_ENV = "GORDO_TPU_FLEET_HEALTH"
#: seconds between serving-count snapshot writes (state transitions —
#: drift verdicts, quarantines, build records — always force a write)
HEALTH_HEARTBEAT_ENV = "GORDO_TPU_HEALTH_HEARTBEAT"
DEFAULT_HEALTH_HEARTBEAT = 2.0
#: rows after which the rolling serving window decays (halves), so a
#: months-lived server's residual mean tracks the present, not January
HEALTH_WINDOW_ENV = "GORDO_TPU_HEALTH_WINDOW"
DEFAULT_HEALTH_WINDOW = 100_000

#: upper edges of the bounded health-score histogram the Prometheus side
#: exports — fixed, so the scrape cardinality is a constant
SCORE_BUCKETS = (0.25, 0.5, 0.75, 0.9, 1.0)

#: lifecycle state-file names, mirrored from ``gordo_tpu.lifecycle.state``
#: (the layering contract forbids telemetry -> lifecycle imports; a test
#: asserts the two spellings stay equal)
_LIFECYCLE_DIR = ".lifecycle"
_LIFECYCLE_STATE_FILE = "state.json"
_LIFECYCLE_QUARANTINE_FILE = "quarantine.json"


def health_enabled() -> bool:
    """Ledger on? (telemetry master switch AND ``GORDO_TPU_FLEET_HEALTH``,
    both default-on)."""
    from ..utils.env import env_bool

    return enabled() and env_bool(FLEET_HEALTH_ENV, True)


# -- the health math ----------------------------------------------------------


def _new_machine() -> Dict[str, Any]:
    return {
        "serving": {
            "requests": 0,
            "errors": 0,
            "rows": 0,
            "residual_mean": None,
            "last_request_at": None,
        },
        "drift": {
            "drifted": False,
            "reasons": [],
            "feature_shift_max": None,
            "residual_ratio": None,
            "window_rows": 0,
            "evaluated_at": None,
        },
        "build": {
            "revision": None,
            "final_loss": None,
            "degraded": False,
            "failed": False,
            "error": None,
            "bisects": 0,
            "retries": 0,
            "built_at": None,
        },
        "quarantine": {
            "active": False,
            "revision": None,
            "reasons": [],
            "since": None,
        },
        # the SERVING circuit breaker (gordo_tpu.serve.breaker): device
        # programs for this member kept failing and the engine tripped
        # it into quarantine (503 + Retry-After) — distinct from the
        # lifecycle `quarantine` section (a rolled-back canary)
        "breaker": {
            "state": "closed",
            "trips": 0,
            "cooldown_s": None,
            "reason": None,
            "updated_at": None,
        },
    }


#: seconds after which a persisted breaker record stops influencing the
#: displayed machine health: the record is written by the SERVING
#: process on transitions only, so a dead server (or a revision swapped
#: out from under its ledger) can never retire its own "open" — without
#: an age cutoff a machine would display quarantined forever while
#: serving 200s. Live breakers re-stamp on every transition (an actual
#: quarantine refreshes itself through its half-open probes).
BREAKER_STATE_MAX_AGE_S = 3600.0


def _live_breaker_state(
    machine: Dict[str, Any], max_age_s: float = BREAKER_STATE_MAX_AGE_S
) -> Optional[str]:
    """The machine's breaker state IF it is tripped and fresh enough to
    trust, else None. Stamps are wall-clock ISO strings (they must
    compare across processes and restarts, where monotonic can't
    reach); ``.get`` everywhere so pre-breaker snapshots read closed."""
    breaker = machine.get("breaker") or {}
    state = breaker.get("state")
    if state not in ("open", "half_open"):
        return None
    stamp = breaker.get("updated_at")
    if max_age_s and stamp:
        try:
            age = (
                datetime.datetime.now(datetime.timezone.utc)
                - datetime.datetime.fromisoformat(str(stamp))
            ).total_seconds()
        except ValueError:
            return state  # unparseable stamp: trust the state
        if age > max_age_s:
            return None
    return state


def health_score(machine: Dict[str, Any]) -> float:
    """One machine's health in [0, 1]: 1.0 healthy, descending through
    drift (−0.2), a degraded/failed build (−0.3), serving errors (up to
    −0.3, proportional to the error rate) and quarantine (−0.5).
    Deterministic in the record — the score is derived state, never
    stored ground truth."""
    score = 1.0
    if machine["quarantine"]["active"]:
        score -= 0.5
    breaker_state = _live_breaker_state(machine)
    if breaker_state == "open":
        score -= 0.4
    elif breaker_state == "half_open":
        score -= 0.2
    if machine["build"]["degraded"] or machine["build"]["failed"]:
        score -= 0.3
    if machine["drift"]["drifted"]:
        score -= 0.2
    serving = machine["serving"]
    if serving["requests"]:
        score -= min(0.3, 3.0 * serving["errors"] / serving["requests"])
    return round(max(0.0, min(1.0, score)), 4)


def machine_state(machine: Dict[str, Any]) -> str:
    """The machine's headline state, by severity: ``quarantined`` >
    ``degraded`` (failed/degraded build) > ``drifting`` > ``healthy``.
    A member whose serving circuit breaker is open (or probing
    half-open) IS quarantined — the serving-plane twin of a rolled-back
    canary."""
    if machine["quarantine"]["active"]:
        return "quarantined"
    if _live_breaker_state(machine) is not None:
        return "quarantined"
    if machine["build"]["degraded"] or machine["build"]["failed"]:
        return "degraded"
    if machine["drift"]["drifted"]:
        return "drifting"
    return "healthy"


def summarize(machines: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Bounded aggregates over the per-machine records: state counts,
    fleet-wide request/error totals, and the fixed-bucket health-score
    histogram (per-bin counts; the Prometheus collector cumulates)."""
    counts = {"healthy": 0, "degraded": 0, "drifting": 0, "quarantined": 0}
    requests = errors = breaker_tripped = 0
    score_sum = 0.0
    bins = [0] * len(SCORE_BUCKETS)
    for machine in machines.values():
        counts[machine_state(machine)] += 1
        requests += machine["serving"]["requests"]
        errors += machine["serving"]["errors"]
        if _live_breaker_state(machine) is not None:
            breaker_tripped += 1
        score = health_score(machine)
        score_sum += score
        for i, edge in enumerate(SCORE_BUCKETS):
            if score <= edge:
                bins[i] += 1
                break
    return {
        "machines": len(machines),
        **counts,
        "requests": requests,
        "errors": errors,
        "error_rate": round(errors / requests, 6) if requests else 0.0,
        # serving-breaker trips, counted here so bounded readers (the
        # lifecycle supervisor's rebuild feed) can skip the full
        # machine parse when nothing is tripped fleet-wide
        "breaker_tripped": breaker_tripped,
        "score_histogram": {
            "buckets": list(SCORE_BUCKETS),
            "counts": bins,
            # the histogram's sum: mean fleet health is one PromQL
            # division (sum / count), so it must be the sum of SCORES,
            # not the machine count
            "score_sum": round(score_sum, 4),
        },
    }


def _fold_summaries(summaries: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-shard :func:`summarize` outputs into one fleet summary.
    Exact, not approximate: every field is a sum (shards partition the
    machines), so the fold equals ``summarize`` over the union."""
    folded = summarize({})
    bins = folded["score_histogram"]["counts"]
    score_sum = 0.0
    for summary in summaries:
        if not isinstance(summary, dict):
            continue
        for key in (
            "machines",
            "healthy",
            "degraded",
            "drifting",
            "quarantined",
            "requests",
            "errors",
            "breaker_tripped",
        ):
            folded[key] += int(summary.get(key) or 0)
        histogram = summary.get("score_histogram") or {}
        score_sum += float(histogram.get("score_sum") or 0.0)
        for i, count in enumerate(histogram.get("counts") or ()):
            if i < len(bins):
                bins[i] += int(count)
    folded["error_rate"] = (
        round(folded["errors"] / folded["requests"], 6)
        if folded["requests"]
        else 0.0
    )
    folded["score_histogram"]["score_sum"] = round(score_sum, 4)
    return folded


def _offender_reason(machine: Dict[str, Any], state: str) -> Optional[str]:
    """The one-line why behind an unhealthy machine (what the renderer
    prints after the score)."""
    if state == "quarantined":
        reasons = machine.get("quarantine", {}).get("reasons") or []
        if reasons:
            return str(reasons[0])
        breaker = machine.get("breaker") or {}
        if breaker.get("reason"):
            return str(breaker["reason"])
        return None
    if state == "degraded":
        error = machine.get("build", {}).get("error")
        return str(error) if error else None
    reasons = machine.get("drift", {}).get("reasons") or []
    return str(reasons[0]) if reasons else None


def _offenders(
    machines: Dict[str, Dict[str, Any]], cap: int
) -> List[Dict[str, Any]]:
    """The ``cap`` unhealthiest machines as bounded rows (name, score,
    state, first reason) — what the fleet-status surfaces show instead
    of 10k inline records."""
    entries = []
    for name, machine in machines.items():
        state = machine_state(machine)
        if state == "healthy":
            continue
        entries.append(
            {
                "machine": name,
                "score": health_score(machine),
                "state": state,
                "reason": _offender_reason(machine, state),
            }
        )
    return heapq.nsmallest(
        cap, entries, key=lambda e: (e["score"], e["machine"])
    )


def _merge_offenders(
    pools: Iterable[List[Dict[str, Any]]], top_k: int
) -> List[Dict[str, Any]]:
    merged: List[Dict[str, Any]] = []
    for pool in pools:
        merged.extend(e for e in pool if isinstance(e, dict))
    return heapq.nsmallest(
        top_k, merged, key=lambda e: (e.get("score", 0.0), str(e.get("machine")))
    )


# -- the ledger ---------------------------------------------------------------


class NullLedger:
    """The do-nothing ledger (health telemetry off): every recording
    method is a no-op, so feed sites stay unconditional."""

    enabled = False
    path = None

    def record_request(self, *args, **kwargs):
        pass

    def record_scores(self, *args, **kwargs):
        pass

    def record_build(self, *args, **kwargs):
        pass

    def record_drift(self, *args, **kwargs):
        pass

    def record_quarantine(self, *args, **kwargs):
        pass

    def record_breaker(self, *args, **kwargs):
        pass

    def record_promotion(self, *args, **kwargs):
        pass

    def record_plan_accuracy(self, accuracy):
        pass

    def document(self):
        return None

    def bounded_document(self, top_k=10):
        return None

    def summary(self):
        return None

    def offenders(self, top_k=10):
        return []

    def machine_count(self):
        return 0

    def write(self, force=False):
        pass

    def flush(self):
        pass


NULL_LEDGER = NullLedger()


def _shard_dir_for(path: str) -> str:
    """``fleet_health.json`` -> ``fleet_health.d`` (pid suffix kept:
    ``fleet_health-123.json`` -> ``fleet_health-123.d``)."""
    stem, _ = os.path.splitext(path)
    return stem + ".d"


def _shard_file_name(shard: int, count: int) -> str:
    # the layout generation rides the name: a reshard (count change)
    # produces a disjoint file set, so stale-generation files are
    # recognizable and removable
    return f"shard-{shard:03d}of{count:03d}.json"


def _shard_files(shard_dir: str) -> List[str]:
    try:
        entries = sorted(os.listdir(shard_dir))
    except OSError:
        return []
    return [
        os.path.join(shard_dir, entry)
        for entry in entries
        if entry.startswith("shard-") and entry.endswith(".json")
    ]


class FleetHealthLedger:
    """The per-machine health ledger for one artifact directory.

    Thread-safe (request threads, dispatcher threads and the builder's
    dump pool all record concurrently); every snapshot write is an
    atomic dotted-tmp + ``os.replace``, throttled like the
    ``build_status.json`` heartbeat so serving traffic cannot turn the
    ledger into an IO load."""

    enabled = True

    def __init__(
        self,
        directory: Optional[str] = None,
        project: str = "",
        heartbeat_seconds: Optional[float] = None,
        window_rows: Optional[int] = None,
    ):
        self.directory = (
            os.path.normpath(directory) if directory is not None else None
        )
        # under a multi-worker server every process snapshots its OWN
        # `fleet_health-<pid>.json` — N workers atomically replacing one
        # shared path were silently overwriting each other's counts;
        # readers merge the variants (load_merged_health)
        self.path = (
            worker_sink_path(os.path.join(self.directory, FLEET_HEALTH_FILE))
            if self.directory is not None
            else None
        )
        # the sharded layout lives beside the monolithic spelling:
        # fleet_health.json -> fleet_health.d/ (worker variants keep
        # their pid suffix: fleet_health-<pid>.json -> fleet_health-<pid>.d/)
        self.shard_dir = (
            _shard_dir_for(self.path) if self.path is not None else None
        )
        self.project = project
        #: the process that built this ledger — ledger_for() compares it
        #: so a child forked AFTER construction (gunicorn --preload)
        #: rebuilds with its own pid-suffixed snapshot path instead of
        #: inheriting the parent's and clobbering it from N workers
        self._pid = os.getpid()
        from ..utils.env import env_float, env_int

        self.heartbeat_seconds = max(
            0.0,
            heartbeat_seconds
            if heartbeat_seconds is not None
            else (
                env_float(HEALTH_HEARTBEAT_ENV, DEFAULT_HEALTH_HEARTBEAT)
                or DEFAULT_HEALTH_HEARTBEAT
            ),
        )
        self.window_rows = max(
            1,
            window_rows
            if window_rows is not None
            else env_int(HEALTH_WINDOW_ENV, DEFAULT_HEALTH_WINDOW),
        )
        self._machines: Dict[str, Dict[str, Any]] = {}
        #: running (sum, rows) behind each machine's residual mean —
        #: kept out of the document (the document carries the mean)
        self._residuals: Dict[str, List[float]] = {}
        self._plan_accuracy: Optional[Dict[str, Any]] = None
        self._listeners: List[Callable[[dict], None]] = []
        self._lock = threading.Lock()
        self._write_lock = threading.Lock()
        self._last_write = 0.0
        # -- shard bookkeeping (all mutated under self._lock) --
        #: pinned shard count from the env (0 = adaptive)
        self._forced_shards = max(0, env_int(HEALTH_SHARDS_ENV, 0))
        self._shard_count = self._forced_shards or 1
        #: shard -> member names, maintained incrementally so flushing
        #: one dirty shard never walks the full fleet
        self._shard_members: Dict[int, set] = {}
        #: shards with unpersisted record changes
        self._dirty: set = set()
        #: shard layout changed (reshard): next flush rewrites the dir
        self._layout_changed = False
        #: per-shard cached {"summary", "offenders"} + refresh stamps —
        #: the scrape/fleet-status summary is a fold over these, O(S),
        #: recomputed only for shards touched since the last refresh
        self._summary_cache: Dict[int, Dict[str, Any]] = {}
        self._summary_stamp: Dict[int, float] = {}
        self._summary_dirty: set = set()

    # -- recording ----------------------------------------------------------

    def _shard_of(self, name: str) -> int:
        # crc32, NOT hash(): shard assignment must be stable across
        # processes and restarts (Python string hashing is randomized)
        return zlib.crc32(name.encode("utf-8")) % self._shard_count

    def _reshard_locked(self) -> None:
        """Grow the shard count to the adaptive target and rebuild the
        membership map (O(N), but only on power-of-two growth — the
        per-record path never walks the fleet)."""
        needed = (len(self._machines) + _SHARD_TARGET_MACHINES - 1) // (
            _SHARD_TARGET_MACHINES
        )
        count = 1 << max(0, needed - 1).bit_length()
        count = min(_MAX_SHARDS, max(1, count))
        if count <= self._shard_count:
            return
        self._shard_count = count
        self._shard_members = {}
        for name in self._machines:
            self._shard_members.setdefault(self._shard_of(name), set()).add(
                name
            )
        self._dirty.update(range(count))
        self._summary_cache.clear()
        self._summary_stamp.clear()
        self._summary_dirty.update(range(count))
        self._layout_changed = True

    def _machine(self, name: str) -> Dict[str, Any]:
        """The (create-once) record for ``name`` — called under
        ``self._lock`` by every mutator, so it is also where the
        machine's shard is marked dirty."""
        machine = self._machines.get(name)
        if machine is None:
            machine = self._machines[name] = _new_machine()
            if (
                not self._forced_shards
                and self._shard_count < _MAX_SHARDS
                and len(self._machines)
                > self._shard_count * _SHARD_TARGET_MACHINES
            ):
                self._reshard_locked()
            shard = self._shard_of(name)
            self._shard_members.setdefault(shard, set()).add(name)
        else:
            shard = self._shard_of(name)
        self._dirty.add(shard)
        self._summary_dirty.add(shard)
        return machine

    def machine_count(self) -> int:
        with self._lock:
            return len(self._machines)

    def record_request(
        self, machine: str, error: bool = False, count: int = 1
    ) -> None:
        """One served request (or ``count`` of them) for ``machine``;
        ``error`` marks server-side failures (5xx) — client errors are
        the client's problem, not the machine's health."""
        with self._lock:
            serving = self._machine(machine)["serving"]
            serving["requests"] += count
            if error:
                serving["errors"] += count
            serving["last_request_at"] = _iso(time.time())
        self.write()

    def record_scores(
        self,
        machine: str,
        rows: int,
        residual_mean: Optional[float] = None,
        write: bool = True,
    ) -> None:
        """Fold one scored window into the machine's rolling serving
        stats: ``rows`` scored, at mean reconstruction error
        ``residual_mean`` (raw-target-space mse, as ``fleet_scores``
        reports it). The window decays (halves) past ``window_rows`` so
        the mean tracks the present. ``write=False`` lets a caller
        batching many machines snapshot once at the end."""
        if rows <= 0:
            return
        with self._lock:
            serving = self._machine(machine)["serving"]
            serving["rows"] += int(rows)
            if residual_mean is not None and residual_mean == residual_mean:
                total, seen = self._residuals.get(machine, (0.0, 0))
                if seen >= self.window_rows:
                    # decay BEFORE folding the new batch, so recent
                    # windows outweigh history instead of averaging
                    # into it forever
                    total *= 0.5
                    seen = int(seen * 0.5)
                total += float(residual_mean) * rows
                seen += rows
                self._residuals[machine] = [total, seen]
                serving["residual_mean"] = round(total / seen, 8)
        if write:
            self.write()

    def record_build(self, machine: str, **fields: Any) -> None:
        """Build provenance for one machine: any of ``revision``,
        ``final_loss``, ``degraded``, ``failed``, ``error``, ``bisects``,
        ``retries``. A successful (re)build clears the failed/degraded
        flags unless the caller re-asserts them."""
        with self._lock:
            build = self._machine(machine)["build"]
            for key, value in fields.items():
                if key in build and value is not None:
                    build[key] = value
            if (
                not build["failed"]
                and not build["degraded"]
                and not fields.get("error")
            ):
                # a clean (re)build supersedes the previous failure's
                # evidence — a recovered machine must not read
                # 'degraded' in the console forever
                build["error"] = None
            build["built_at"] = _iso(time.time())
        # a thousand-machine fleet records a thousand of these — only
        # the state-changing ones (failures/degradations) force the
        # snapshot; healthy completions ride the heartbeat throttle
        self.write(
            force=bool(
                fields.get("failed")
                or fields.get("degraded")
                or fields.get("error")
            )
        )

    def record_drift(
        self,
        machine: str,
        drifted: bool,
        reasons: Any = (),
        stats: Optional[Dict[str, Any]] = None,
        write: bool = True,
    ) -> None:
        """The machine's latest drift verdict (the PR 6 windows).
        ``write=False`` lets the lifecycle loop record a whole fleet's
        verdicts under one forced snapshot (its own ``flush()``)."""
        stats = stats or {}
        with self._lock:
            drift = self._machine(machine)["drift"]
            drift["drifted"] = bool(drifted)
            drift["reasons"] = [str(r) for r in (reasons or [])]
            for key in ("feature_shift_max", "residual_ratio", "window_rows"):
                if key in stats:
                    drift[key] = stats[key]
            drift["evaluated_at"] = _iso(time.time())
        if write:
            self.write(force=True)

    def record_quarantine(
        self,
        machines: Any,
        revision: Optional[str] = None,
        reasons: Any = (),
    ) -> None:
        """Mark ``machines`` quarantined (their canary was rolled back)."""
        now = _iso(time.time())
        with self._lock:
            for name in machines:
                quarantine = self._machine(str(name))["quarantine"]
                quarantine["active"] = True
                quarantine["revision"] = revision
                quarantine["reasons"] = [str(r) for r in (reasons or [])][:5]
                quarantine["since"] = now
        self.write(force=True)

    def record_breaker(
        self,
        machine: str,
        state: str,
        trips: Optional[int] = None,
        cooldown_s: Optional[float] = None,
        reason: Optional[str] = None,
    ) -> None:
        """The member's serving circuit-breaker state (fed by the serve
        engine on every transition). An ``open`` record is what nominates
        the member to the lifecycle supervisor as a rebuild candidate
        (:func:`breaker_tripped_machines`); ``closed`` retires it."""
        now = _iso(time.time())
        with self._lock:
            record = self._machine(machine).setdefault(
                "breaker", _new_machine()["breaker"]
            )
            record["state"] = str(state)
            if trips is not None:
                record["trips"] = int(trips)
            record["cooldown_s"] = cooldown_s
            record["reason"] = str(reason)[:200] if reason else None
            record["updated_at"] = now
        # every breaker transition is a state change: force the snapshot
        self.write(force=True)

    def record_promotion(
        self, revision: Optional[str], machines: Any = ()
    ) -> None:
        """A promoted revision: the rebuilt ``machines`` leave
        quarantine, drift AND breaker state (their windows restart
        against the new artifacts), their build revision advances, and
        any degraded/failed flags clear — a rebuild that passed the
        gates and took traffic IS a successful build."""
        with self._lock:
            for name in machines:
                machine = self._machine(str(name))
                machine["quarantine"] = _new_machine()["quarantine"]
                machine["drift"] = _new_machine()["drift"]
                # a tripped serving breaker drove (or rode along with)
                # this rebuild: the fresh artifacts start closed — the
                # engine's in-process breaker reset the same way when
                # the hot-swap minted a new RevisionFleet
                machine["breaker"] = _new_machine()["breaker"]
                build = machine["build"]
                build["degraded"] = False
                build["failed"] = False
                build["error"] = None
                if revision is not None:
                    build["revision"] = revision
        self.write(force=True)

    def record_plan_accuracy(self, accuracy: Dict[str, Any]) -> None:
        """The build's predicted-vs-measured plan numbers (compiles,
        wall seconds, padding waste, HBM) — the ledger carries them so
        the joined fleet-status view can show plan accuracy without
        re-reading the whole span trace."""
        with self._lock:
            self._plan_accuracy = dict(accuracy)
        self.write(force=True)

    def add_listener(self, listener: Callable[[dict], None]) -> None:
        """Call ``listener(summary_dict)`` after every forced snapshot
        write (advisory, exceptions swallowed)."""
        with self._lock:
            self._listeners.append(listener)

    # -- the document -------------------------------------------------------

    def machine(self, name: str) -> Optional[Dict[str, Any]]:
        """One machine's record (deep-ish copy), with derived health."""
        with self._lock:
            machine = self._machines.get(name)
            if machine is None:
                return None
            machine = json.loads(json.dumps(machine))
        machine["health"] = {
            "score": health_score(machine),
            "state": machine_state(machine),
        }
        return machine

    def document(self) -> Dict[str, Any]:
        # one json.dumps pass under the lock (the cheapest consistent
        # snapshot of the nested records), the loads + summarize +
        # derived-health math OUTSIDE it — document() runs on whichever
        # request thread loses the heartbeat race, and holding the
        # shared lock through the full round-trip would stall every
        # concurrent record_* call behind one serialization
        with self._lock:
            payload = json.dumps(self._machines, default=str)
            plan_accuracy = (
                dict(self._plan_accuracy) if self._plan_accuracy else None
            )
        machines = json.loads(payload)
        for machine in machines.values():
            machine["health"] = {
                "score": health_score(machine),
                "state": machine_state(machine),
            }
        doc: Dict[str, Any] = {
            "version": 1,
            "project": self.project,
            "updated_at": _iso(time.time()),
            "machines": machines,
            "summary": summarize(machines),
        }
        if plan_accuracy is not None:
            doc["plan_accuracy"] = plan_accuracy
        return doc

    def _refresh_summaries_locked(self) -> None:
        """Recompute the per-shard summary cache for shards touched
        since the last refresh (or stale past the breaker age-out
        window). Caller holds ``self._lock``."""
        now = time.time()
        for shard in range(self._shard_count):
            if (
                shard not in self._summary_dirty
                and shard in self._summary_cache
                and now - self._summary_stamp.get(shard, 0.0)
                <= _SUMMARY_MAX_AGE_S
            ):
                continue
            names = self._shard_members.get(shard) or ()
            machines = {
                name: self._machines[name]
                for name in names
                if name in self._machines
            }
            self._summary_cache[shard] = {
                "summary": summarize(machines),
                "offenders": _offenders(machines, _OFFENDER_CAP),
            }
            self._summary_stamp[shard] = now
        self._summary_dirty.clear()

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            if self._shard_count > 1:
                # fold of per-shard cached summaries: O(shards + dirty)
                # — this is what keeps the Prometheus scrape flat as
                # the fleet grows
                self._refresh_summaries_locked()
                return _fold_summaries(
                    entry["summary"] for entry in self._summary_cache.values()
                )
            machines = dict(self._machines)
        return summarize(machines)

    def offenders(self, top_k: int = 10) -> List[Dict[str, Any]]:
        """The ``top_k`` unhealthiest machines as bounded rows."""
        with self._lock:
            if self._shard_count > 1:
                self._refresh_summaries_locked()
                pools = [
                    entry["offenders"]
                    for entry in self._summary_cache.values()
                ]
                return _merge_offenders(pools, top_k)
            machines = dict(self._machines)
        return _offenders(machines, top_k)

    def bounded_document(self, top_k: int = 10) -> Dict[str, Any]:
        """The summary-first view of this ledger: fleet summary, top-K
        offenders and the machine count — never the per-machine map.
        O(shards + dirty) however large the fleet is; what the bounded
        fleet-status path reads instead of :meth:`document`."""
        with self._lock:
            total = len(self._machines)
            plan_accuracy = (
                dict(self._plan_accuracy) if self._plan_accuracy else None
            )
            if self._shard_count > 1:
                self._refresh_summaries_locked()
                summary = _fold_summaries(
                    entry["summary"] for entry in self._summary_cache.values()
                )
                offenders = _merge_offenders(
                    [
                        entry["offenders"]
                        for entry in self._summary_cache.values()
                    ],
                    top_k,
                )
                machines = None
            else:
                machines = dict(self._machines)
        if machines is not None:
            summary = summarize(machines)
            offenders = _offenders(machines, top_k)
        doc: Dict[str, Any] = {
            "version": 1,
            "project": self.project,
            "updated_at": _iso(time.time()),
            "machines_total": total,
            "summary": summary,
            "offenders": offenders,
        }
        if plan_accuracy is not None:
            doc["plan_accuracy"] = plan_accuracy
        return doc

    # -- persistence --------------------------------------------------------

    def write(self, force: bool = False) -> None:
        """Atomically replace the snapshot (best-effort, throttled).
        Forced writes (state transitions) also notify listeners.

        Monolithic layout (one shard): the whole document replaces
        ``fleet_health.json`` exactly as it always has. Sharded layout:
        only the shards dirtied since the last flush are rewritten —
        one noisy machine costs one bounded shard file, not the fleet."""
        if self.path is None:
            return
        now = time.time()
        with self._write_lock:
            with self._lock:
                if not force and now - self._last_write < self.heartbeat_seconds:
                    return
                self._last_write = now
                listeners = list(self._listeners)
                sharded = self._shard_count > 1
            if sharded:
                summary = self._write_shards()
            else:
                doc = self.document()
                summary = doc["summary"]
                tmp = os.path.join(
                    os.path.dirname(self.path),
                    f".{FLEET_HEALTH_FILE}.tmp-{os.getpid()}",
                )
                try:
                    os.makedirs(os.path.dirname(self.path), exist_ok=True)
                    with open(tmp, "w") as f:
                        json.dump(doc, f, default=str)
                    os.replace(tmp, self.path)
                except OSError as exc:
                    logger.debug("fleet_health snapshot not written: %r", exc)
                    with contextlib.suppress(OSError):
                        os.remove(tmp)
                with self._lock:
                    self._dirty.clear()
                self._cleanup_shard_layout()
        if force and summary is not None:
            for listener in listeners:
                try:
                    listener(summary)
                except Exception:  # noqa: BLE001 - listeners are advisory
                    pass

    def _atomic_write(self, path: str, doc: Dict[str, Any]) -> None:
        tmp = os.path.join(
            os.path.dirname(path),
            f".{os.path.basename(path)}.tmp-{os.getpid()}",
        )
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
        except OSError:
            with contextlib.suppress(OSError):
                os.remove(tmp)
            raise

    def _write_shards(self) -> Optional[Dict[str, Any]]:
        """Flush the dirty shards (serialize under the lock, write
        outside it) plus the bounded ``summary.json``; returns the
        folded fleet summary. Caller holds ``self._write_lock``."""
        if self.shard_dir is None:
            return None
        with self._lock:
            count = self._shard_count
            dirty = sorted(self._dirty)
            self._dirty.clear()
            layout_changed = self._layout_changed
            self._layout_changed = False
            payloads = {}
            for shard in dirty:
                names = self._shard_members.get(shard) or ()
                payloads[shard] = json.dumps(
                    {
                        name: self._machines[name]
                        for name in sorted(names)
                        if name in self._machines
                    },
                    default=str,
                )
            plan_accuracy = (
                dict(self._plan_accuracy) if self._plan_accuracy else None
            )
            total = len(self._machines)
            self._refresh_summaries_locked()
            shard_summaries = {
                shard: entry["summary"]
                for shard, entry in self._summary_cache.items()
            }
            offender_pools = [
                entry["offenders"] for entry in self._summary_cache.values()
            ]
        summary = _fold_summaries(shard_summaries.values())
        offenders = _merge_offenders(offender_pools, _OFFENDER_CAP)
        stamp = _iso(time.time())
        current_names = {_shard_file_name(k, count) for k in range(count)}
        try:
            os.makedirs(self.shard_dir, exist_ok=True)
            if layout_changed:
                # a reshard re-homes every machine: drop files from the
                # previous layout so merge-on-read never sees a machine
                # in two generations of shards
                for entry in os.listdir(self.shard_dir):
                    if (
                        entry.startswith("shard-")
                        and entry.endswith(".json")
                        and entry not in current_names
                    ):
                        with contextlib.suppress(OSError):
                            os.remove(os.path.join(self.shard_dir, entry))
            for shard in dirty:
                machines = json.loads(payloads[shard])
                for machine in machines.values():
                    machine["health"] = {
                        "score": health_score(machine),
                        "state": machine_state(machine),
                    }
                shard_doc = {
                    "version": 1,
                    "kind": "fleet-health-shard",
                    "project": self.project,
                    "updated_at": stamp,
                    "shard": shard,
                    "shards": count,
                    "machines": machines,
                    "summary": shard_summaries.get(shard),
                }
                self._atomic_write(
                    os.path.join(
                        self.shard_dir, _shard_file_name(shard, count)
                    ),
                    shard_doc,
                )
            summary_doc: Dict[str, Any] = {
                "version": 1,
                "kind": "fleet-health-summary",
                "project": self.project,
                "updated_at": stamp,
                "shards": count,
                "machines_total": total,
                "summary": summary,
                "offenders": offenders,
            }
            if plan_accuracy is not None:
                summary_doc["plan_accuracy"] = plan_accuracy
            self._atomic_write(
                os.path.join(self.shard_dir, FLEET_HEALTH_SUMMARY_FILE),
                summary_doc,
            )
            # the shard layout is now authoritative: retire this
            # worker's monolithic spelling so merge-on-read can never
            # double-count the two layouts (the migration contract —
            # the legacy file is read once at restore, then gone)
            if self.path and os.path.exists(self.path):
                with contextlib.suppress(OSError):
                    os.remove(self.path)
        except OSError as exc:
            logger.debug("fleet_health shard flush failed: %r", exc)
        return summary

    def _cleanup_shard_layout(self) -> None:
        """Monolithic mode: remove a stale shard directory left by a
        previous (larger) incarnation, so readers never merge both."""
        if self.shard_dir is None or not os.path.isdir(self.shard_dir):
            return
        with contextlib.suppress(OSError):
            for entry in os.listdir(self.shard_dir):
                with contextlib.suppress(OSError):
                    os.remove(os.path.join(self.shard_dir, entry))
            os.rmdir(self.shard_dir)

    def flush(self) -> None:
        self.write(force=True)

    def restore(self, doc: Dict[str, Any]) -> None:
        """Adopt a previously persisted snapshot (a restarted server
        resumes its counts instead of starting the fleet 'healthy')."""
        if not isinstance(doc, dict) or not isinstance(
            doc.get("machines"), dict
        ):
            return
        template = _new_machine()
        with self._lock:
            for name, record in doc["machines"].items():
                machine = self._machine(str(name))
                for section in template:
                    incoming = record.get(section)
                    if isinstance(incoming, dict):
                        for key in template[section]:
                            if key in incoming:
                                machine[section][key] = incoming[key]
            if isinstance(doc.get("plan_accuracy"), dict):
                self._plan_accuracy = dict(doc["plan_accuracy"])

    def _load_own_snapshot(self) -> Optional[Dict[str, Any]]:
        """This worker's persisted state, whichever layout it left:
        the shard directory when it has files (newest flush wins per
        machine), else the legacy monolithic document — read ONCE here;
        the first sharded flush retires it."""
        if self.shard_dir:
            shard_docs = []
            for path in _shard_files(self.shard_dir):
                doc = _load_json(path)
                if isinstance(doc, dict) and isinstance(
                    doc.get("machines"), dict
                ):
                    shard_docs.append(doc)
            if shard_docs:
                shard_docs.sort(key=lambda d: str(d.get("updated_at") or ""))
                machines: Dict[str, Any] = {}
                for doc in shard_docs:
                    machines.update(doc["machines"])
                merged: Dict[str, Any] = {"machines": machines}
                summary_doc = _load_json(
                    os.path.join(self.shard_dir, FLEET_HEALTH_SUMMARY_FILE)
                )
                if isinstance(summary_doc, dict) and isinstance(
                    summary_doc.get("plan_accuracy"), dict
                ):
                    merged["plan_accuracy"] = summary_doc["plan_accuracy"]
                return merged
        return _load_json(self.path) if self.path else None


# -- the process-global registry ---------------------------------------------

_registry_lock = threading.Lock()
_ledgers: Dict[str, FleetHealthLedger] = {}


def _reset_after_fork() -> None:
    """Drop inherited ledgers in a freshly forked worker: each froze
    the PARENT's pid-suffixed snapshot path at construction, so N
    children writing through them would clobber one shared file — the
    gunicorn ``--preload`` collision the per-call ``_pid`` check in
    :func:`ledger_for` also guards (the reset makes the fresh start
    unconditional; the check stays as belt-and-braces). The child is
    single-threaded here and the inherited lock may be frozen
    mid-acquire, so rebind both without locking."""
    global _registry_lock, _ledgers
    _registry_lock = threading.Lock()
    # gt-lint: disable=lock-guard -- post-fork child is single-threaded;
    # taking the (possibly frozen-held) inherited lock could deadlock
    _ledgers = {}


register_postfork_reset(_reset_after_fork, name="telemetry.fleet_health.ledgers")


def ledger_for(directory: str, project: str = "") -> Any:
    """The (create-once) ledger for an artifact directory, or
    :data:`NULL_LEDGER` when health telemetry is off. One ledger per
    normalized path — the builder, the serve path and the lifecycle
    supervisor all feed the same record set for the same directory."""
    if not health_enabled():
        return NULL_LEDGER
    key = os.path.normpath(directory)
    ledger = _ledgers.get(key)
    if ledger is not None and ledger._pid == os.getpid():
        return ledger
    with _registry_lock:
        ledger = _ledgers.get(key)
        if ledger is not None and ledger._pid != os.getpid():
            # inherited across a fork: the snapshot path froze the
            # PARENT's pid, so every child writing through it would
            # clobber one shared file — exactly the collision the
            # worker-sink split exists to prevent. Rebuild per process.
            ledger = None
        if ledger is None:
            ledger = FleetHealthLedger(directory=key, project=project)
            # restore from the ledger's OWN snapshot (pid-suffixed
            # under worker sinks; shard dir when the last incarnation
            # was sharded): adopting another worker's snapshot would
            # double its counts once readers merge the variants
            persisted = ledger._load_own_snapshot()
            if isinstance(persisted, dict):
                ledger.restore(persisted)
            _ledgers[key] = ledger
    return ledger


def ledger_summaries() -> Dict[str, Dict[str, Any]]:
    """directory -> bounded summary for every live ledger (what the
    Prometheus fleet-health collector reads at scrape time)."""
    with _registry_lock:
        ledgers = dict(_ledgers)
    return {path: ledger.summary() for path, ledger in ledgers.items()}


def reset_ledgers() -> None:
    """Drop every live ledger (tests only)."""
    with _registry_lock:
        _ledgers.clear()


def _load_shard_unit(shard_dir: str) -> Optional[Dict[str, Any]]:
    """One worker's shard directory folded back into a single health
    document (machines union, newest flush wins; plan accuracy from
    ``summary.json``)."""
    shard_docs = []
    for path in _shard_files(shard_dir):
        doc = _load_json(path)
        if isinstance(doc, dict) and isinstance(doc.get("machines"), dict):
            shard_docs.append(doc)
    if not shard_docs:
        return None
    shard_docs.sort(key=lambda d: str(d.get("updated_at") or ""))
    machines: Dict[str, Any] = {}
    for doc in shard_docs:
        machines.update(doc["machines"])
    newest = shard_docs[-1]
    merged: Dict[str, Any] = {
        "version": 1,
        "project": newest.get("project", ""),
        "updated_at": newest.get("updated_at"),
        "machines": machines,
        "summary": summarize(machines),
    }
    summary_doc = _load_json(
        os.path.join(shard_dir, FLEET_HEALTH_SUMMARY_FILE)
    )
    if isinstance(summary_doc, dict) and isinstance(
        summary_doc.get("plan_accuracy"), dict
    ):
        merged["plan_accuracy"] = summary_doc["plan_accuracy"]
    return merged


def load_health(directory: str) -> Optional[Dict[str, Any]]:
    """The persisted shared-spelling health snapshot from ``directory``
    (the ``fleet_health.d/`` shard layout when present, else the
    monolithic ``fleet_health.json``), or None."""
    shard_dir = os.path.join(directory, FLEET_HEALTH_SHARD_DIR)
    if os.path.isdir(shard_dir):
        doc = _load_shard_unit(shard_dir)
        if doc is not None:
            return doc
    doc = _load_json(os.path.join(directory, FLEET_HEALTH_FILE))
    return doc if isinstance(doc, dict) else None


def health_snapshot_paths(directory: str) -> List[str]:
    """Every persisted monolithic health snapshot in ``directory``: the
    shared ``fleet_health.json`` plus per-worker
    ``fleet_health-<pid>.json`` variants (one grammar:
    ``aggregate.is_worker_variant``), sorted for determinism. Sharded
    workers don't appear here — see :func:`health_snapshot_units`."""
    from .aggregate import is_worker_variant

    try:
        entries = os.listdir(directory)
    except OSError:
        return []
    return [
        os.path.join(directory, entry)
        for entry in sorted(entries)
        if entry == FLEET_HEALTH_FILE
        or is_worker_variant(entry, FLEET_HEALTH_FILE)
    ]


def health_snapshot_units(directory: str) -> List[Dict[str, Any]]:
    """Every persisted health snapshot in ``directory``, one unit per
    WORKER: ``{"stem", "kind": "file"|"shards", "paths", "dir"}``. A
    worker that left both layouts (a crash between the shard flush and
    the legacy unlink) counts once — the shard directory wins, so the
    merge can never double its records."""
    from .aggregate import is_worker_variant

    try:
        entries = os.listdir(directory)
    except OSError:
        return []
    files: Dict[str, str] = {}
    shard_dirs: Dict[str, str] = {}
    for entry in sorted(entries):
        path = os.path.join(directory, entry)
        if entry == FLEET_HEALTH_FILE or is_worker_variant(
            entry, FLEET_HEALTH_FILE
        ):
            files[os.path.splitext(entry)[0]] = path
        elif (
            entry == FLEET_HEALTH_SHARD_DIR
            or is_worker_variant(entry, FLEET_HEALTH_SHARD_DIR)
        ) and os.path.isdir(path):
            shard_dirs[os.path.splitext(entry)[0]] = path
    units: List[Dict[str, Any]] = []
    for stem in sorted(set(files) | set(shard_dirs)):
        shard_dir = shard_dirs.get(stem)
        if shard_dir is not None:
            paths = _shard_files(shard_dir)
            if paths:
                units.append(
                    {
                        "stem": stem,
                        "kind": "shards",
                        "paths": paths,
                        "dir": shard_dir,
                    }
                )
                continue
        if stem in files:
            units.append(
                {
                    "stem": stem,
                    "kind": "file",
                    "paths": [files[stem]],
                    "dir": None,
                }
            )
    return units


def _load_unit_document(unit: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    if unit["kind"] == "shards":
        return _load_shard_unit(unit["dir"])
    doc = _load_json(unit["paths"][0])
    return doc if isinstance(doc, dict) else None


def _unit_summary(unit: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """A worker unit's bounded summary WITHOUT parsing its machines:
    ``summary.json`` for sharded units (constant-size however large the
    worker's fleet), the persisted document's own summary for monolithic
    units (whose size is bounded by the monolithic threshold anyway).
    Returns ``{"summary", "offenders"?, "machines_total"?, ...}``."""
    if unit["kind"] == "shards":
        doc = _load_json(
            os.path.join(unit["dir"], FLEET_HEALTH_SUMMARY_FILE)
        )
        if isinstance(doc, dict) and isinstance(doc.get("summary"), dict):
            return doc
        return None
    doc = _load_json(unit["paths"][0])
    if isinstance(doc, dict) and isinstance(doc.get("summary"), dict):
        return {
            "summary": doc["summary"],
            "machines_total": len(doc.get("machines") or {}),
            "updated_at": doc.get("updated_at"),
            "plan_accuracy": doc.get("plan_accuracy"),
        }
    return None


def _newest(records: List[Dict[str, Any]], stamp_key: str) -> Dict[str, Any]:
    """The record with the greatest ISO timestamp at ``stamp_key``
    (records with no stamp lose to any stamped one; ties keep the
    later-listed, i.e. the live document's)."""
    best = records[0]
    best_stamp = str(best.get(stamp_key) or "")
    for record in records[1:]:
        stamp = str(record.get(stamp_key) or "")
        if stamp >= best_stamp:
            best, best_stamp = record, stamp
    return best


#: per-section timestamp used to pick the authoritative worker for the
#: non-additive machine sections (state, not counts)
_SECTION_STAMPS = {
    "drift": "evaluated_at",
    "build": "built_at",
    "quarantine": "since",
    "breaker": "updated_at",
}


def merge_health_documents(
    docs: List[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """
    One fleet-health document out of N per-worker snapshots:

    - **serving counts are summed** (requests/errors/rows — each worker
      saw a disjoint slice of the traffic, so the fleet totals are the
      sums; the RED regression test pins aggregated == Σ per-worker);
    - the residual mean is the row-weighted mean of the workers' means;
    - **state sections** (drift verdicts, build provenance, quarantine)
      are not additive — the record with the newest section timestamp
      wins (every worker that observed the transition wrote the same
      facts, the newest is simply the most current);
    - derived health (score/state) and the bounded summary are
      recomputed over the merged records.
    """
    docs = [
        doc
        for doc in docs
        if isinstance(doc, dict) and isinstance(doc.get("machines"), dict)
    ]
    if not docs:
        return None
    merged_machines: Dict[str, Dict[str, Any]] = {}
    by_machine: Dict[str, List[Dict[str, Any]]] = {}
    for doc in docs:
        for name, record in doc["machines"].items():
            if isinstance(record, dict):
                by_machine.setdefault(str(name), []).append(record)
    for name, records in by_machine.items():
        machine = _new_machine()
        serving = machine["serving"]
        weighted_residual = 0.0
        residual_rows = 0
        for record in records:
            incoming = record.get("serving") or {}
            serving["requests"] += int(incoming.get("requests") or 0)
            serving["errors"] += int(incoming.get("errors") or 0)
            rows = int(incoming.get("rows") or 0)
            serving["rows"] += rows
            residual = incoming.get("residual_mean")
            if residual is not None and rows > 0:
                weighted_residual += float(residual) * rows
                residual_rows += rows
            stamp = incoming.get("last_request_at")
            if stamp and str(stamp) > str(serving["last_request_at"] or ""):
                serving["last_request_at"] = stamp
        if residual_rows:
            serving["residual_mean"] = round(
                weighted_residual / residual_rows, 8
            )
        for section, stamp_key in _SECTION_STAMPS.items():
            candidates = [
                record[section]
                for record in records
                if isinstance(record.get(section), dict)
            ]
            if candidates:
                chosen = _newest(candidates, stamp_key)
                for key in machine[section]:
                    if key in chosen:
                        machine[section][key] = chosen[key]
        machine["health"] = {
            "score": health_score(machine),
            "state": machine_state(machine),
        }
        merged_machines[name] = machine
    newest_doc = _newest(docs, "updated_at")
    merged: Dict[str, Any] = {
        "version": 1,
        "project": newest_doc.get("project", ""),
        "updated_at": newest_doc.get("updated_at"),
        "workers_merged": len(docs),
        "machines": merged_machines,
        "summary": summarize(merged_machines),
    }
    accuracy = [
        doc["plan_accuracy"]
        for doc in docs
        if isinstance(doc.get("plan_accuracy"), dict)
    ]
    if accuracy:
        merged["plan_accuracy"] = accuracy[-1]
    return merged


def load_merged_health(
    directory: str,
    live_documents: Optional[List[Dict[str, Any]]] = None,
    exclude_paths: Optional[List[str]] = None,
) -> Optional[Dict[str, Any]]:
    """The merged health view over every snapshot in ``directory``,
    optionally folding in live in-process documents — whose own snapshot
    paths go in ``exclude_paths`` so a worker's counts never merge with
    its own persisted copy (see :func:`fleet_status_document`)."""
    docs = list(live_documents or [])
    # exclusion is per WORKER (stem), not per file: a live ledger must
    # skip its own persisted copy whichever layout it last wrote
    excluded = {
        os.path.splitext(os.path.basename(p))[0]
        for p in (exclude_paths or [])
    }
    for unit in health_snapshot_units(directory):
        if unit["stem"] in excluded:
            continue
        doc = _load_unit_document(unit)
        if isinstance(doc, dict):
            docs.append(doc)
    if len(docs) == 1:
        only = docs[0]
        if "machines" in only and "summary" in only:
            return only
    return merge_health_documents(docs)


def breaker_tripped_machines(
    directory: str, max_age_s: float = 3600.0
) -> Dict[str, Dict[str, Any]]:
    """
    Machines whose SERVING circuit breaker is currently tripped (open or
    probing half-open), from the merged health snapshots under
    ``directory`` — the feed the lifecycle supervisor reads to nominate
    tripped members as rebuild candidates (the serve layer never imports
    lifecycle; the ledger is the arrow between them).

    ``max_age_s`` ignores stale trip records (the shared
    :func:`_live_breaker_state` cutoff): a dead server (or a revision
    swapped out from under its ledger) can never resolve its own
    record, and a forgotten ``open`` stamp must not drive rebuild
    canaries forever (the same reasoning as the SLO engine's
    ``firing_alerts(max_age_s=...)``).

    Bounded fast path: every worker's persisted summary carries a
    ``breaker_tripped`` count (a trip forces a flush, so the counts are
    current); when they all read zero the full machine parse — O(N)
    per lifecycle cycle at 10k members — is skipped entirely.
    """
    # (only when the caller's cutoff is at most the summaries' own —
    # a laxer cutoff, including 0 = "no cutoff", could admit records
    # the summaries already aged out)
    units = (
        health_snapshot_units(directory)
        if 0 < max_age_s <= BREAKER_STATE_MAX_AGE_S
        else []
    )
    if units:
        tripped_hint = 0
        for unit in units:
            summary_doc = _unit_summary(unit)
            summary = (summary_doc or {}).get("summary")
            count = (summary or {}).get("breaker_tripped")
            if count is None:
                # pre-upgrade snapshot without the count: can't prove
                # anything cheaply, fall through to the full read
                tripped_hint = -1
                break
            tripped_hint += int(count)
        if tripped_hint == 0:
            return {}
    doc = load_merged_health(directory)
    if not isinstance(doc, dict):
        return {}
    tripped: Dict[str, Dict[str, Any]] = {}
    for name, record in (doc.get("machines") or {}).items():
        if _live_breaker_state(record or {}, max_age_s=max_age_s) is None:
            continue
        tripped[str(name)] = dict((record or {}).get("breaker") or {})
    return tripped


# -- the joined fleet-status surface -----------------------------------------


def _load_json(path: str) -> Optional[Any]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _machine_selection(
    machines: Union[None, str, Iterable[str]],
) -> Tuple[Optional[str], Optional[List[str]]]:
    """Normalize the ``machines=`` selector: ``(kind, names)`` where
    kind is None (adaptive default), ``"none"``, ``"all"``, a state
    filter (``healthy``/``degraded``/``drifting``/``quarantined``/
    ``unhealthy``) or ``"names"``."""
    if machines is None:
        return None, None
    if isinstance(machines, str):
        token = machines.strip()
        low = token.lower()
        if low in ("", "none", "summary"):
            return "none", None
        if low == "all":
            return "all", None
        if low in ("healthy", "degraded", "drifting", "quarantined", "unhealthy"):
            return low, None
        return "names", [t.strip() for t in token.split(",") if t.strip()]
    return "names", [str(name) for name in machines]


def _select_machines(
    machines: Dict[str, Dict[str, Any]],
    kind: Optional[str],
    names: Optional[List[str]],
    offset: int,
    limit: int,
) -> Tuple[Dict[str, Dict[str, Any]], bool]:
    """Apply a normalized selector + page window to the merged machine
    map; returns (selected, truncated)."""
    if kind == "names":
        wanted = [n for n in (names or []) if n in machines]
        page = wanted[offset : offset + limit]
        return (
            {name: machines[name] for name in page},
            len(wanted) > offset + len(page),
        )
    if kind == "unhealthy":
        pool = [
            name
            for name in sorted(machines)
            if (machines[name].get("health") or {}).get("state") != "healthy"
        ]
    elif kind in ("healthy", "degraded", "drifting", "quarantined"):
        pool = [
            name
            for name in sorted(machines)
            if (machines[name].get("health") or {}).get("state") == kind
        ]
    else:  # "all"
        pool = sorted(machines)
    page = pool[offset : offset + limit]
    return (
        {name: machines[name] for name in page},
        len(pool) > offset + len(page),
    )


def _doc_offenders(
    machines: Dict[str, Dict[str, Any]], top_k: int
) -> List[Dict[str, Any]]:
    """Top-K offender rows from a merged document's machine map (whose
    records already carry derived ``health``)."""
    entries = []
    for name, record in machines.items():
        health = record.get("health") or {}
        state = health.get("state")
        if state in (None, "healthy"):
            continue
        entries.append(
            {
                "machine": name,
                "score": health.get("score", 0.0),
                "state": state,
                "reason": _offender_reason(record, state),
            }
        )
    return heapq.nsmallest(
        top_k, entries, key=lambda e: (e["score"], e["machine"])
    )


def fleet_status_document(
    directory: str,
    device: Optional[Dict[str, Any]] = None,
    programs: Optional[Dict[str, Any]] = None,
    serving: Optional[Dict[str, Any]] = None,
    stream: Optional[Dict[str, Any]] = None,
    machines: Union[None, str, Iterable[str]] = None,
    limit: Optional[int] = None,
    offset: int = 0,
) -> Dict[str, Any]:
    """
    The one joined operator view over a build+serve directory:

    - ``build`` — the live ``build_status.json`` heartbeat (PR 3);
    - ``plan`` — ``fleet_plan.json`` strategy/totals plus the measured
      plan-accuracy actuals recorded into the health ledger;
    - ``lifecycle`` — the supervisor's ``state.json`` phase/identities,
      quarantine record count, and most recent history events;
    - ``health`` — the per-machine ledger (live when this process holds
      one, else the persisted snapshot) and its bounded summary;
    - ``device`` — injected device-utilization stats (memory +
      compile-cache counters; ``telemetry.device.utilization_snapshot``)
    - ``programs`` — injected serving program-cache stats.
    - ``serving`` — injected serve-engine stats (batch/shed counters and
      the precision ladder: per-precision coalesce counts, degrade
      counter, cached precision-parity gate reports).
    - ``stream`` — injected streaming-plane stats
      (``gordo_tpu.stream.plane.stream_plane_section``, like the other
      injected sections — telemetry never imports the plane):
      session/subscriber counts, the summed zero-gap row accounting,
      score-lag and watermark-delay freshness, flush/lag percentiles.

    Sections degrade to None independently: a build dir with no
    lifecycle state still joins, a serve dir with no plan still joins.

    The health section is BOUNDED at scale: per-machine records are
    inlined only while the fleet fits ``GORDO_TPU_FLEET_STATUS_MAX_MACHINES``
    (default 500); past that the section carries the summary, the
    machine count and the top-K offenders. ``machines=`` selects
    explicitly — ``"all"`` / a state name / ``"unhealthy"`` / a
    comma-separated name list / ``"none"`` — with ``limit``/``offset``
    paging (capped at the same knob).
    """
    from .progress import load_status

    directory = os.path.normpath(directory)
    root = os.path.dirname(directory)
    doc: Dict[str, Any] = {
        "version": 1,
        "directory": directory,
        "revision": os.path.basename(directory),
        "generated_at": _iso(time.time()),
    }
    doc["build"] = load_status(directory)

    plan = _load_json(os.path.join(directory, "fleet_plan.json"))

    from ..utils.env import env_int

    kind, names = _machine_selection(machines)
    max_inline = max(
        1,
        env_int(
            FLEET_STATUS_MAX_MACHINES_ENV, DEFAULT_FLEET_STATUS_MAX_MACHINES
        ),
    )
    top_k = max(
        1, env_int(FLEET_STATUS_TOP_K_ENV, DEFAULT_FLEET_STATUS_TOP_K)
    )
    page_limit = (
        max_inline if limit is None else max(0, min(int(limit), max_inline))
    )
    page_offset = max(0, int(offset or 0))

    # the health view is a MERGE: this process's live ledger (its own
    # snapshot excluded by worker stem — a worker must not double-count
    # with its persisted copy) plus every other worker's snapshots.
    # Bounded-first: when no per-machine records are wanted (or the
    # fleet outgrew the inline threshold) and a single source can
    # answer, the summary path never materializes the machine map —
    # O(shards), not O(fleet).
    ledger = _ledgers.get(directory)
    own_stems = set()
    if ledger is not None and ledger.path:
        own_stems.add(os.path.splitext(os.path.basename(ledger.path))[0])
    units = [
        unit
        for unit in health_snapshot_units(directory)
        if unit["stem"] not in own_stems
    ]
    single_live = ledger is not None and not units

    bounded_doc: Optional[Dict[str, Any]] = None
    health_doc: Optional[Dict[str, Any]] = None
    if single_live and (
        kind == "none"
        or (kind is None and ledger.machine_count() > max_inline)
    ):
        bounded_doc = ledger.bounded_document(top_k)
    elif (
        kind in (None, "none")
        and ledger is None
        and len(units) == 1
        and units[0]["kind"] == "shards"
    ):
        candidate = _unit_summary(units[0])
        if candidate is not None and (
            kind == "none"
            or int(candidate.get("machines_total") or 0) > max_inline
        ):
            bounded_doc = candidate
    if bounded_doc is None:
        live_docs = [ledger.document()] if ledger is not None else []
        own_paths = (
            [ledger.path] if ledger is not None and ledger.path else []
        )
        health_doc = load_merged_health(
            directory, live_documents=live_docs, exclude_paths=own_paths
        )

    accuracy_source = (
        bounded_doc if bounded_doc is not None else (health_doc or {})
    )
    if isinstance(plan, dict):
        doc["plan"] = {
            "strategy": plan.get("strategy"),
            "totals": plan.get("totals"),
            "accuracy": accuracy_source.get("plan_accuracy"),
        }
    else:
        doc["plan"] = None

    state = _load_json(
        os.path.join(root, _LIFECYCLE_DIR, _LIFECYCLE_STATE_FILE)
    )
    quarantine = _load_json(
        os.path.join(root, _LIFECYCLE_DIR, _LIFECYCLE_QUARANTINE_FILE)
    )
    if isinstance(state, dict):
        doc["lifecycle"] = {
            "phase": state.get("phase"),
            "serving_revision": state.get("serving_revision"),
            "canary_revision": state.get("canary_revision"),
            "stale": state.get("stale") or [],
            "quarantine_records": (
                len(quarantine) if isinstance(quarantine, list) else 0
            ),
            "history": (state.get("history") or [])[-5:],
        }
    else:
        doc["lifecycle"] = None

    if bounded_doc is not None:
        total = int(bounded_doc.get("machines_total") or 0)
        doc["health"] = {
            "summary": bounded_doc.get("summary"),
            "machines": None,
            "machines_total": total,
            "machines_truncated": total > 0,
            "top_offenders": (bounded_doc.get("offenders") or [])[:top_k],
            "updated_at": bounded_doc.get("updated_at"),
        }
    elif health_doc is not None:
        machines_all = health_doc.get("machines") or {}
        total = len(machines_all)
        section: Dict[str, Any] = {
            "summary": health_doc.get("summary"),
            "updated_at": health_doc.get("updated_at"),
            "machines_total": total,
            "top_offenders": _doc_offenders(machines_all, top_k),
        }
        if kind is None:
            # adaptive default: small fleets inline every record (the
            # document everyone always got); big ones get the bounded
            # summary + offenders and explicit selection on request
            if total <= max_inline:
                section["machines"] = machines_all
                section["machines_truncated"] = False
            else:
                section["machines"] = None
                section["machines_truncated"] = True
        elif kind == "none":
            section["machines"] = None
            section["machines_truncated"] = total > 0
        else:
            selected, truncated = _select_machines(
                machines_all, kind, names, page_offset, page_limit
            )
            section["machines"] = selected
            section["machines_offset"] = page_offset
            section["machines_truncated"] = truncated
        if health_doc.get("workers_merged"):
            section["workers_merged"] = health_doc["workers_merged"]
        doc["health"] = section
    else:
        doc["health"] = None
    # the SLO verdict joins the console: alert states from the engine's
    # persisted state machine (slo.py), summarized — budgets/burn rates
    # live in the full `gordo-tpu slo status` / /slo route document.
    # The state lives where the SINKS live (the configured telemetry
    # dir when set, else this directory) — resolved exactly as the /slo
    # route resolves it, so the two surfaces can never disagree
    from .slo import slo_directory, slo_section

    doc["slo"] = slo_section(slo_directory(directory) or directory)
    doc["device"] = device
    doc["programs"] = programs
    doc["serving"] = serving
    # the streaming plane joins the console like device/programs — an
    # injected live-process section, None wherever no plane is installed
    doc["stream"] = stream
    return doc


def render_fleet_status(doc: Dict[str, Any]) -> str:
    """Human rendering of the joined document (the ``fleet-status``
    CLI's table view)."""
    lines: List[str] = [
        f"Directory: {doc.get('directory', '-')}",
        f"Revision:  {doc.get('revision', '-')}",
    ]
    build = doc.get("build")
    if build:
        machines = build.get("machines") or {}
        lines.append(
            f"Build:     {build.get('state', '?')}"
            + (f" (phase: {build.get('phase')})" if build.get("phase") else "")
            + f" — {machines.get('completed', 0)}/{machines.get('total', 0)}"
            f" done, {machines.get('failed', 0)} failed"
        )
    else:
        lines.append("Build:     (no build_status.json)")
    plan = doc.get("plan")
    if plan and plan.get("totals"):
        totals = plan["totals"]
        accuracy = plan.get("accuracy") or {}
        lines.append(
            f"Plan:      {plan.get('strategy', '?')} — "
            f"{totals.get('buckets', 0)} bucket(s), "
            f"{totals.get('compiles', 0)} predicted compile(s), "
            f"waste {100.0 * float(totals.get('padding_waste') or 0.0):.1f}%"
        )
        if accuracy:
            measured = accuracy.get("measured_member_waste")
            hbm = accuracy.get("measured_hbm_peak_bytes")
            lines.append(
                "  actuals: "
                f"{accuracy.get('actual_compiles', '?')} compile(s), "
                f"fit {accuracy.get('actual_fit_s', '?')}s"
                + (
                    f", member waste {100.0 * float(measured):.1f}%"
                    if measured is not None
                    else ""
                )
                + (
                    f", HBM peak {int(hbm) / (1 << 20):.1f} MiB"
                    if hbm
                    else ""
                )
            )
    lifecycle = doc.get("lifecycle")
    if lifecycle:
        lines.append(
            f"Lifecycle: {lifecycle.get('phase', '?')} — "
            f"serving {lifecycle.get('serving_revision') or '-'}"
            + (
                f", canary {lifecycle['canary_revision']}"
                if lifecycle.get("canary_revision")
                else ""
            )
            + (
                f", {lifecycle.get('quarantine_records')} quarantine record(s)"
                if lifecycle.get("quarantine_records")
                else ""
            )
        )
    health = doc.get("health")
    if health and health.get("summary"):
        summary = health["summary"]
        lines.append(
            f"Health:    {summary.get('machines', 0)} machine(s) — "
            f"{summary.get('healthy', 0)} healthy, "
            f"{summary.get('drifting', 0)} drifting, "
            f"{summary.get('degraded', 0)} degraded, "
            f"{summary.get('quarantined', 0)} quarantined"
            f" (error rate {100.0 * float(summary.get('error_rate') or 0.0):.2f}%)"
        )
        total = health.get("machines_total")
        shown = health.get("machines")
        if health.get("machines_truncated") and total:
            lines.append(
                f"  (per-machine records elided at {total} members — "
                "select with --machines/?machines=)"
            )
        elif isinstance(shown, dict) and total and len(shown) < total:
            lines.append(
                f"  (showing {len(shown)} of {total} machine record(s))"
            )
        offenders = health.get("top_offenders")
        if offenders is None:
            # pre-upgrade documents: derive from the inline records
            machines = shown or {}
            offenders = [
                {
                    "machine": name,
                    "score": record["health"]["score"],
                    "state": record["health"]["state"],
                    "reason": _offender_reason(
                        record, record["health"]["state"]
                    ),
                }
                for name, record in machines.items()
                if record.get("health", {}).get("state") != "healthy"
            ]
            offenders = heapq.nsmallest(
                10, offenders, key=lambda e: (e["score"], e["machine"])
            )
        for entry in offenders:
            lines.append(
                f"  {entry.get('machine')}: {entry.get('state')} "
                f"(score {float(entry.get('score') or 0.0):.2f})"
                + (
                    f" — {entry['reason']}"
                    if entry.get("reason")
                    else ""
                )
            )
    else:
        lines.append("Health:    (no fleet_health.json)")
    slo = doc.get("slo")
    if slo:
        firing = slo.get("firing", 0)
        pending = slo.get("pending", 0)
        verdict = "inside SLO" if slo.get("ok", True) else "BURNING"
        lines.append(
            f"SLO:       {verdict} — {firing} firing, {pending} pending "
            f"alert(s)"
        )
        for name, remaining in sorted((slo.get("budgets") or {}).items()):
            lines.append(
                f"  {name}: {100.0 * float(remaining):.1f}% budget remaining"
            )
    device = doc.get("device")
    if device:
        memory = device.get("memory")
        if memory and memory.get("available"):
            lines.append(
                f"Device:    {memory.get('measured_devices', 0)} device(s) — "
                f"{memory.get('bytes_in_use', 0) / (1 << 20):.1f} MiB in use, "
                f"peak {memory.get('peak_bytes_in_use', 0) / (1 << 20):.1f} MiB"
                + (
                    f" ({100.0 * memory['utilization']:.1f}% of limit)"
                    if memory.get("utilization") is not None
                    else ""
                )
            )
        else:
            lines.append("Device:    memory stats unavailable on this backend")
        for kind, counters in sorted(
            (device.get("compile_cache") or {}).items()
        ):
            rate = counters.get("hit_rate")
            lines.append(
                f"  {kind} programs: {counters.get('compiles', 0)} compile(s), "
                f"{counters.get('cache_hits', 0)} cache hit(s)"
                + (f" ({100.0 * rate:.1f}% hit rate)" if rate is not None else "")
            )
        persistent = device.get("persistent_cache")
        if persistent:
            lines.append(
                f"  persistent cache: {persistent.get('entries', 0)} entr"
                f"{'y' if persistent.get('entries', 0) == 1 else 'ies'}, "
                f"{persistent.get('bytes', 0) / (1 << 20):.1f} MiB "
                f"({persistent.get('path')})"
            )
    programs = doc.get("programs")
    if programs:
        lines.append(
            f"Programs:  {programs.get('programs', 0)} cached jit entr"
            f"{'y' if programs.get('programs', 0) == 1 else 'ies'}, "
            f"{programs.get('signatures', 0)} compiled signature(s)"
        )
        by_precision = programs.get("by_precision")
        if by_precision:
            lines.append(
                "  by precision: "
                + ", ".join(
                    f"{prec}={count}"
                    for prec, count in sorted(by_precision.items())
                )
            )
    serving = doc.get("serving")
    if serving:
        precision = serving.get("precision") or {}
        coalesced = precision.get("coalesced") or {}
        gates = [
            g for g in serving.get("gates", []) if isinstance(g, dict)
        ]
        lines.append(
            f"Serving:   precision={precision.get('config', 'f32')}"
            + (
                " — coalesced "
                + ", ".join(
                    f"{p}={n}" for p, n in sorted(coalesced.items())
                )
                if coalesced
                else ""
            )
            + (
                f", {serving.get('precision_degraded', 0)} degraded req(s)"
                if serving.get("precision_degraded")
                else ""
            )
        )
        for gate in gates:
            lines.append(
                f"  gate {gate.get('precision')}: "
                f"{'PASS' if gate.get('passed') else 'FAIL — degraded to f32'}"
                + (
                    f" (agreement {gate.get('agreement_min'):.4f})"
                    if gate.get("agreement_min") is not None
                    else ""
                )
            )
        breaker = serving.get("breaker") or {}
        if breaker.get("open") or breaker.get("half_open") or breaker.get(
            "trips"
        ):
            lines.append(
                f"  breakers: {breaker.get('open', 0)} open, "
                f"{breaker.get('half_open', 0)} half-open "
                f"({breaker.get('trips', 0)} trip(s) total)"
            )
            for member in breaker.get("members", [])[:5]:
                lines.append(
                    f"    {member.get('member')}: {member.get('state')}"
                    + (
                        f", cooldown {member.get('cooldown_s')}s"
                        if member.get("cooldown_s")
                        else ""
                    )
                )
    stream = doc.get("stream")
    if stream:
        accounting = stream.get("accounting") or {}
        lag = stream.get("lag") or {}
        lag_p95 = lag.get("lag_p95_ms")
        lines.append(
            f"Stream:    {stream.get('sessions_active', 0)} active "
            f"session(s), {stream.get('subscribers', 0)} subscriber(s)"
            + (" — DRAINING" if stream.get("draining") else "")
        )
        lines.append(
            f"  rows: {accounting.get('rows_in', 0)} in, "
            f"{accounting.get('rows_scored', 0)} scored, "
            f"{accounting.get('rows_failed', 0)} failed, "
            f"{accounting.get('rows_pending', 0)} pending, "
            f"{accounting.get('rows_shed', 0)} shed "
            f"(gap {accounting.get('gap', 0)})"
        )
        lines.append(
            f"  freshness: lag p95 "
            + (f"{lag_p95:g}ms" if lag_p95 is not None else "-")
            + (
                f", watermark delay {lag['watermark_delay_max_ms']:g}ms"
                if lag.get("watermark_delay_max_ms") is not None
                else ""
            )
            + (
                f", {stream['quarantined_machines']} quarantined machine(s)"
                if stream.get("quarantined_machines")
                else ""
            )
        )
    return "\n".join(lines)
