"""
The build-telemetry span recorder.

The reference system's observability was Kubernetes': one pod per model
build means ``argo get`` shows per-machine phase, duration and retries
for free. The chip-fan-out build collapses thousands of machines into
one process, so the same visibility has to be *data* the process emits:
this module records named spans (wall-clock intervals with attributes)
and point events into an in-memory list and an optional JSONL sink,
shaped like OpenTelemetry span dicts so a real OTLP exporter can be
bolted on later without touching the instrumentation sites.

Stdlib-only by design — the recorder is imported by the training hot
path (models/training.py, parallel/fleet.py) and must never drag server
or metrics dependencies into it. Prometheus export happens via
listeners the *builder* registers (parallel/fleet_build.py), keeping
the dependency arrow pointing outward.

Two activation models coexist:

- a process-global recorder (:func:`activate` / :func:`get_recorder`)
  used by the fleet build, so deep call sites (the trainer's device
  programs) record without threading a recorder argument through every
  layer. The default is :data:`NULL_RECORDER`, whose spans cost a few
  hundred nanoseconds and record nothing.
- explicit per-object recorders (the model server builds one per
  request for its ``Server-Timing`` stages).

Compile-vs-run attribution: :func:`program_span` wraps jit entry
points. The first call per ``(program, key)`` — key includes the spec,
fit config and array shapes, i.e. the XLA compilation signature — is
attributed ``compile=True`` (jax traces+compiles synchronously inside
that first call); later calls with the same signature are steady-state
``compile=False`` runs. This is the cache-hit/miss signal future
compile-cache work needs.
"""

import contextlib
import datetime
import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, Hashable, List, Optional

TELEMETRY_ENV = "GORDO_TPU_TELEMETRY"
TRACE_DIR_ENV = "GORDO_TPU_TELEMETRY_DIR"


def enabled() -> bool:
    """Telemetry master switch: on unless ``GORDO_TPU_TELEMETRY`` is a
    falsy string (``0``/``false``/``off``/``no``)."""
    return os.getenv(TELEMETRY_ENV, "1").strip().lower() not in (
        "0",
        "false",
        "off",
        "no",
    )


def _iso(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).isoformat()


class SpanHandle:
    """The object a ``with recorder.span(...)`` block receives; lets the
    body attach attributes discovered mid-span (e.g. result counts)."""

    __slots__ = ("attributes",)

    def __init__(self, attributes: Dict[str, Any]):
        self.attributes = attributes

    def set(self, **attributes) -> "SpanHandle":
        self.attributes.update(attributes)
        return self


class NullRecorder:
    """The do-nothing recorder: spans yield a throwaway handle and
    record nothing. Shared process-wide default."""

    enabled = False
    trace_id = ""

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        yield SpanHandle({})

    def event(self, name: str, **attributes) -> None:
        pass

    def record(self, name: str, seconds: float, **attributes) -> None:
        pass

    def add_listener(self, listener: Callable[[dict], None]) -> None:
        pass

    def finished(self, name: Optional[str] = None) -> List[dict]:
        return []

    def durations(self) -> Dict[str, float]:
        return {}

    def close(self) -> None:
        pass


NULL_RECORDER = NullRecorder()


class SpanRecorder:
    """
    Span/event recorder: in-memory tree + optional JSONL sink.

    Thread-safe — the dump/data thread pools record spans concurrently;
    parent/child nesting is tracked per thread (a pool thread's spans
    are roots of their own subtree, which is the truth: they do not run
    inside the main thread's current span).

    Every finished span is appended to ``sink_path`` as one JSON line
    the instant it closes, so a killed build leaves a complete trace of
    everything that actually happened.
    """

    enabled = True

    def __init__(
        self,
        sink_path: Optional[str] = None,
        service: str = "gordo-tpu",
        retain_spans: Optional[bool] = None,
    ):
        self.trace_id = uuid.uuid4().hex
        self.service = service
        self.sink_path = sink_path
        self._sink = None
        self._lock = threading.Lock()
        # In-memory retention serves short-lived recorders (the server's
        # per-request Server-Timing, in-process tests). A sink-backed
        # BUILD recorder must not retain: a many-hour fleet build emits
        # an unbounded span stream that nothing in the build path reads
        # back — the JSONL sink and the listeners are its consumers.
        self.retain_spans = (
            retain_spans if retain_spans is not None else sink_path is None
        )
        self._spans: List[dict] = []
        self._listeners: List[Callable[[dict], None]] = []
        self._local = threading.local()

    # -- recording ----------------------------------------------------------

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        """Record the enclosed block as one span; exceptions mark the
        span ``ERROR`` (with the exception repr) and propagate."""
        handle = SpanHandle(dict(attributes))
        span_id = uuid.uuid4().hex[:16]
        stack = self._stack()
        parent_id = stack[-1] if stack else None
        stack.append(span_id)
        start = time.time()
        error: Optional[BaseException] = None
        try:
            yield handle
        except BaseException as exc:
            error = exc
            raise
        finally:
            stack.pop()
            end = time.time()
            self._record(
                self._span_dict(
                    name,
                    span_id,
                    parent_id,
                    start,
                    end,
                    handle.attributes,
                    error,
                )
            )

    def event(self, name: str, **attributes) -> None:
        """A point-in-time (zero-duration) record."""
        now = time.time()
        stack = self._stack()
        self._record(
            self._span_dict(
                name,
                uuid.uuid4().hex[:16],
                stack[-1] if stack else None,
                now,
                now,
                dict(attributes),
                None,
                kind="event",
            )
        )

    def record(self, name: str, seconds: float, **attributes) -> None:
        """An externally-timed interval as a finished span (ends now).

        For durations measured on ANOTHER thread's clock — e.g. a
        request handler folding the micro-batcher's shared stack/device
        stage times into its own Server-Timing — where a ``with span``
        block on this recorder would double-count the wait."""
        end = time.time()
        stack = self._stack()
        self._record(
            self._span_dict(
                name,
                uuid.uuid4().hex[:16],
                stack[-1] if stack else None,
                end - max(0.0, seconds),
                end,
                dict(attributes),
                None,
            )
        )

    def _span_dict(
        self,
        name,
        span_id,
        parent_id,
        start,
        end,
        attributes,
        error,
        kind="internal",
    ) -> dict:
        return {
            "name": name,
            "context": {"trace_id": self.trace_id, "span_id": span_id},
            "parent_id": parent_id,
            "kind": kind,
            "start_time": _iso(start),
            "end_time": _iso(end),
            "duration_ms": round((end - start) * 1000.0, 3),
            "status": {
                "status_code": "ERROR" if error is not None else "OK",
                **({"description": repr(error)} if error is not None else {}),
            },
            "attributes": attributes,
            "resource": {"service.name": self.service},
        }

    def _record(self, span: dict) -> None:
        with self._lock:
            if self.retain_spans:
                self._spans.append(span)
            if self.sink_path is not None:
                try:
                    if self._sink is None:
                        self._sink = open(self.sink_path, "a")
                    self._sink.write(json.dumps(span, default=str) + "\n")
                    self._sink.flush()
                except OSError:
                    # telemetry is advisory: a full/readonly volume must
                    # never fail the build it is describing
                    self.sink_path = None
                    self._sink = None
            listeners = list(self._listeners)
        for listener in listeners:
            try:
                listener(span)
            except Exception:  # noqa: BLE001 - listeners are advisory too
                pass

    # -- introspection ------------------------------------------------------

    def add_listener(self, listener: Callable[[dict], None]) -> None:
        """Call ``listener(span_dict)`` for every span/event as it
        finishes (the builder uses this for live Prometheus export)."""
        with self._lock:
            self._listeners.append(listener)

    def finished(self, name: Optional[str] = None) -> List[dict]:
        """Finished spans (optionally filtered by name), oldest first.
        Empty when ``retain_spans`` is off (the default for sink-backed
        recorders — read the JSONL sink instead)."""
        with self._lock:
            spans = list(self._spans)
        if name is not None:
            spans = [s for s in spans if s["name"] == name]
        return spans

    def durations(self) -> Dict[str, float]:
        """Total seconds per span name, in first-seen order."""
        totals: Dict[str, float] = {}
        for span in self.finished():
            if span["kind"] == "event":
                continue
            totals[span["name"]] = (
                totals.get(span["name"], 0.0) + span["duration_ms"] / 1000.0
            )
        return totals

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    pass
                self._sink = None


# -- the process-global recorder --------------------------------------------

_active: Any = NULL_RECORDER
_active_lock = threading.Lock()


def get_recorder():
    """The currently active recorder (:data:`NULL_RECORDER` when no
    build is being traced)."""
    return _active


@contextlib.contextmanager
def activate(recorder):
    """Install ``recorder`` as the process-global recorder for the
    enclosed block (the fleet build wraps itself in this)."""
    global _active
    with _active_lock:
        previous, _active = _active, recorder
    try:
        yield recorder
    finally:
        with _active_lock:
            _active = previous


# -- compile-vs-run attribution ---------------------------------------------

_seen_lock = threading.Lock()
_seen_programs: set = set()


def seen_program(key: Hashable) -> bool:
    """Register a program signature; True when it was already seen this
    process (→ the jit cache will hit and the call is a steady-state
    run, not a compile)."""
    with _seen_lock:
        if key in _seen_programs:
            return True
        _seen_programs.add(key)
        return False


def reset_seen_programs() -> None:
    """Forget all program signatures (tests only — real processes keep
    the set for the jit caches' lifetime, which is the process)."""
    with _seen_lock:
        _seen_programs.clear()


def program_span(program: str, key: Hashable, **attributes):
    """
    Span around one jit-program invocation, attributed ``compile=True``
    on the first call per signature and ``compile=False`` after.

    ``key`` must capture the full compilation signature — spec, fit
    config, and array shapes — exactly as the jit cache would.
    """
    compile_flag = not seen_program((program, key))
    return get_recorder().span(
        "device_program", program=program, compile=compile_flag, **attributes
    )
