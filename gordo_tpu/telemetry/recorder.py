"""
The build-telemetry span recorder.

The reference system's observability was Kubernetes': one pod per model
build means ``argo get`` shows per-machine phase, duration and retries
for free. The chip-fan-out build collapses thousands of machines into
one process, so the same visibility has to be *data* the process emits:
this module records named spans (wall-clock intervals with attributes)
and point events into an in-memory list and an optional JSONL sink,
shaped like OpenTelemetry span dicts so a real OTLP exporter can be
bolted on later without touching the instrumentation sites.

Stdlib-only by design — the recorder is imported by the training hot
path (models/training.py, parallel/fleet.py) and must never drag server
or metrics dependencies into it. Prometheus export happens via
listeners the *builder* registers (parallel/fleet_build.py), keeping
the dependency arrow pointing outward.

Two activation models coexist:

- a process-global recorder (:func:`activate` / :func:`get_recorder`)
  used by the fleet build, so deep call sites (the trainer's device
  programs) record without threading a recorder argument through every
  layer. The default is :data:`NULL_RECORDER`, whose spans cost a few
  hundred nanoseconds and record nothing.
- explicit per-object recorders (the model server builds one per
  request for its ``Server-Timing`` stages).

Compile-vs-run attribution: :func:`program_span` wraps jit entry
points. The first call per ``(program, key)`` — key includes the spec,
fit config and array shapes, i.e. the XLA compilation signature — is
attributed ``compile=True`` (jax traces+compiles synchronously inside
that first call); later calls with the same signature are steady-state
``compile=False`` runs. This is the cache-hit/miss signal future
compile-cache work needs.
"""

import collections
import contextlib
import datetime
import json
import os
import random
import threading
import time
from typing import Any, Callable, Dict, Hashable, List, Optional

TELEMETRY_ENV = "GORDO_TPU_TELEMETRY"
TRACE_DIR_ENV = "GORDO_TPU_TELEMETRY_DIR"
#: size-based trace-sink rotation: when a JSONL sink crosses this many
#: bytes it is rotated (``trace.jsonl`` -> ``trace.jsonl.1`` -> ...), so
#: a months-lived serving or lifecycle process can never fill the disk.
#: 0 disables rotation.
MAX_BYTES_ENV = "GORDO_TPU_TELEMETRY_MAX_BYTES"
#: rotated generations kept per sink (older ones are deleted)
KEEP_ENV = "GORDO_TPU_TELEMETRY_KEEP"
DEFAULT_MAX_BYTES = 256 * 1024 * 1024
DEFAULT_KEEP = 3

#: per-process sink split: when on, process-owned telemetry sinks
#: (``serve_trace.jsonl``, ``fleet_health.json``) get a ``-<pid>``
#: suffix so N gunicorn workers stop clobbering one shared path — the
#: aggregator (telemetry/aggregate.py) and every reader merge all
#: variants. Defaults to ON exactly when a multi-worker deployment is
#: already configured (``PROMETHEUS_MULTIPROC_DIR``, the same signal
#: prometheus_client keys worker fan-in on); single-process servers and
#: tests keep the unsuffixed spelling.
WORKER_SINKS_ENV = "GORDO_TPU_WORKER_SINKS"


def worker_sinks_enabled() -> bool:
    from ..utils.env import env_bool

    multi_worker = bool(
        os.environ.get("PROMETHEUS_MULTIPROC_DIR")
        or os.environ.get("prometheus_multiproc_dir")
    )
    return env_bool(WORKER_SINKS_ENV, multi_worker)


def worker_sink_path(path: str) -> str:
    """``serve_trace.jsonl`` -> ``serve_trace-<pid>.jsonl`` when worker
    sinks are on (the suffix sits before the extension so rotated
    generations keep their ``.N`` tail grammar)."""
    if not worker_sinks_enabled():
        return path
    stem, ext = os.path.splitext(path)
    return f"{stem}-{os.getpid()}{ext}"


def enabled() -> bool:
    """Telemetry master switch: on unless ``GORDO_TPU_TELEMETRY`` is a
    falsy string (``0``/``false``/``off``/``no``)."""
    from ..utils.env import env_bool

    return env_bool(TELEMETRY_ENV, True)


def _iso(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).isoformat()


def _env_size(name: str, default: int) -> int:
    # utils.env is the one shared GORDO_TPU_* numeric-knob parser (it
    # warns on invalid values); stdlib-only, so the telemetry package's
    # no-heavy-deps contract holds
    from ..utils.env import env_int

    return max(0, env_int(name, default))


#: id generator for trace/span ids — a PRNG seeded once from the OS,
#: NOT uuid4: ids only need uniqueness, and uuid4's per-call urandom
#: syscall costs ~20x more, which matters at one span id per request
#: stage on the serving hot path (GIL makes getrandbits effectively
#: atomic; ids are not security tokens)
_id_source = random.Random(int.from_bytes(os.urandom(16), "big"))


def rand_hex(chars: int = 32) -> str:
    """``chars`` lowercase hex characters of PRNG randomness (32 = a
    W3C trace id, 16 = a span id)."""
    return f"{_id_source.getrandbits(chars * 4):0{chars}x}"


class SpanHandle:
    """The object a ``with recorder.span(...)`` block receives; lets the
    body attach attributes discovered mid-span (e.g. result counts) and
    OTel-shaped links to spans in OTHER traces (the serving engine links
    each fused batch span to the request spans it coalesced)."""

    __slots__ = ("attributes", "links", "trace_id", "span_id")

    def __init__(
        self,
        attributes: Dict[str, Any],
        trace_id: str = "",
        span_id: str = "",
    ):
        self.attributes = attributes
        self.links: List[dict] = []
        #: this span's own identity (empty on the null recorder) — lets
        #: a producer hand its context to a LATER span in another trace
        #: that wants to link back (the stream ingest→flush links)
        self.trace_id = trace_id
        self.span_id = span_id

    def set(self, **attributes) -> "SpanHandle":
        self.attributes.update(attributes)
        return self

    def link(self, trace_id: str, span_id: str, **attributes) -> "SpanHandle":
        """Attach a link to a span in another trace (OTel link shape:
        a span context plus link attributes)."""
        self.links.append(
            {
                "context": {"trace_id": trace_id, "span_id": span_id},
                **({"attributes": attributes} if attributes else {}),
            }
        )
        return self


class NullRecorder:
    """The do-nothing recorder: spans yield a throwaway handle and
    record nothing. Shared process-wide default."""

    enabled = False
    trace_id = ""
    default_parent_id = None

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        yield SpanHandle({})

    def event(self, name: str, **attributes) -> None:
        pass

    def record(self, name: str, seconds: float, **attributes) -> None:
        pass

    def emit(self, span: dict) -> None:
        pass

    def flush(self) -> None:
        pass

    def add_listener(self, listener: Callable[[dict], None]) -> None:
        pass

    def finished(self, name: Optional[str] = None) -> List[dict]:
        return []

    def durations(self) -> Dict[str, float]:
        return {}

    def close(self) -> None:
        pass


NULL_RECORDER = NullRecorder()


class SpanRecorder:
    """
    Span/event recorder: in-memory tree + optional JSONL sink.

    Thread-safe — the dump/data thread pools record spans concurrently;
    parent/child nesting is tracked per thread (a pool thread's spans
    are roots of their own subtree, which is the truth: they do not run
    inside the main thread's current span).

    Every finished span is appended to ``sink_path`` as one JSON line
    the instant it closes, so a killed build leaves a complete trace of
    everything that actually happened.
    """

    enabled = True

    def __init__(
        self,
        sink_path: Optional[str] = None,
        service: str = "gordo-tpu",
        retain_spans: Optional[bool] = None,
        trace_id: Optional[str] = None,
        max_bytes: Optional[int] = None,
        keep: Optional[int] = None,
        async_sink: bool = False,
    ):
        #: explicit ``trace_id`` joins an existing trace (the server's
        #: per-request recorders adopt the request's W3C trace id so its
        #: stage spans land in the caller's trace); default is a fresh one
        self.trace_id = trace_id or rand_hex(32)
        #: parent for spans opened on a thread with no enclosing span —
        #: the per-request recorder points this at the request's root
        #: span id, so stage spans (and the batcher's externally-timed
        #: ``record()`` intervals) nest under the request span
        self.default_parent_id: Optional[str] = None
        self.service = service
        self.sink_path = sink_path
        #: async sink: spans queue to a background writer thread that
        #: batch-writes them — the mode the process-shared SERVING
        #: recorder runs in, where the recording threads are request
        #: threads and the ~50us of json+write+flush per span would be
        #: paid at request rate. Builds keep the synchronous default
        #: (every span durable the instant it closes, crash-complete).
        self.async_sink = bool(async_sink) and sink_path is not None
        if sink_path is not None:
            # rotation knobs and writer plumbing only matter with a
            # sink; the per-REQUEST in-memory recorders skip all of it
            # (two env reads + deque/event allocation per request add up)
            self.max_bytes = (
                max_bytes
                if max_bytes is not None
                else _env_size(MAX_BYTES_ENV, DEFAULT_MAX_BYTES)
            )
            self.keep = (
                keep if keep is not None else _env_size(KEEP_ENV, DEFAULT_KEEP)
            )
            self._queue: "collections.deque" = collections.deque(maxlen=20000)
            self._wakeup = threading.Event()
            self._write_lock = threading.Lock()
        else:
            self.max_bytes = max_bytes or 0
            self.keep = keep or 0
        self._writer: Optional[threading.Thread] = None
        self._closed = False
        self._sink = None
        self._lock = threading.Lock()
        # In-memory retention serves short-lived recorders (the server's
        # per-request Server-Timing, in-process tests). A sink-backed
        # BUILD recorder must not retain: a many-hour fleet build emits
        # an unbounded span stream that nothing in the build path reads
        # back — the JSONL sink and the listeners are its consumers.
        self.retain_spans = (
            retain_spans if retain_spans is not None else sink_path is None
        )
        self._spans: List[dict] = []
        self._listeners: List[Callable[[dict], None]] = []
        self._local = threading.local()

    # -- recording ----------------------------------------------------------

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        """Record the enclosed block as one span; exceptions mark the
        span ``ERROR`` (with the exception repr) and propagate."""
        span_id = rand_hex(16)
        handle = SpanHandle(dict(attributes), self.trace_id, span_id)
        stack = self._stack()
        parent_id = stack[-1] if stack else self.default_parent_id
        stack.append(span_id)
        start = time.time()
        error: Optional[BaseException] = None
        try:
            yield handle
        except BaseException as exc:
            error = exc
            raise
        finally:
            stack.pop()
            end = time.time()
            self._record(
                self._span_dict(
                    name,
                    span_id,
                    parent_id,
                    start,
                    end,
                    handle.attributes,
                    error,
                    links=handle.links or None,
                )
            )

    def event(self, name: str, **attributes) -> None:
        """A point-in-time (zero-duration) record."""
        now = time.time()
        stack = self._stack()
        self._record(
            self._span_dict(
                name,
                rand_hex(16),
                stack[-1] if stack else self.default_parent_id,
                now,
                now,
                dict(attributes),
                None,
                kind="event",
            )
        )

    def record(self, name: str, seconds: float, **attributes) -> None:
        """An externally-timed interval as a finished span (ends now).

        For durations measured on ANOTHER thread's clock — e.g. a
        request handler folding the micro-batcher's shared stack/device
        stage times into its own Server-Timing — where a ``with span``
        block on this recorder would double-count the wait."""
        end = time.time()
        stack = self._stack()
        self._record(
            self._span_dict(
                name,
                rand_hex(16),
                stack[-1] if stack else self.default_parent_id,
                end - max(0.0, seconds),
                end,
                dict(attributes),
                None,
            )
        )

    def emit(self, span: dict) -> None:
        """Record a pre-built span dict as-is (sink + listeners + retain).

        The request-trace export path uses this: per-request recorders
        are in-memory (cheap, no file handle per request); at response
        finalization their finished spans — already carrying the
        request's own trace id — are emitted into the process-shared
        serving sink in one pass."""
        self._record(span)

    def emit_deferred(self, build: Callable[[], List[dict]]) -> None:
        """Queue a zero-arg callable whose returned span dicts are
        materialized ON THE WRITER THREAD (async sinks only; falls back
        to immediate emission otherwise).

        The request-export hot path uses this so a request thread pays
        one deque append while dict assembly + json + IO happen off the
        request's GIL time — the difference between the serving trace
        costing ~100us and ~10us per request."""
        if self.async_sink and self.sink_path is not None:
            # gt-lint: disable=lock-guard -- deque.append/popleft are
            # GIL-atomic; the bounded deque IS the lock-free handoff to
            # the writer thread (locking here would serialize requests)
            self._queue.append(build)
            if self._writer is None:
                self._ensure_writer()
            elif len(self._queue) >= 2048:
                self._wakeup.set()
            return
        for span in build():
            self._record(span)

    def _span_dict(
        self,
        name,
        span_id,
        parent_id,
        start,
        end,
        attributes,
        error,
        kind="internal",
        links=None,
    ) -> dict:
        return {
            "name": name,
            "context": {"trace_id": self.trace_id, "span_id": span_id},
            "parent_id": parent_id,
            "kind": kind,
            "start_time": _iso(start),
            "end_time": _iso(end),
            "duration_ms": round((end - start) * 1000.0, 3),
            "status": {
                "status_code": "ERROR" if error is not None else "OK",
                **({"description": repr(error)} if error is not None else {}),
            },
            "attributes": attributes,
            **({"links": links} if links else {}),
            "resource": {"service.name": self.service},
        }

    def _record(self, span: dict) -> None:
        if self.async_sink and self.sink_path is not None:
            # the serving hot path: request threads pay one deque append
            # (~0.1us); the writer thread does the json encode + IO.
            # A bounded deque sheds oldest-first if the disk ever stalls
            # — advisory telemetry must never become backpressure.
            # gt-lint: disable=lock-guard -- deque.append/popleft are
            # GIL-atomic; the bounded deque IS the lock-free handoff to
            # the writer thread (locking here would serialize requests)
            self._queue.append(span)
            if self._writer is None:
                self._ensure_writer()
            elif len(self._queue) >= 2048:
                # deep backlog: wake the writer early rather than risk
                # the bounded deque shedding (the only signaling the
                # recording threads ever do — see _writer_loop)
                self._wakeup.set()
            if not self.retain_spans and not self._listeners:
                return
        with self._lock:
            if self.retain_spans:
                self._spans.append(span)
            if self.sink_path is not None and not self.async_sink:
                try:
                    self._ensure_sink_linked()
                    if self._sink is None:
                        self._sink = open(self.sink_path, "a")
                    self._sink.write(json.dumps(span, default=str) + "\n")
                    self._sink.flush()
                    if self.max_bytes and self._sink.tell() >= self.max_bytes:
                        self._rotate_locked()
                except OSError:
                    # telemetry is advisory: a full/readonly volume must
                    # never fail the build it is describing
                    self.sink_path = None
                    self._sink = None
            listeners = list(self._listeners)
        for listener in listeners:
            try:
                listener(span)
            except Exception:  # noqa: BLE001 - listeners are advisory too
                pass

    def _ensure_sink_linked(self) -> None:
        """Drop a sink handle whose file no longer sits at the sink
        path (an aggregator in another pid namespace garbage-collected
        a sink it wrongly judged dead, or another process rotated a
        shared path): appending through the orphaned fd would make
        every later span invisible to all readers forever. Detection is
        a path-stat vs fd-stat inode comparison, NOT ``st_nlink == 0``
        — overlayfs (containers) keeps reporting nlink 1 for an
        unlinked-but-open file. One stat pair per write/batch; the
        caller reopens by path right after, so the next span starts a
        fresh, discoverable file."""
        if self._sink is None:
            return
        try:
            handle_stat = os.fstat(self._sink.fileno())
            try:
                path_stat = os.stat(self.sink_path)
            except OSError:
                orphaned = True  # the path is simply gone
            else:
                orphaned = (
                    path_stat.st_ino != handle_stat.st_ino
                    or path_stat.st_dev != handle_stat.st_dev
                )
            if orphaned:
                self._sink.close()
                self._sink = None
        except OSError:
            self._sink = None

    # -- async sink (serving) -----------------------------------------------

    def _ensure_writer(self) -> None:
        with self._lock:
            if self._writer is None and not self._closed:
                self._writer = threading.Thread(
                    target=self._writer_loop,
                    name="gordo-trace-writer",
                    daemon=True,
                )
                self._writer.start()

    def _writer_loop(self) -> None:
        # Self-polling instead of per-span signaling: an Event.set()
        # from the recording thread is a futex syscall that wakes the
        # writer mid-request — measured ~4% of scoring throughput at a
        # 10% export rate. While spans flow the poll is 50ms (bounds
        # trace latency); an idle writer backs off exponentially to 1s
        # so a quiet server doesn't pay 20 scheduler wakes/second for
        # nothing (under cgroup CPU quota even idle wakes bill the
        # throttle budget). close()/flush() still signal for prompt
        # shutdown.
        timeout = 0.05
        while True:
            self._wakeup.wait(timeout=timeout)
            self._wakeup.clear()
            if self._queue:
                timeout = 0.05
                self._drain()
            else:
                timeout = min(1.0, timeout * 2)
            if self._closed and not self._queue:
                return

    def _drain(self) -> None:
        """Write everything queued, as one batched write+flush. Queue
        items are span dicts or deferred builders (zero-arg callables
        returning span lists — see :meth:`emit_deferred`)."""
        with self._write_lock:
            batch: List[dict] = []
            while True:
                try:
                    item = self._queue.popleft()
                except IndexError:
                    break
                if callable(item):
                    try:
                        batch.extend(item())
                    except Exception:  # noqa: BLE001 - a broken deferred
                        # builder loses ITS spans, never the writer
                        pass
                else:
                    batch.append(item)
            if not batch or self.sink_path is None:
                return
            try:
                self._ensure_sink_linked()
                if self._sink is None:
                    self._sink = open(self.sink_path, "a")
                self._sink.write(
                    "".join(
                        json.dumps(span, default=str) + "\n" for span in batch
                    )
                )
                self._sink.flush()
                if self.max_bytes and self._sink.tell() >= self.max_bytes:
                    self._rotate_locked()
            except OSError:
                self.sink_path = None
                self._sink = None

    def flush(self) -> None:
        """Block until everything recorded so far is on disk (async
        sinks; a synchronous sink is always flushed per span). Tests
        and the route bench call this before reading the trace back."""
        if self.async_sink:
            self._drain()

    def _rotate_locked(self) -> None:
        """Rotate the sink: ``p`` -> ``p.1`` -> ... -> ``p.<keep>``
        (older generations deleted), then reopen a fresh ``p``. Called
        with the lock held, right after a write crossed ``max_bytes`` —
        so a months-lived serving/lifecycle process caps its telemetry
        footprint at ~``(keep + 1) * max_bytes`` per sink instead of
        growing without bound."""
        self._sink.close()
        self._sink = None
        if self.keep < 1:
            os.remove(self.sink_path)
            return
        for generation in range(self.keep, 0, -1):
            src = (
                self.sink_path
                if generation == 1
                else f"{self.sink_path}.{generation - 1}"
            )
            if os.path.exists(src):
                os.replace(src, f"{self.sink_path}.{generation}")

    # -- introspection ------------------------------------------------------

    def add_listener(self, listener: Callable[[dict], None]) -> None:
        """Call ``listener(span_dict)`` for every span/event as it
        finishes (the builder uses this for live Prometheus export)."""
        with self._lock:
            self._listeners.append(listener)

    def finished(self, name: Optional[str] = None) -> List[dict]:
        """Finished spans (optionally filtered by name), oldest first.
        Empty when ``retain_spans`` is off (the default for sink-backed
        recorders — read the JSONL sink instead)."""
        with self._lock:
            spans = list(self._spans)
        if name is not None:
            spans = [s for s in spans if s["name"] == name]
        return spans

    def durations(self) -> Dict[str, float]:
        """Total seconds per span name, in first-seen order."""
        totals: Dict[str, float] = {}
        for span in self.finished():
            if span["kind"] == "event":
                continue
            totals[span["name"]] = (
                totals.get(span["name"], 0.0) + span["duration_ms"] / 1000.0
            )
        return totals

    def close(self) -> None:
        if self.async_sink:
            self._closed = True
            self._wakeup.set()
            writer = self._writer
            if writer is not None:
                writer.join(timeout=2.0)
                with self._lock:  # _ensure_writer races shutdown
                    self._writer = None
            self._drain()  # anything the writer left behind
            with self._write_lock:
                if self._sink is not None:
                    try:
                        self._sink.close()
                    except OSError:
                        pass
                    self._sink = None
            return
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    pass
                self._sink = None


# -- the process-global recorder --------------------------------------------

_active: Any = NULL_RECORDER
_active_lock = threading.Lock()


def get_recorder():
    """The currently active recorder (:data:`NULL_RECORDER` when no
    build is being traced)."""
    return _active


@contextlib.contextmanager
def activate(recorder):
    """Install ``recorder`` as the process-global recorder for the
    enclosed block (the fleet build wraps itself in this)."""
    global _active
    with _active_lock:
        previous, _active = _active, recorder
    try:
        yield recorder
    finally:
        with _active_lock:
            _active = previous


# -- compile-vs-run attribution ---------------------------------------------

_seen_lock = threading.Lock()
_seen_programs: set = set()


def seen_program(key: Hashable) -> bool:
    """Register a program signature; True when it was already seen this
    process (→ the jit cache will hit and the call is a steady-state
    run, not a compile)."""
    with _seen_lock:
        if key in _seen_programs:
            return True
        _seen_programs.add(key)
        return False


def reset_seen_programs() -> None:
    """Forget all program signatures (tests only — real processes keep
    the set for the jit caches' lifetime, which is the process)."""
    with _seen_lock:
        _seen_programs.clear()


def program_span(program: str, key: Hashable, **attributes):
    """
    Span around one jit-program invocation, attributed ``compile=True``
    on the first call per signature and ``compile=False`` after.

    ``key`` must capture the full compilation signature — spec, fit
    config, and array shapes — exactly as the jit cache would.
    """
    compile_flag = not seen_program((program, key))
    # feed the process-wide compile-vs-cache-hit accounting (device.py):
    # unlike the span below this is unconditional — the fleet console's
    # hit-rate numbers must not depend on a recorder being active
    from .device import note_program_execution

    note_program_execution(compile_flag, kind="build")
    return get_recorder().span(
        "device_program", program=program, compile=compile_flag, **attributes
    )
