"""
The performance-regression gate: ``gordo-tpu bench-check``.

The repo's committed ``BENCH_*.json`` files are its performance
trajectory — every PR that touched a hot path re-ran a bench and
committed the result. Until now nothing *compared* them: a serving
regression had to be noticed by a human reading JSON diffs. This module
makes the comparison executable: each known bench kind declares which
of its numbers are load-bearing (direction + relative tolerance, or an
absolute budget), :func:`compare` evaluates a fresh candidate run
against the committed baseline, and the CLI exits non-zero on any
regression — the gate the ROADMAP's full-route optimization work needs
before it can claim wins (and keep them).

Tolerances are deliberately loose by default (shared CI hosts show
multi-x wall-clock noise; the benches fight it with interleaved
quiet-window floors, but a gate that cries wolf gets deleted) and scale
with ``--tolerance``. CI runs the gate in ``--report-only`` mode —
visibility without flakiness — while release branches can enforce.
"""

import json
from typing import Any, Dict, List, NamedTuple, Optional


class MetricSpec(NamedTuple):
    """One gated number inside a bench document.

    ``kind``: ``higher`` / ``lower`` (relative to baseline, within
    ``tolerance``), ``max_bound`` (candidate must stay ≤ ``bound``,
    baseline-independent), ``min_bound`` (candidate must stay ≥
    ``bound`` — absolute floors like "batching-on must not lose to
    batching-off"), or ``truthy`` (candidate must be true).
    ``path`` is dotted (``scoring.batching_on.throughput_rps``).
    """

    label: str
    path: str
    kind: str
    tolerance: float = 0.0
    bound: Optional[float] = None


#: the load-bearing numbers per bench kind, keyed by the document's
#: ``bench`` field — adding a bench to the trajectory means adding its
#: gate row here (the golden-schema tests pin the paths)
GATES: Dict[str, List[MetricSpec]] = {
    "route-observability": [
        MetricSpec(
            "full-route throughput (floor rps)",
            "route.throughput_rps",
            "higher",
            0.25,
        ),
        MetricSpec("full-route p50 latency", "route.p50_ms", "lower", 0.25),
        MetricSpec(
            "stage attribution coverage",
            "route.attribution_coverage",
            "higher",
            0.05,
        ),
        # µs/request, not % of the floor: the telemetry-on cost is a
        # fixed per-request price (trace identity + log binding +
        # head-sampled export), so a %-of-floor budget PENALIZES making
        # scoring faster — the same ~28µs that read as 2% at PR 7's
        # 665rps floor reads as 5% past 1900rps (PR 12 recalibration).
        MetricSpec(
            "telemetry overhead on the scoring path (µs/request)",
            "scoring_overhead.overhead_us_per_request",
            "max_bound",
            bound=60.0,
        ),
        # -- the columnar-wire acceptance set (PR 12) -------------------
        MetricSpec(
            "response_assemble p50 budget (ms)",
            "route.stages.response_assemble.p50_ms",
            "max_bound",
            bound=50.0,
        ),
        # tightened 3.0 -> 1.5 by the device-resident ingest subsystem
        # (PR 19): with decode, staging and preprocessing all columnar/
        # on-device, the route may cost at most 1.5x the scoring-only
        # floor at matched concurrency
        MetricSpec(
            "columnar (Arrow) route p50 vs scoring-only floor at "
            "matched concurrency (ratio)",
            "route_gap_p50_ratio",
            "max_bound",
            bound=1.5,
        ),
        # wire parse + device staging together must stay a small
        # absolute cost per request (the stages the ingest subsystem
        # owns: data_decode narrowed to wire->host parse, device_ingest
        # the wire->device staging it used to hide)
        MetricSpec(
            "data_decode + device_ingest p50 budget (ms)",
            "ingest_p50_ms",
            "max_bound",
            bound=10.0,
        ),
        # route-level batching must stay at least at parity with
        # batching-off (noise margin included) — the wash PR 7 measured
        # was invisible to the gate until this row. On CPU-only hosts
        # the fused program has no parallel hardware to exploit, so
        # parity IS the CPU ceiling; a ratio below the floor means the
        # batched path regressed (e.g. dispatcher latency, queue
        # convoy), which is exactly what this row exists to catch.
        MetricSpec(
            "route-level batched vs unbatched throughput (ratio)",
            "route_batched_vs_unbatched",
            "min_bound",
            bound=0.6,
        ),
    ],
    "serve-micro-batching": [
        MetricSpec(
            "batched scoring throughput (floor rps)",
            "scoring.batching_on.throughput_rps",
            "higher",
            0.25,
        ),
        MetricSpec(
            "unbatched scoring throughput (floor rps)",
            "scoring.batching_off.throughput_rps",
            "higher",
            0.25,
        ),
        MetricSpec("batching gain", "throughput_gain", "higher", 0.2),
        MetricSpec("program-cache bounded", "programs_bounded", "truthy"),
    ],
    "telemetry-overhead": [
        MetricSpec(
            "build telemetry overhead (%)",
            "overhead_pct",
            "max_bound",
            bound=3.0,
        ),
    ],
    "planner-strategies": [
        MetricSpec("packed beats naive", "packed_wins", "truthy"),
    ],
    "lifecycle-hot-swap": [
        MetricSpec("hot-swap p50 (ms)", "swap_p50_ms", "lower", 0.5),
        MetricSpec(
            "dropped requests during swaps",
            "requests_dropped",
            "max_bound",
            bound=0.0,
        ),
    ],
    "fleet-health-overhead": [
        MetricSpec(
            "health ledger + device sampler overhead (%)",
            "overhead_pct",
            "max_bound",
            bound=2.0,
        ),
        MetricSpec(
            "fleet_health.json written by the instrumented build",
            "ledger_written",
            "truthy",
        ),
        MetricSpec(
            "ledger record throughput (records/s)",
            "ledger_records_per_sec",
            "higher",
            0.5,
        ),
    ],
    "precision-ladder": [
        MetricSpec(
            "f32 fused scoring throughput (floor rows/s)",
            "throughput.f32.rows_per_sec",
            "higher",
            0.5,
        ),
        # CPU hosts have no bf16/int8 compute units, so parity with f32
        # is the CEILING there (measured ~0.5x under XLA's emulation) —
        # these floors exist to catch the reduced paths REGRESSING
        # (an accidental f64 upcast, a dequant blowup), exactly the
        # route_batched_vs_unbatched min_bound pattern; the speedup
        # itself asserts on device hardware.
        MetricSpec(
            "bf16 vs f32 fused scoring throughput (ratio)",
            "ratios.bf16_vs_f32",
            "min_bound",
            bound=0.3,
        ),
        MetricSpec(
            "int8 vs f32 fused scoring throughput (ratio)",
            "ratios.int8_vs_f32",
            "min_bound",
            bound=0.25,
        ),
        MetricSpec(
            "reduced-vs-f32 verdict agreement (min across precisions)",
            "verdict_agreement.min",
            "min_bound",
            bound=0.95,
        ),
        MetricSpec(
            "precision-parity gates passed",
            "parity_gates_passed",
            "truthy",
        ),
    ],
    "serve-chaos": [
        # the containment contract, verbatim: one poisoned member out of
        # a coalesced fleet must never turn into innocent-rider 5xx
        MetricSpec(
            "innocent-rider 5xx during the device-fault drill",
            "innocent_rider_5xx",
            "max_bound",
            bound=0.0,
        ),
        MetricSpec(
            "poison member's breaker tripped into quarantine",
            "breaker_tripped",
            "truthy",
        ),
        MetricSpec(
            "breaker recovered via its half-open probe",
            "breaker_recovered",
            "truthy",
        ),
        MetricSpec(
            "health ledger narrated the trip and recovery",
            "ledger_narrated",
            "truthy",
        ),
        MetricSpec(
            "hot-swap mid-drill dropped requests",
            "swap_dropped",
            "max_bound",
            bound=0.0,
        ),
        # steady-state throughput under faults vs the no-fault floor:
        # bisection + breaker quarantine must CONTAIN the poison, not
        # drag the whole serving plane down with it
        MetricSpec(
            "faulted vs clean innocent-rider throughput (ratio)",
            "throughput_ratio_faulted_vs_clean",
            "min_bound",
            bound=0.4,
        ),
    ],
    "fleet-scale": [
        # the bounded fleet-status contract: the summary-first document
        # must stay both cheap in absolute terms and a small fraction
        # of the naive full render at the largest measured N
        MetricSpec(
            "fleet-status summary build+render budget (ms)",
            "gates.fleet_status_summary_ms",
            "max_bound",
            bound=250.0,
        ),
        MetricSpec(
            "fleet-status summary vs naive full render (ratio)",
            "gates.fleet_status_summary_vs_full_ratio",
            "max_bound",
            bound=0.5,
        ),
        # one machine's flush must rewrite ~one shard's share of the
        # corpus regardless of N (the ratio is shard-normalized, so the
        # budget holds at CI's reduced sizes too): a value near the
        # shard count would mean the flush went monolithic again
        MetricSpec(
            "ledger dirty-flush bytes vs one-shard share (ratio)",
            "gates.ledger_dirty_flush_shard_ratio",
            "max_bound",
            bound=2.0,
        ),
        MetricSpec(
            "merged-window read opened only manifest-selected files",
            "gates.rollup_reads_bounded",
            "truthy",
        ),
        MetricSpec(
            "rollup aggregation throughput at scale (spans/s)",
            "gates.rollup_spans_per_sec",
            "higher",
            0.5,
        ),
        MetricSpec(
            "ledger populate throughput at scale (records/s)",
            "gates.ledger_records_per_sec",
            "higher",
            0.5,
        ),
        MetricSpec(
            "breaker-board bounded summary budget (ms)",
            "gates.breaker_summary_ms",
            "max_bound",
            bound=5.0,
        ),
    ],
    "stream-soak": [
        # the always-on plane must beat the request/response ceiling:
        # one ingest connection amortizes decode + dispatch across many
        # windows, where the JSON route pays it per exchange
        MetricSpec(
            "sustained streaming scoring throughput (rows/s)",
            "soak.rows_per_sec",
            "higher",
            0.5,
        ),
        # the zero-gap invariant, audited per machine across the whole
        # soak: rows_in == rows_scored + rows_failed + pending + shed
        MetricSpec(
            "per-machine row-accounting gaps across the soak",
            "soak.accounting_gaps",
            "max_bound",
            bound=0.0,
        ),
        # hot-swap mid-stream: anomaly frames' [first_seq, last_seq]
        # spans must stay contiguous per machine across every promotion
        # — a hole is a dropped window, an overlap a double-score
        MetricSpec(
            "hot-swaps completed mid-stream",
            "swap.swaps",
            "min_bound",
            bound=5.0,
        ),
        MetricSpec(
            "windows dropped or double-scored across hot-swaps",
            "swap.seq_gaps",
            "max_bound",
            bound=0.0,
        ),
        # poison containment: breakers quarantine the poisoned member;
        # its stream-mates keep scoring without a single dropped window
        MetricSpec(
            "poisoned member quarantined by its breaker",
            "poison.quarantined",
            "truthy",
        ),
        MetricSpec(
            "innocent machines' dropped windows under member poison",
            "poison.innocent_drops",
            "max_bound",
            bound=0.0,
        ),
        MetricSpec(
            "quarantined member recovered via half-open probe",
            "poison.recovered",
            "truthy",
        ),
        # drain: every open SSE subscription ended with a terminal frame
        MetricSpec(
            "drain closed every stream with a terminal frame",
            "drain.clean_terminals",
            "truthy",
        ),
        # -- the streaming-observability acceptance set (PR 18) ---------
        # span telemetry on the flush path, interleaved quiet floors:
        # the always-on plane must not pay a visible tax for its own
        # observability
        MetricSpec(
            "stream telemetry soak overhead (%)",
            "telemetry.overhead_pct",
            "max_bound",
            bound=2.0,
        ),
        # freshness under sustained load: the soak's row-weighted
        # ingest-to-scored lag p95, an absolute budget well under the
        # packaged 5s freshness SLO threshold
        MetricSpec(
            "soak ingest-to-scored lag p95 budget (ms)",
            "soak.lag_p95_ms",
            "max_bound",
            bound=2000.0,
        ),
        # the freshness SLO drill: an injected stream_score stall must
        # walk the alert pending -> firing (the page-severity predicate
        # that holds lifecycle auto-promotion) and resolve on recovery
        MetricSpec(
            "freshness drill: stall -> pending -> firing -> resolved",
            "slo_drill.drill_ok",
            "truthy",
        ),
        MetricSpec(
            "freshness firing held the canary promotion gate",
            "slo_drill.held_promotion",
            "truthy",
        ),
        # the scrape surface must stay a small constant at 10k members:
        # per-machine detail belongs to /stream/status and the trace
        MetricSpec(
            "stream scrape surface bounded at 10k members",
            "prometheus.bounded",
            "truthy",
        ),
        MetricSpec(
            "stream scrape samples at 10k members",
            "prometheus.samples",
            "max_bound",
            bound=100.0,
        ),
    ],
    "device-ingest": [
        # compiled-vs-host numeric parity on the same payloads is the
        # subsystem's contract — a fast wrong answer fails the run
        MetricSpec(
            "compiled plan output matches the host pipeline",
            "parity_ok",
            "truthy",
        ),
        MetricSpec(
            "broken-dlpack fallback still answers correct bytes",
            "fallback_ok",
            "truthy",
        ),
        # the rung dlpack_enabled() picks for this backend vs forced
        # host staging: on CPU both are the host rung, so parity is the
        # ceiling and the floor catches the picked rung REGRESSING (the
        # precision-ladder min_bound pattern); the dlpack zero-copy win
        # itself asserts on device hardware
        MetricSpec(
            "serving transfer rung vs host staging throughput (ratio)",
            "transfer.speedup",
            "min_bound",
            bound=0.4,
        ),
        MetricSpec(
            "compiled-plan vs host-pipeline scoring throughput (ratio)",
            "compiled.speedup",
            "min_bound",
            bound=0.5,
        ),
        MetricSpec(
            "end-to-end staging p50 budget (ms)",
            "compiled.staged_p50_ms",
            "max_bound",
            bound=10.0,
        ),
    ],
    "slo-engine": [
        MetricSpec(
            "rollup aggregation throughput (spans/s)",
            "aggregate_spans_per_sec",
            "higher",
            0.5,
        ),
        MetricSpec(
            "steady-state SLO evaluation overhead vs telemetry-on "
            "floor (%)",
            "overhead_pct",
            "max_bound",
            bound=2.0,
        ),
        MetricSpec(
            "burn drill: pending -> firing -> resolved",
            "drill_ok",
            "truthy",
        ),
    ],
    "learned-perfmodel": [
        # the learned regressor earns its place by beating the analytic
        # model on a held-out slice of the same trace corpus — the same
        # accuracy gate fit_and_promote enforces, re-checked end to end
        # from raw traces. Ratio = learned MAE / analytic MAE in log
        # space; 1.0 is parity, the promotion gate's own floor.
        MetricSpec(
            "learned vs analytic holdout MAE, device time (ratio)",
            "accuracy.device_ms.mae_ratio",
            "max_bound",
            bound=1.0,
        ),
        MetricSpec(
            "learned vs analytic holdout MAE, compile time (ratio)",
            "accuracy.compile_ms.mae_ratio",
            "max_bound",
            bound=1.0,
        ),
        MetricSpec("model promoted from bench corpus", "fit.promoted", "truthy"),
        # learned-informed serving (model-ordered warmup + learned step
        # predictions) vs the static ladder at equal offered load. On
        # CPU hosts there is no hardware for the model to exploit, so
        # parity is the ceiling — the floor catches the learned path
        # *losing* throughput (mispredicted ladders, estimator overhead
        # on the hot path).
        MetricSpec(
            "learned-informed vs static ladder throughput (ratio)",
            "ladder.learned_vs_static_throughput",
            "min_bound",
            bound=0.85,
        ),
        MetricSpec(
            "learned-informed vs static ladder p99 latency (ratio)",
            "ladder.learned_vs_static_p99_ratio",
            "max_bound",
            bound=1.5,
        ),
    ],
}

#: where each bench kind's committed baseline lives (repo root)
BASELINE_FILES: Dict[str, str] = {
    "route-observability": "BENCH_ROUTE.json",
    "serve-micro-batching": "BENCH_SERVE.json",
    "telemetry-overhead": "BENCH_TELEMETRY.json",
    "planner-strategies": "BENCH_PLAN.json",
    "lifecycle-hot-swap": "BENCH_LIFECYCLE.json",
    "fleet-health-overhead": "BENCH_FLEET_HEALTH.json",
    "slo-engine": "BENCH_SLO.json",
    "fleet-scale": "BENCH_SCALE.json",
    "precision-ladder": "BENCH_PRECISION.json",
    "serve-chaos": "BENCH_CHAOS.json",
    "stream-soak": "BENCH_STREAM.json",
    "device-ingest": "BENCH_INGEST.json",
    "learned-perfmodel": "BENCH_PERFMODEL.json",
}


def get_path(doc: Any, path: str) -> Any:
    """Walk a dotted path through nested dicts; None when absent."""
    node = doc
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


def _evaluate(
    spec: MetricSpec,
    baseline: Optional[float],
    candidate: Any,
    tolerance_scale: float,
) -> Dict[str, Any]:
    result: Dict[str, Any] = {
        "metric": spec.label,
        "path": spec.path,
        "kind": spec.kind,
        "baseline": baseline,
        "candidate": candidate,
        "status": "ok",
    }
    if candidate is None:
        result["status"] = "regression"
        result["detail"] = "metric missing from candidate run"
        return result
    if spec.kind == "truthy":
        if not candidate:
            result["status"] = "regression"
            result["detail"] = "expected truthy"
        return result
    if spec.kind == "max_bound":
        # --tolerance scales budgets too ("2.0 = twice as lenient"
        # must mean every gate, or the loosening a noisy host needs
        # is vetoed by whichever metric is noisiest)
        bound = float(spec.bound) * tolerance_scale
        result["bound"] = round(bound, 6)
        if float(candidate) > bound:
            result["status"] = "regression"
            result["detail"] = f"exceeds budget {bound:g}"
        return result
    if spec.kind == "min_bound":
        # scaling DIVIDES here: "2.0 = twice as lenient" lowers a floor
        bound = float(spec.bound) / tolerance_scale
        result["bound"] = round(bound, 6)
        if float(candidate) < bound:
            result["status"] = "regression"
            result["detail"] = f"below floor {bound:g}"
        return result
    if baseline is None:
        # a schema-evolving candidate gains metrics the old baseline
        # lacks: report, don't fail — the next committed baseline picks
        # it up
        result["status"] = "skipped"
        result["detail"] = "metric missing from baseline"
        return result
    baseline_f, candidate_f = float(baseline), float(candidate)
    tolerance = spec.tolerance * tolerance_scale
    result["tolerance"] = round(tolerance, 4)
    if baseline_f != 0:
        result["ratio"] = round(candidate_f / baseline_f, 4)
    if spec.kind == "higher":
        limit = baseline_f * (1.0 - tolerance)
        if candidate_f < limit:
            result["status"] = "regression"
            result["detail"] = (
                f"below baseline {baseline_f:g} by more than "
                f"{tolerance * 100:.0f}%"
            )
    elif spec.kind == "lower":
        limit = baseline_f * (1.0 + tolerance)
        if candidate_f > limit:
            result["status"] = "regression"
            result["detail"] = (
                f"above baseline {baseline_f:g} by more than "
                f"{tolerance * 100:.0f}%"
            )
    return result


def compare(
    baseline_doc: Dict[str, Any],
    candidate_doc: Dict[str, Any],
    specs: Optional[List[MetricSpec]] = None,
    tolerance_scale: float = 1.0,
) -> Dict[str, Any]:
    """Evaluate ``candidate_doc`` against ``baseline_doc`` under the
    bench kind's gate specs. The two documents must describe the same
    bench (``bench`` field) unless explicit ``specs`` are supplied."""
    bench = candidate_doc.get("bench")
    if specs is None:
        if baseline_doc.get("bench") != bench:
            raise ValueError(
                f"bench mismatch: baseline is "
                f"{baseline_doc.get('bench')!r}, candidate {bench!r}"
            )
        specs = GATES.get(str(bench))
        if specs is None:
            raise ValueError(
                f"no gate specs for bench {bench!r} "
                f"(known: {sorted(GATES)})"
            )
    results = [
        _evaluate(
            spec,
            get_path(baseline_doc, spec.path),
            get_path(candidate_doc, spec.path),
            tolerance_scale,
        )
        for spec in specs
    ]
    regressions = sum(1 for r in results if r["status"] == "regression")
    return {
        "bench": bench,
        "tolerance_scale": tolerance_scale,
        "results": results,
        "regressions": regressions,
        "ok": regressions == 0,
    }


def compare_files(
    baseline_path: str,
    candidate_path: str,
    tolerance_scale: float = 1.0,
) -> Dict[str, Any]:
    with open(baseline_path) as handle:
        baseline_doc = json.load(handle)
    with open(candidate_path) as handle:
        candidate_doc = json.load(handle)
    report = compare(
        baseline_doc, candidate_doc, tolerance_scale=tolerance_scale
    )
    report["baseline"] = baseline_path
    report["candidate"] = candidate_path
    return report


def render_report(report: Dict[str, Any]) -> str:
    """Human-readable gate report."""
    lines = [
        f"bench-check: {report['bench']}  "
        f"(baseline {report.get('baseline', '?')} vs "
        f"candidate {report.get('candidate', '?')})"
    ]
    for result in report["results"]:
        mark = {"ok": "PASS", "regression": "FAIL", "skipped": "SKIP"}[
            result["status"]
        ]
        value = result["candidate"]
        baseline = result["baseline"]
        detail = result.get("detail", "")
        extra = f"  [{detail}]" if detail else ""
        lines.append(
            f"  {mark}  {result['metric']}: {value!r}"
            + (f" (baseline {baseline!r})" if baseline is not None else "")
            + extra
        )
    verdict = "OK" if report["ok"] else (
        f"{report['regressions']} regression(s)"
    )
    lines.append(f"result: {verdict}")
    return "\n".join(lines)
