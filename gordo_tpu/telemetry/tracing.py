"""
W3C Trace Context for the serving path.

The reference system leaned on its mesh for request correlation (Envoy
stamps ``x-request-id`` and the access log is the trace). Here every
request gets a real W3C ``traceparent`` identity instead: the server
accepts an incoming header (so a gateway's trace continues through the
model server), allocates one otherwise, threads it through the request's
stage spans and the micro-batcher (batch spans *link* back to the
request spans they coalesced), echoes it on the response, and binds it
to log lines — one id correlates the access log, the span trace and the
client's own telemetry.

Stdlib-only, like the rest of ``gordo_tpu.telemetry``.

>>> ctx = parse_traceparent("00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01")
>>> ctx.trace_id
'0af7651916cd43dd8448eb211c80319c'
>>> format_traceparent(ctx.trace_id, ctx.span_id)
'00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01'
>>> parse_traceparent("not-a-traceparent") is None
True
"""

import contextlib
import contextvars
import logging
import re
from typing import NamedTuple, Optional

from .recorder import rand_hex

TRACEPARENT_HEADER = "traceparent"

#: version "00" traceparent: 16-byte trace id, 8-byte parent span id,
#: flags — all lowercase hex, all-zero ids are invalid per the spec
_TRACEPARENT_RE = re.compile(
    r"^00-(?P<trace_id>[0-9a-f]{32})-(?P<span_id>[0-9a-f]{16})"
    r"-(?P<flags>[0-9a-f]{2})$"
)


class TraceContext(NamedTuple):
    """A parsed ``traceparent``: the trace id, the caller's span id, and
    whether the caller sampled the trace (flags bit 0 — a sampled
    upstream trace is always exported so distributed traces never end
    at this server's doorstep)."""

    trace_id: str
    span_id: str
    sampled: bool = True


def new_trace_id() -> str:
    """A fresh 16-byte (32 hex char) W3C trace id."""
    return rand_hex(32)


def new_span_id() -> str:
    """A fresh 8-byte (16 hex char) W3C span id."""
    return rand_hex(16)


def new_trace_context() -> TraceContext:
    """A fresh (trace id, span id) pair from ONE randomness draw — the
    request hot path mints both per request, and one 192-bit draw +
    format costs half of two separate calls."""
    both = rand_hex(48)
    return TraceContext(both[:32], both[32:], True)


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """The ``(trace_id, span_id)`` of a version-00 ``traceparent``
    header, or None for anything malformed (a bad header must never 500
    a prediction — the request simply starts a fresh trace)."""
    if not header:
        return None
    match = _TRACEPARENT_RE.match(header.strip().lower())
    if match is None:
        return None
    trace_id = match.group("trace_id")
    span_id = match.group("span_id")
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    sampled = bool(int(match.group("flags"), 16) & 0x01)
    return TraceContext(trace_id, span_id, sampled)


def format_traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    """The version-00 ``traceparent`` wire form for this trace/span."""
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


# -- log correlation ---------------------------------------------------------

#: the trace id bound to the current execution context (contextvars so
#: the binding follows the request across the handlers it calls; worker
#: threads the request *spawns* inherit a copy at thread start only via
#: contextvars.copy_context — dispatcher threads log their own spans'
#: trace ids instead)
_current_trace_id: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "gordo_tpu_trace_id", default=""
)


def current_trace_id() -> str:
    """The trace id bound to this context ("" outside a request)."""
    return _current_trace_id.get()


@contextlib.contextmanager
def bind_trace(trace_id: str):
    """Bind ``trace_id`` as the current trace for the enclosed block —
    the request dispatcher wraps handler execution in this so log lines
    emitted anywhere below carry the request's trace id."""
    token = _current_trace_id.set(trace_id)
    try:
        yield
    finally:
        _current_trace_id.reset(token)


def bind(trace_id: str):
    """Generator-free binding for the request hot path: returns the
    reset token for :func:`unbind`. ``bind_trace`` is the ergonomic
    form; this pair skips the contextmanager generator (~5us/request
    under thread contention)."""
    return _current_trace_id.set(trace_id)


def unbind(token) -> None:
    _current_trace_id.reset(token)


class TraceIdFilter(logging.Filter):
    """A logging filter that stamps the bound trace id onto every record
    as ``record.trace_id`` ("-" outside a request), for handlers whose
    format string opts into ``%(trace_id)s``."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.trace_id = current_trace_id() or "-"
        return True


_factory_installed = False


def install_trace_log_stamping() -> None:
    """Stamp the bound trace id into every in-request log record,
    process-wide, once. Implemented as a log-record *factory* (not a
    logger filter — filters do not inherit to child loggers, and every
    module logs through its own ``gordo_tpu.<module>`` child): records
    created while a trace is bound gain ``record.trace_id`` and a
    ``trace_id=<id>`` message suffix, so existing handlers and format
    strings surface the correlation unchanged. ``build_app`` calls this
    unconditionally; idempotent."""
    global _factory_installed
    if _factory_installed:
        return
    _factory_installed = True
    previous_factory = logging.getLogRecordFactory()

    def factory(*args, **kwargs):
        record = previous_factory(*args, **kwargs)
        trace_id = current_trace_id()
        record.trace_id = trace_id or "-"
        if trace_id:
            record.msg = f"{record.msg} trace_id={trace_id}"
        return record

    logging.setLogRecordFactory(factory)
