"""
A low-overhead sampling profiler for the serving host pipeline.

BENCH_SERVE.json's open finding is that the full HTTP route runs ~50x
slower than scoring alone — the host pipeline (JSON decode, pandas
alignment, response serialization) dominates, but nothing could say
*which functions* eat the time on a live server. Deterministic tracing
(``sys.setprofile``) is off the table: it taxes every Python call on
every request, profiled or not. This profiler samples instead: a
background thread wakes every few milliseconds, grabs the profiled
request thread's current frame via ``sys._current_frames()``, and
charges one sample of **self time** to the (request stage, top frame)
pair. The request thread itself executes zero extra instructions; the
cost is one sampling thread per *profiled* request, and profiling is
off by default.

Two switches, both per-request:

- ``?profile=1`` on any model route profiles that request;
- ``GORDO_TPU_PROFILE_SAMPLE_RATE=0.01`` profiles ~1% of requests at
  random — the always-on production setting that keeps a live
  self-time breakdown flowing into ``serve_trace.jsonl`` (the
  ``profile`` span; ``gordo-tpu trace`` aggregates them).

The aggregated report is intentionally tiny — top-N frames by self
time, keyed ``(stage, function)`` — because its destination is a span
attribute in a JSONL trace, not a pprof blob. For raw XLA device
traces there is the separate opt-in ``jax.profiler`` layer
(``utils/profiling.maybe_trace``; ``?profile=device`` hooks it when
``GORDO_TPU_PROFILE_DIR`` is set).
"""

import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

SAMPLE_RATE_ENV = "GORDO_TPU_PROFILE_SAMPLE_RATE"
INTERVAL_ENV = "GORDO_TPU_PROFILE_INTERVAL_MS"

DEFAULT_INTERVAL_MS = 5.0
#: hard wall on one profile's runtime: a hung request must not leak an
#: immortal sampling thread
MAX_PROFILE_SECONDS = 120.0
#: frames kept in the report (by self time) — it travels as a span
#: attribute, so it must stay small
MAX_REPORT_FRAMES = 25


def sample_rate() -> float:
    """The configured random-sampling fraction in [0, 1] (default 0 =
    only explicitly requested profiles run)."""
    from ..utils.env import env_float

    return min(1.0, max(0.0, env_float(SAMPLE_RATE_ENV, 0.0)))


def sample_interval_s() -> float:
    from ..utils.env import env_float

    return max(
        0.0005, env_float(INTERVAL_ENV, DEFAULT_INTERVAL_MS) / 1000.0
    )


def should_profile(explicit: Optional[str]) -> bool:
    """Whether to profile this request: an explicit ``?profile=``
    value wins (any truthy spelling); otherwise a coin flip at
    ``GORDO_TPU_PROFILE_SAMPLE_RATE``."""
    if explicit is not None:
        return explicit.strip().lower() not in ("", "0", "false", "off", "no")
    rate = sample_rate()
    if rate <= 0.0:
        return False
    import random

    return random.random() < rate


def _frame_label(frame) -> str:
    """``<file>:<function>`` with the path trimmed to its last two
    segments — stable across hosts, short enough for a span attribute."""
    code = frame.f_code
    parts = code.co_filename.replace("\\", "/").rsplit("/", 2)
    filename = "/".join(parts[-2:]) if len(parts) > 1 else parts[-1]
    return f"{filename}:{code.co_name}"


class SamplingProfiler:
    """
    Samples ONE thread's stack until stopped, aggregating self time by
    ``(stage, function)``.

    ``stage_getter`` is a zero-argument callable answering the profiled
    request's current pipeline stage (the request context updates it as
    ``ctx.stage(...)`` blocks enter and exit); samples landing outside
    any stage are charged to ``"-"``. Aggregation happens inside the
    sampling thread, so ``stop()`` is just an event + join.
    """

    def __init__(
        self,
        interval_s: Optional[float] = None,
        max_seconds: float = MAX_PROFILE_SECONDS,
    ):
        self.interval_s = interval_s if interval_s else sample_interval_s()
        self.max_seconds = max_seconds
        self._counts: Dict[Tuple[str, str], int] = {}
        self._samples = 0
        self._missed = 0
        self._started_at = 0.0
        self._stopped_at = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(
        self,
        thread_id: Optional[int] = None,
        stage_getter: Optional[Callable[[], Optional[str]]] = None,
    ) -> "SamplingProfiler":
        """Begin sampling ``thread_id`` (default: the calling thread)."""
        target_id = thread_id if thread_id is not None else threading.get_ident()
        self._started_at = time.monotonic()
        self._thread = threading.Thread(
            target=self._sample_loop,
            args=(target_id, stage_getter or (lambda: None)),
            name="gordo-profile-sampler",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> Dict[str, Any]:
        """Stop sampling and return the aggregated report."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self._stopped_at = time.monotonic()
        return self.report()

    # -- sampling (profiler thread) -----------------------------------------

    def _sample_loop(self, target_id: int, stage_getter) -> None:
        deadline = self._started_at + self.max_seconds
        interval = self.interval_s
        while not self._stop.wait(interval):
            if time.monotonic() > deadline:
                return
            frame = sys._current_frames().get(target_id)
            if frame is None:
                # the request thread finished (or hasn't a frame yet)
                self._missed += 1
                continue
            try:
                stage = stage_getter() or "-"
            except Exception:  # noqa: BLE001 - the getter reads request
                # state that may be mid-mutation; a bad read is one
                # mislabeled sample, never a dead profiler
                stage = "-"
            key = (str(stage), _frame_label(frame))
            self._counts[key] = self._counts.get(key, 0) + 1
            self._samples += 1
            del frame  # never keep a live frame reference across sleeps

    # -- report -------------------------------------------------------------

    def report(self, max_frames: int = MAX_REPORT_FRAMES) -> Dict[str, Any]:
        """The aggregated self-time profile: top ``max_frames`` by
        sample count, each charged ``samples * interval`` milliseconds
        of self time. Wire-shaped (plain dicts/lists) — this travels as
        a ``profile`` span's attributes."""
        stopped = self._stopped_at or time.monotonic()
        per_sample_ms = self.interval_s * 1000.0
        ranked = sorted(
            self._counts.items(), key=lambda kv: kv[1], reverse=True
        )
        frames: List[Dict[str, Any]] = [
            {
                "stage": stage,
                "function": function,
                "samples": count,
                "self_ms": round(count * per_sample_ms, 3),
            }
            for (stage, function), count in ranked[:max_frames]
        ]
        return {
            "samples": self._samples,
            "missed": self._missed,
            "interval_ms": round(per_sample_ms, 3),
            "duration_ms": round(
                max(0.0, stopped - self._started_at) * 1000.0, 3
            ),
            "truncated_frames": max(0, len(ranked) - max_frames),
            "frames": frames,
        }
