"""
The cross-worker telemetry reducer: every sink in a collection dir,
folded into compact time-windowed rollups the SLO engine (and any
pod-level aggregator) can evaluate without re-reading the span corpus.

PRs 3/7/9 left the telemetry *sinks* per process: under gunicorn each
worker appends its own ``serve_trace-<pid>.jsonl`` (PR 10's worker-sink
split), builds append ``build_trace.jsonl``, and every sink rotates by
size. Nothing merged them — answering "what was the error rate in the
last hour" meant re-parsing a quarter-gigabyte of JSONL per question.
This module is the merge:

- :func:`discover_sinks` finds every trace sink in a directory — the
  shared base names, the ``-<pid>`` worker variants, and all rotated
  generations of each;
- :class:`RollupStore` streams *new* spans out of them (per-file byte
  offsets keyed by a content signature, so rotation — which renames a
  file under the reader — resumes where the bytes moved to, not at the
  path), dedupes by ``(trace_id, span_id)``, assigns each span to a
  fixed time window, and folds it into ``rollups/<window>.json``
  artifacts (request/error counts, fixed-bucket latency histograms,
  per-stage and per-machine breakdowns), each written atomically;
- re-aggregation is **incremental**: a second pass over an unchanged
  corpus reads zero bytes. Rollups are plain mergeable JSON, so a
  pod-level aggregator over N hosts is a directory walk plus
  :func:`merge_rollups` — not a rewrite.

Percentiles come from the fixed-bucket histograms (stdlib-only, like
the whole package: no numpy inside the telemetry layer).
"""

import hashlib
import json
import logging
import os
import re
import threading
import time
from datetime import datetime, timezone
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .progress import BUILD_TRACE_FILE
from .serving import SERVE_TRACE_FILE

logger = logging.getLogger(__name__)

#: where rollups (and the reducer's resume state) live, under the
#: aggregated directory — a builder dropping, like the sinks themselves
ROLLUP_DIR = "rollups"
#: per-file read offsets + signatures (inside ROLLUP_DIR)
ROLLUP_STATE_FILE = "rollup_state.json"
#: the rollup index (inside ROLLUP_DIR): window -> file map with
#: per-window summaries, plus per-sink span-time windows — so
#: merged-window reads and ``--since``/``--last`` queries open only the
#: files they need instead of walking a busy directory
ROLLUP_MANIFEST_FILE = "manifest.json"
ROLLUP_MANIFEST_ENV = "GORDO_TPU_ROLLUP_MANIFEST"

#: rollup window size in seconds (every window boundary is aligned to
#: it, so windows from different workers/hosts merge bucket-for-bucket)
WINDOW_SECONDS_ENV = "GORDO_TPU_SLO_WINDOW_SECONDS"
DEFAULT_WINDOW_SECONDS = 60
#: rollup windows retained on disk (oldest pruned past this); the
#: default covers a 30d SLO window at 60s granularity with headroom
ROLLUP_KEEP_ENV = "GORDO_TPU_SLO_ROLLUP_KEEP"
DEFAULT_ROLLUP_KEEP = 50_000
#: seconds a dead worker's fully-consumed trace chain must sit
#: unwritten before the reducer garbage-collects it (0 disables sink
#: GC entirely — e.g. an aggregator in another pid namespace, where
#: the liveness probe cannot see the writers)
SINK_GC_AGE_ENV = "GORDO_TPU_SLO_SINK_GC_AGE"
DEFAULT_SINK_GC_AGE = 24 * 3600.0

#: fixed latency bucket upper edges (ms) — fixed so histograms merge
#: across workers, windows and hosts by pure count addition; the +Inf
#: overflow bucket is implicit as the last counts slot
LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 350.0, 500.0,
    750.0, 1000.0, 1500.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
)

#: span names that are never request *stages* (see trace_analysis)
_NON_STAGE_NAMES = frozenset(
    (
        "request",
        "profile",
        "serve_batch",
        "stream_ingest",
        "stream_score",
        "stream_emit",
    )
)


def window_seconds() -> int:
    from ..utils.env import env_int

    return max(1, env_int(WINDOW_SECONDS_ENV, DEFAULT_WINDOW_SECONDS))


def rollup_keep() -> int:
    from ..utils.env import env_int

    return max(1, env_int(ROLLUP_KEEP_ENV, DEFAULT_ROLLUP_KEEP))


def manifest_enabled() -> bool:
    from ..utils.env import env_bool

    return env_bool(ROLLUP_MANIFEST_ENV, True)


def sink_window_index(directory: str) -> Dict[str, Dict[str, Any]]:
    """Per-sink span-time windows from the rollup manifest: sink file
    basename -> ``{"min_ts", "max_ts", "complete"}`` (epoch seconds of
    the spans the reducer consumed; ``complete`` means it reached EOF,
    so the window covers the whole file). ``{}`` when no manifest —
    callers fall back to mtime heuristics. This is what lets
    ``gordo-tpu trace --since`` skip whole rotated generations by
    recorded span window instead of trusting filesystem mtimes."""
    path = os.path.join(directory, ROLLUP_DIR, ROLLUP_MANIFEST_FILE)
    try:
        with open(path) as handle:
            doc = json.load(handle)
    except (OSError, ValueError):
        return {}
    sinks = doc.get("sinks") if isinstance(doc, dict) else None
    if not isinstance(sinks, dict):
        return {}
    return {
        str(name): entry
        for name, entry in sinks.items()
        if isinstance(entry, dict)
    }


def parse_span_time(value: Any) -> Optional[float]:
    """Epoch seconds from a recorded span timestamp (the recorder's
    UTC isoformat); None when unparseable."""
    if not isinstance(value, str) or not value:
        return None
    try:
        stamp = datetime.fromisoformat(value)
    except ValueError:
        return None
    if stamp.tzinfo is None:
        stamp = stamp.replace(tzinfo=timezone.utc)
    return stamp.timestamp()


# -- the mergeable histogram --------------------------------------------------


def new_histogram() -> Dict[str, Any]:
    """An empty fixed-bucket latency histogram (counts has one overflow
    slot past the last edge)."""
    return {
        "buckets_ms": list(LATENCY_BUCKETS_MS),
        "counts": [0] * (len(LATENCY_BUCKETS_MS) + 1),
        "count": 0,
        "sum_ms": 0.0,
    }


def histogram_add(histogram: Dict[str, Any], value_ms: float) -> None:
    edges = histogram["buckets_ms"]
    slot = len(edges)
    for i, edge in enumerate(edges):
        if value_ms <= edge:
            slot = i
            break
    histogram["counts"][slot] += 1
    histogram["count"] += 1
    histogram["sum_ms"] = round(histogram["sum_ms"] + value_ms, 3)


def histogram_merge(into: Dict[str, Any], other: Dict[str, Any]) -> None:
    """Fold ``other`` into ``into`` (same fixed edges by construction;
    a rollup written under different edges merges by value re-binning
    of its bucket midpoints — lossy but monotone)."""
    if other.get("buckets_ms") == into["buckets_ms"]:
        for i, count in enumerate(other.get("counts", ())):
            if i < len(into["counts"]):
                into["counts"][i] += int(count)
    else:  # edge-set drift between versions: re-bin by midpoint
        edges = other.get("buckets_ms") or []
        lower = 0.0
        for i, count in enumerate(other.get("counts", ())):
            if not count:
                continue
            upper = edges[i] if i < len(edges) else lower * 2 or 1.0
            midpoint = (lower + upper) / 2.0
            for _ in range(int(count)):
                histogram_add(into, midpoint)
            into["count"] -= int(count)  # re-added below with the totals
            into["sum_ms"] = round(into["sum_ms"] - midpoint * count, 3)
            lower = upper
    into["count"] += int(other.get("count", 0))
    into["sum_ms"] = round(into["sum_ms"] + float(other.get("sum_ms", 0.0)), 3)


def histogram_percentile(histogram: Dict[str, Any], q: float) -> float:
    """Percentile estimate (ms) by linear interpolation inside the
    containing bucket; the overflow bucket reports the top edge."""
    total = histogram.get("count", 0)
    if not total:
        return 0.0
    rank = q * total
    edges = histogram["buckets_ms"]
    cumulative = 0
    lower = 0.0
    for i, count in enumerate(histogram["counts"]):
        if not count:
            if i < len(edges):
                lower = edges[i]
            continue
        if cumulative + count >= rank:
            if i >= len(edges):
                return round(lower, 3)
            upper = edges[i]
            inside = max(0.0, min(1.0, (rank - cumulative) / count))
            return round(lower + (upper - lower) * inside, 3)
        cumulative += count
        if i < len(edges):
            lower = edges[i]
    return round(lower, 3)


# -- sink discovery -----------------------------------------------------------


_ROTATION_SUFFIX_RE = re.compile(r"\.(\d+)$")


def is_worker_variant(name: str, base_name: str) -> bool:
    """True when ``name`` is a per-worker variant of ``base_name``
    (``serve_trace-<pid>.jsonl`` for ``serve_trace.jsonl``), rotation
    suffix NOT included — THE one spelling of the worker-sink grammar
    (``recorder.worker_sink_path`` writes it; this reads it; the
    serializer's dropping predicate and the health-snapshot walk both
    delegate here)."""
    stem, ext = os.path.splitext(base_name)
    return name.startswith(stem + "-") and name.endswith(ext)


def sink_bases(directory: str, base_name: str) -> List[str]:
    """Every base sink path in ``directory`` for one logical sink: the
    shared spelling (``serve_trace.jsonl``) plus every per-worker
    variant (``serve_trace-<pid>.jsonl``) — rotated generations ride
    each base (``<base>.N``). A base whose live file is momentarily
    absent (the writer's rotation renames it away and recreates it on
    the next write) is still discovered through its generations."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return []
    bases = set()
    for entry in entries:
        root = _ROTATION_SUFFIX_RE.sub("", entry)
        if root == base_name or is_worker_variant(root, base_name):
            bases.add(os.path.join(directory, root))
    return sorted(bases)


def generation_files(base_path: str) -> List[str]:
    """All physical files of one sink, oldest first (``p.N`` ... ``p.1``,
    then ``p``). Generations come from the directory listing, not a
    ``while exists`` walk: mid-rotation the ``.1`` slot is briefly empty
    while higher generations still hold bytes, and a probe walk would
    go blind to all of them for the pass."""
    directory, name = os.path.split(base_path)
    try:
        entries = os.listdir(directory or ".")
    except OSError:
        entries = []
    generations = []
    prefix = name + "."
    for entry in entries:
        if entry.startswith(prefix) and entry[len(prefix):].isdigit():
            generations.append((int(entry[len(prefix):]), entry))
    paths = [
        os.path.join(directory, entry)
        for _, entry in sorted(generations, reverse=True)
    ]
    if os.path.exists(base_path):
        paths.append(base_path)
    return paths


def discover_sinks(directory: str) -> List[Tuple[str, str]]:
    """``(kind, physical_path)`` for every trace file in ``directory``:
    kind ``serve`` for request traces, ``build`` for build traces."""
    found: List[Tuple[str, str]] = []
    for kind, base_name in (
        ("serve", SERVE_TRACE_FILE),
        ("build", BUILD_TRACE_FILE),
    ):
        for base in sink_bases(directory, base_name):
            for path in generation_files(base):
                found.append((kind, path))
    return found


_WORKER_PID_RE = re.compile(r"-(\d+)$")


def _worker_pid(name: str, base_name: str) -> Optional[int]:
    """The pid baked into a worker-variant sink name, or None for the
    shared spelling."""
    if not is_worker_variant(name, base_name):
        return None
    stem, _ = os.path.splitext(name)
    match = _WORKER_PID_RE.search(stem)
    return int(match.group(1)) if match else None


def _pid_alive(pid: int) -> bool:
    """Best-effort liveness probe (signal 0). Unknown errors count as
    alive — deleting a live worker's sink is the only unsafe answer."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True
    return True


def _signature_from_head(head: bytes) -> Optional[str]:
    """The content identity from a sink's first bytes, or None when the
    file has no COMPLETE first line yet. The basis is the first line
    (newline inclusive), capped at 256 bytes: append-only files never
    change it, so the signature is stable across the file's whole life.
    Hashing a raw 256-byte prefix is NOT — a file whose only line is
    shorter than 256 bytes would change identity when line two lands,
    orphaning its saved offset and double-folding line one."""
    if not head:
        return "empty"
    newline = head.find(b"\n")
    if newline != -1:
        head = head[: newline + 1]
    elif len(head) < 256:
        # a torn, still-growing first line: nothing complete to read,
        # and any prefix hash would be unstable — identify it next pass
        return None
    return hashlib.sha1(head).hexdigest()[:20]


def file_signature(path: str) -> Optional[str]:
    """A content identity for resume offsets that survives rotation:
    the hash of the file's first line (span lines carry random ids, so
    it is unique per file — see :func:`_signature_from_head`). Rotation
    renames the file but keeps its bytes, so the signature follows
    them. None when the file is gone or holds no complete line yet;
    empty files share the ``empty`` signature (offset 0 anyway)."""
    try:
        with open(path, "rb") as handle:
            head = handle.read(256)
    except OSError:
        return None
    return _signature_from_head(head)


# -- the rollup reducer -------------------------------------------------------


def _empty_rollup(start: int, seconds: int) -> Dict[str, Any]:
    return {
        "version": 1,
        "window": {
            "start": start,
            "seconds": seconds,
            "start_iso": datetime.fromtimestamp(
                start, timezone.utc
            ).isoformat(),
        },
        "requests": {
            "count": 0,
            "errors": 0,
            "by_class": {"2xx": 0, "3xx": 0, "4xx": 0, "5xx": 0},
        },
        "latency_ms": new_histogram(),
        "stages": {},
        "machines": {},
        "build": {"device_programs": 0, "compiles": 0, "phases": {}},
        "stream": _empty_stream_section(),
        "spans": 0,
    }


def _empty_stream_section() -> Dict[str, Any]:
    """The streaming-plane rollup section: row accounting, flush count,
    flush-duration and rows-weighted ingest→scored lag histograms —
    folded from ``stream_score`` spans, merged like everything else, and
    read by the stream SLOs (freshness = lag_ms fraction under
    threshold, integrity = non-shed/non-failed row fraction)."""
    return {
        "rows_in": 0,
        "rows_scored": 0,
        "rows_failed": 0,
        "rows_shed": 0,
        "flushes": 0,
        "windows": 0,
        "flush_ms": new_histogram(),
        "lag_ms": new_histogram(),
    }


def merge_rollups(into: Dict[str, Any], other: Dict[str, Any]) -> Dict[str, Any]:
    """Fold rollup ``other`` into ``into`` (same window or a wider
    aggregate — counts add, histograms merge). Returns ``into``."""
    requests = into["requests"]
    other_requests = other.get("requests") or {}
    requests["count"] += int(other_requests.get("count", 0))
    requests["errors"] += int(other_requests.get("errors", 0))
    for klass, count in (other_requests.get("by_class") or {}).items():
        requests["by_class"][klass] = (
            requests["by_class"].get(klass, 0) + int(count)
        )
    if other.get("latency_ms"):
        histogram_merge(into["latency_ms"], other["latency_ms"])
    for stage, histogram in (other.get("stages") or {}).items():
        mine = into["stages"].setdefault(stage, new_histogram())
        histogram_merge(mine, histogram)
    for machine, counts in (other.get("machines") or {}).items():
        mine = into["machines"].setdefault(
            machine, {"requests": 0, "errors": 0}
        )
        mine["requests"] += int(counts.get("requests", 0))
        mine["errors"] += int(counts.get("errors", 0))
    build = into["build"]
    other_build = other.get("build") or {}
    build["device_programs"] += int(other_build.get("device_programs", 0))
    build["compiles"] += int(other_build.get("compiles", 0))
    for phase, count in (other_build.get("phases") or {}).items():
        build["phases"][phase] = build["phases"].get(phase, 0) + int(count)
    stream = into.setdefault("stream", _empty_stream_section())
    other_stream = other.get("stream")
    if other_stream:  # pre-upgrade rollups have no stream section
        for key in (
            "rows_in",
            "rows_scored",
            "rows_failed",
            "rows_shed",
            "flushes",
            "windows",
        ):
            stream[key] += int(other_stream.get(key, 0))
        if other_stream.get("flush_ms"):
            histogram_merge(stream["flush_ms"], other_stream["flush_ms"])
        if other_stream.get("lag_ms"):
            histogram_merge(stream["lag_ms"], other_stream["lag_ms"])
    into["spans"] = int(into.get("spans", 0)) + int(other.get("spans", 0))
    return into


def _fold_span(rollup: Dict[str, Any], kind: str, span: Dict[str, Any]) -> None:
    """One span into one window rollup."""
    rollup["spans"] += 1
    name = span.get("name", "")
    duration_ms = float(span.get("duration_ms", 0.0) or 0.0)
    if kind == "build":
        build = rollup["build"]
        if name == "device_program":
            build["device_programs"] += 1
            if (span.get("attributes") or {}).get("compile"):
                build["compiles"] += 1
        elif name == "build_phase":
            phase = str((span.get("attributes") or {}).get("phase", "?"))
            build["phases"][phase] = build["phases"].get(phase, 0) + 1
        return
    if span.get("kind") == "event":
        return
    if name in ("stream_ingest", "stream_score"):
        _fold_stream_span(rollup, name, span, duration_ms)
        return
    if name == "request":
        attributes = span.get("attributes") or {}
        requests = rollup["requests"]
        requests["count"] += 1
        try:
            status = int(attributes.get("http.status_code", 0))
        except (TypeError, ValueError):
            status = 0
        klass = f"{status // 100}xx" if 200 <= status < 600 else "2xx"
        requests["by_class"][klass] = requests["by_class"].get(klass, 0) + 1
        error = status >= 500
        if error:
            requests["errors"] += 1
        histogram_add(rollup["latency_ms"], duration_ms)
        machine = str(attributes.get("gordo_name") or "")
        if machine:
            record = rollup["machines"].setdefault(
                machine, {"requests": 0, "errors": 0}
            )
            record["requests"] += 1
            if error:
                record["errors"] += 1
    elif name not in _NON_STAGE_NAMES and span.get("parent_id"):
        stage = rollup["stages"].setdefault(name, new_histogram())
        histogram_add(stage, duration_ms)


def _fold_stream_span(
    rollup: Dict[str, Any],
    name: str,
    span: Dict[str, Any],
    duration_ms: float,
) -> None:
    """Fold one streaming-plane span into the rollup's ``stream``
    section. ``stream_ingest`` contributes row arrivals; ``stream_score``
    (one per flush) contributes the scored/failed/shed split, the flush
    duration, and its pre-binned rows-weighted lag histogram — the
    per-span ``lag_hist`` shares :data:`LATENCY_BUCKETS_MS`, so the
    fold is an elementwise add, no re-binning."""
    stream = rollup.setdefault("stream", _empty_stream_section())
    attributes = span.get("attributes") or {}
    if name == "stream_ingest":
        stream["rows_in"] += int(attributes.get("rows", 0) or 0)
        return
    stream["flushes"] += 1
    stream["windows"] += int(attributes.get("windows", 0) or 0)
    scored = attributes.get("rows_scored")
    if scored is None:  # early-exit flushes never stamp the split
        scored = attributes.get("rows", 0)
    stream["rows_scored"] += int(scored or 0)
    stream["rows_failed"] += int(attributes.get("rows_failed", 0) or 0)
    stream["rows_shed"] += int(attributes.get("shed", 0) or 0)
    histogram_add(stream["flush_ms"], duration_ms)
    lag = stream["lag_ms"]
    counts = attributes.get("lag_hist")
    if (
        isinstance(counts, (list, tuple))
        and len(counts) == len(lag["counts"])
    ):
        folded = 0
        for i, count in enumerate(counts):
            count = int(count or 0)
            lag["counts"][i] += count
            folded += count
        lag["count"] += folded
        lag["sum_ms"] += float(attributes.get("lag_sum_ms", 0.0) or 0.0)


class RollupStore:
    """Incremental reducer + rollup persistence for one directory.

    Thread-safe per instance; distinct processes aggregating the same
    directory are safe too (atomic artifact replaces; at worst two
    concurrent reducers fold the same new spans — the per-file offsets
    are re-read under the instance lock and rollup updates are
    last-writer-wins per window, so the drill below pins single-reducer
    exactness and multi-reducer convergence is advisory)."""

    def __init__(self, directory: str, seconds: Optional[int] = None):
        self.directory = os.path.normpath(directory)
        self.rollup_dir = os.path.join(self.directory, ROLLUP_DIR)
        self.state_path = os.path.join(self.rollup_dir, ROLLUP_STATE_FILE)
        self.manifest_path = os.path.join(
            self.rollup_dir, ROLLUP_MANIFEST_FILE
        )
        self.seconds = int(seconds) if seconds else window_seconds()
        #: the manifest this store last wrote (authoritative in the
        #: aggregating process; reader-only processes re-load from disk)
        self._manifest: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()
        #: bumped whenever a rollup file changes (fold or prune) — the
        #: merge cache's invalidation token
        self._version = 0
        #: (since, until, version) -> merged doc; re-polling an
        #: unchanged corpus (scrape refresh over an idle service) must
        #: not re-read every rollup file. Busy dirs still pay one full
        #: window walk per refresh once the corpus spans weeks — the
        #: known scaling edge; coarser rollup tiers are the multi-host
        #: roadmap item's follow-up.
        self._merged_cache: Dict[Tuple[Any, Any, int], Dict[str, Any]] = {}

    # -- paths / IO ---------------------------------------------------------

    def window_start(self, ts: float) -> int:
        return int(ts // self.seconds) * self.seconds

    def rollup_path(self, start: int) -> str:
        return os.path.join(self.rollup_dir, f"{int(start)}.json")

    def _load_json(self, path: str) -> Optional[Any]:
        try:
            with open(path) as handle:
                return json.load(handle)
        except (OSError, ValueError):
            return None

    def _write_json(self, path: str, doc: Any) -> None:
        # stage + os.replace in this function: the atomic-write contract
        # for telemetry artifacts (a crash mid-dump must never leave a
        # half-written rollup where the SLO engine would read it)
        tmp = os.path.join(
            os.path.dirname(path),
            f".{os.path.basename(path)}.tmp-{os.getpid()}",
        )
        with open(tmp, "w") as handle:
            json.dump(doc, handle, sort_keys=True)
        os.replace(tmp, path)

    # -- aggregation --------------------------------------------------------

    def aggregate(self) -> Dict[str, Any]:
        """Fold every unread span in the directory's sinks into the
        window rollups. Returns a summary (spans read, windows touched,
        files visited). Incremental: per-file offsets resume by content
        signature, so an unchanged corpus costs a stat-walk."""
        with self._lock:
            return self._aggregate_locked()

    def _aggregate_locked(self) -> Dict[str, Any]:
        os.makedirs(self.rollup_dir, exist_ok=True)
        state = self._load_json(self.state_path)
        previous: Dict[str, Dict[str, Any]] = (
            dict(state.get("files") or {}) if isinstance(state, dict) else {}
        )
        # rebuilt per pass. Signatures NOT visited this pass are carried
        # forward for a few passes before they are dropped ("misses"
        # counter): a writer's mid-rotation rename can hide a file for
        # one walk, and forgetting its offset would re-read its bytes —
        # the exact double-count this reducer exists to prevent. Files
        # gone for good (the keep policy deleted them) age out, so the
        # state cannot grow without bound either.
        files: Dict[str, Dict[str, Any]] = {}
        seen_ids: set = set()
        windows: Dict[int, Dict[str, Any]] = {}
        spans_read = 0
        visited = 0
        for kind, path in discover_sinks(self.directory):
            # signature and read share ONE file descriptor: computing
            # the signature by path and reopening would race the
            # writer's rotation — the old signature's offset would bind
            # to the freshly-created file and both chains would corrupt
            result = self._read_file(
                kind, path, previous, files, seen_ids, windows
            )
            if result is None:
                continue
            visited += 1
            signature, offset = result
            spans_read += offset["spans"]
            files[signature] = {
                "offset": offset["offset"],
                "path": path,
                "complete": bool(offset.get("eof")),
            }
            if offset.get("min_ts") is not None:
                files[signature]["min_ts"] = offset["min_ts"]
            if offset.get("max_ts") is not None:
                files[signature]["max_ts"] = offset["max_ts"]
        for signature, entry in previous.items():
            if signature in files:
                continue
            misses = int(entry.get("misses", 0)) + 1
            if misses <= 8:
                files[signature] = {**entry, "misses": misses}
        # window rollups land BEFORE the offsets: a crash between the
        # two atomic writes re-reads (and re-folds) the tail once — the
        # deliberate at-least-once choice, because the alternative
        # ordering silently DROPS spans, and an alerting pipeline must
        # fail toward noticing errors, never toward missing them
        persisted = self._persist_windows(windows)
        updated = sorted(persisted)
        pruned = self._prune()
        sinks_pruned = self._prune_dead_worker_sinks(files)
        if updated or pruned:
            self._version += 1
            self._merged_cache.clear()
        if manifest_enabled():
            self._update_manifest(persisted, pruned, files)
        self._write_json(
            self.state_path,
            {
                "version": 1,
                "seconds": self.seconds,
                "files": files,
            },
        )
        return {
            "spans_read": spans_read,
            "files_visited": visited,
            "windows_updated": updated,
            "rollups_pruned": len(pruned),
            "worker_sinks_pruned": sinks_pruned,
        }

    def _prune_dead_worker_sinks(
        self, files: Dict[str, Dict[str, Any]]
    ) -> int:
        """Delete trace sinks of DEAD workers once fully consumed and
        cold.

        Worker recycling (gunicorn --max-requests) mints a fresh
        ``serve_trace-<pid>.jsonl`` chain per worker lifetime; nothing
        else ever deletes the old pids' chains, so a months-lived
        deployment accumulates sinks (each with its own rotation KEEP
        budget) without bound. A chain is removed only when (a) its pid
        no longer exists, (b) every byte of every generation is already
        folded into the rollups — the reducer is the sink's only
        consumer with the offsets to prove that — and (c) nothing has
        written it for ``GORDO_TPU_SLO_SINK_GC_AGE`` (the pid probe is
        blind across pid namespaces/hosts, so a *quiet day* is required
        evidence too; set the knob to 0 there to disable GC outright —
        and the writers re-open a sink deleted under them anyway, see
        ``SpanRecorder``'s unlink check). Health snapshots
        (``fleet_health-<pid>.json``) are NOT touched: they are tiny,
        and deleting one would erase that worker's counts from every
        future merge."""
        from ..utils.env import env_float

        age_s = env_float(SINK_GC_AGE_ENV, DEFAULT_SINK_GC_AGE)
        age_s = DEFAULT_SINK_GC_AGE if age_s is None else age_s
        if age_s <= 0:
            return 0
        consumed_to: Dict[str, int] = {
            entry["path"]: int(entry.get("offset", 0))
            for entry in files.values()
            if entry.get("path")
        }
        now = time.time()
        removed = 0
        for base_name in (SERVE_TRACE_FILE, BUILD_TRACE_FILE):
            for base in sink_bases(self.directory, base_name):
                pid = _worker_pid(os.path.basename(base), base_name)
                if pid is None or pid == os.getpid() or _pid_alive(pid):
                    continue
                chain = generation_files(base)
                removable = True
                for path in chain:
                    try:
                        stat = os.stat(path)
                    except OSError:
                        continue
                    if (
                        stat.st_size > consumed_to.get(path, 0)
                        or now - stat.st_mtime < age_s
                    ):
                        removable = False
                        break
                if not removable:
                    continue
                for path in chain:
                    try:
                        os.remove(path)
                        removed += 1
                    except OSError:
                        pass
        return removed

    def _read_file(
        self,
        kind: str,
        path: str,
        previous: Dict[str, Dict[str, Any]],
        files: Dict[str, Dict[str, Any]],
        seen_ids: set,
        windows: Dict[int, Dict[str, Any]],
    ) -> Optional[Tuple[str, Dict[str, Any]]]:
        """Open ``path`` once, identify it by content signature from the
        SAME descriptor, resume at the signature's saved offset and fold
        every complete new line. Returns ``(signature, {spans, offset})``
        or None when the file vanished. The descriptor is the identity
        anchor: once open, the writer renaming the path cannot swap a
        different file's bytes under the saved offset."""
        try:
            handle = open(path, "rb")
        except OSError:
            return None
        with handle:
            head = handle.read(256)
            if not head:
                return ("empty", {"spans": 0, "offset": 0, "eof": True})
            signature = _signature_from_head(head)
            if signature is None:
                # no complete first line yet — nothing foldable either
                return None
            entry = previous.get(signature) or files.get(signature) or {}
            offset = int(entry.get("offset", 0))
            # span-time window accumulated across passes (the manifest's
            # per-sink index): an incremental read only sees new spans,
            # so fold this pass's range into the carried one
            min_ts = entry.get("min_ts")
            max_ts = entry.get("max_ts")
            spans = 0

            def result(position: int, eof: bool) -> Tuple[str, Dict[str, Any]]:
                return (
                    signature,
                    {
                        "spans": spans,
                        "offset": position,
                        "eof": eof,
                        "min_ts": min_ts,
                        "max_ts": max_ts,
                    },
                )

            try:
                size = os.fstat(handle.fileno()).st_size
                if size <= offset:
                    # fully consumed (rotated generations are immutable,
                    # the live file simply has nothing new)
                    return result(offset, True)
                handle.seek(offset)
                # byte positions are tracked by hand: BufferedReader.tell()
                # costs ~40us and a per-line tell() was 40% of the whole
                # aggregation pass
                position = offset
                while True:
                    line = handle.readline()
                    if not line:
                        break
                    if not line.endswith(b"\n"):
                        # a torn tail the writer is mid-appending: leave
                        # the offset BEFORE it so the next pass rereads
                        # the completed line exactly once
                        return result(position, False)
                    position += len(line)
                    text = line.strip()
                    if not text:
                        continue
                    try:
                        span = json.loads(text.decode("utf-8", "replace"))
                    except ValueError:
                        continue
                    if not isinstance(span, dict) or "name" not in span:
                        continue
                    ts = parse_span_time(span.get("end_time"))
                    if ts is not None:
                        # the sink's span window counts every span seen,
                        # duplicates included — a generation holding only
                        # dupes still gets an honest window
                        if min_ts is None or ts < min_ts:
                            min_ts = ts
                        if max_ts is None or ts > max_ts:
                            max_ts = ts
                    context = span.get("context") or {}
                    span_key = (
                        context.get("trace_id", ""),
                        context.get("span_id", ""),
                    )
                    if span_key != ("", ""):
                        if span_key in seen_ids:
                            continue  # duplicated across sinks/generations
                        seen_ids.add(span_key)
                    if ts is None:
                        continue
                    start = self.window_start(ts)
                    rollup = windows.get(start)
                    if rollup is None:
                        rollup = windows[start] = _empty_rollup(
                            start, self.seconds
                        )
                    _fold_span(rollup, kind, span)
                    spans += 1
                return result(position, True)
            except OSError:
                return result(offset, False)

    def _persist_windows(
        self, windows: Dict[int, Dict[str, Any]]
    ) -> Dict[int, Dict[str, Any]]:
        persisted: Dict[int, Dict[str, Any]] = {}
        for start, delta in windows.items():
            path = self.rollup_path(start)
            existing = self._load_json(path)
            if isinstance(existing, dict) and existing.get("window"):
                merged = merge_rollups(existing, delta)
                # merge_rollups adds counts into `existing` in place but
                # leaves its fixed window header intact
                doc = merged
            else:
                doc = delta
            self._write_json(path, doc)
            persisted[start] = doc
        return persisted

    def _prune(self) -> List[int]:
        keep = rollup_keep()
        try:
            entries = sorted(
                entry
                for entry in os.listdir(self.rollup_dir)
                if entry.endswith(".json")
                and entry[: -len(".json")].isdigit()
            )
        except OSError:
            return []
        doomed = entries[:-keep] if len(entries) > keep else []
        removed = []
        for entry in doomed:
            try:
                os.remove(os.path.join(self.rollup_dir, entry))
            except OSError:
                continue
            removed.append(int(entry[: -len(".json")]))
        return removed

    def _update_manifest(
        self,
        persisted: Dict[int, Dict[str, Any]],
        pruned: List[int],
        files: Dict[str, Dict[str, Any]],
    ) -> None:
        """Fold this pass's window updates into ``manifest.json``: the
        window -> file map (with per-window request summaries) readers
        select from, plus the per-sink span-time index the trace CLI
        uses to skip whole rotated generations. Rebuilt from a directory
        listing when absent (the one walk that makes all later reads
        walk-free)."""
        manifest = self._manifest
        if manifest is None:
            manifest = self._load_json(self.manifest_path)
        window_map: Dict[str, Dict[str, Any]] = {}
        if (
            isinstance(manifest, dict)
            and isinstance(manifest.get("windows"), dict)
            and int(manifest.get("seconds") or 0) == self.seconds
        ):
            window_map = dict(manifest["windows"])
        else:
            try:
                for entry in os.listdir(self.rollup_dir):
                    if (
                        entry.endswith(".json")
                        and entry[: -len(".json")].isdigit()
                    ):
                        window_map[entry[: -len(".json")]] = {"file": entry}
            except OSError:
                window_map = {}
        for start, doc in persisted.items():
            requests = doc.get("requests") or {}
            window_map[str(int(start))] = {
                "file": f"{int(start)}.json",
                "requests": int(requests.get("count") or 0),
                "errors": int(requests.get("errors") or 0),
            }
        for start in pruned:
            window_map.pop(str(int(start)), None)
        sinks: Dict[str, Dict[str, Any]] = {}
        for entry in files.values():
            path = entry.get("path")
            if not path or entry.get("max_ts") is None:
                continue
            sinks[os.path.basename(path)] = {
                "min_ts": entry.get("min_ts"),
                "max_ts": entry.get("max_ts"),
                "complete": bool(entry.get("complete")),
            }
        doc = {
            "version": 1,
            "seconds": self.seconds,
            "updated_at": time.time(),
            "windows": window_map,
            "sinks": sinks,
        }
        try:
            self._write_json(self.manifest_path, doc)
        except OSError as exc:
            logger.debug("rollup manifest not written: %r", exc)
            return
        self._manifest = doc

    # -- reading back -------------------------------------------------------

    def _manifest_windows(self) -> Optional[List[int]]:
        """Window starts from the manifest (sorted), or None when the
        manifest is disabled/absent/incompatible — readers then fall
        back to the directory walk. The in-memory copy is used only by
        the aggregating process (it is authoritative there); everyone
        else re-loads the file, which is one open instead of a listdir
        over tens of thousands of entries."""
        if not manifest_enabled():
            return None
        doc = self._manifest
        if doc is None:
            doc = self._load_json(self.manifest_path)
        if (
            not isinstance(doc, dict)
            or not isinstance(doc.get("windows"), dict)
            or int(doc.get("seconds") or 0) != self.seconds
        ):
            return None
        try:
            return sorted(int(start) for start in doc["windows"])
        except (TypeError, ValueError):
            return None

    def windows(
        self,
        since: Optional[float] = None,
        until: Optional[float] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Persisted rollups whose window overlaps [since, until],
        oldest first. With a manifest, only the overlapping files are
        ever opened (the scale contract a counting-open test pins);
        without one, the directory walk selects by name."""
        starts = self._manifest_windows()
        if starts is None:
            try:
                starts = sorted(
                    int(entry[: -len(".json")])
                    for entry in os.listdir(self.rollup_dir)
                    if entry.endswith(".json")
                    and entry[: -len(".json")].isdigit()
                )
            except OSError:
                return
        for start in starts:
            if since is not None and start + self.seconds <= since:
                continue
            if until is not None and start >= until:
                continue
            doc = self._load_json(self.rollup_path(start))
            if isinstance(doc, dict) and doc.get("window"):
                yield doc

    def merged(
        self,
        since: Optional[float] = None,
        until: Optional[float] = None,
    ) -> Dict[str, Any]:
        """One aggregate rollup over every window in [since, until]
        (the SLO engine's unit of evaluation). Cached per (bounds,
        corpus version): repeated evaluations over an unchanged corpus
        cost a dict lookup, not a re-read of every rollup file. Bounds
        quantize to the window grid — two calls in the same window see
        the same window set."""
        key = (
            self.window_start(since) if since is not None else None,
            self.window_start(until) if until is not None else None,
            self._version,
        )
        cached = self._merged_cache.get(key)
        if cached is not None:
            return json.loads(json.dumps(cached))
        merged = _empty_rollup(int(since or 0), self.seconds)
        count = 0
        for rollup in self.windows(since=since, until=until):
            merge_rollups(merged, rollup)
            count += 1
        merged["window"]["merged_windows"] = count
        if since is not None:
            merged["window"]["since"] = int(since)
        if until is not None:
            merged["window"]["until"] = int(until)
        # deep-copy OUTSIDE the lock (a month's busy-dir rollup is large
        # — serializing it under the lock would convoy concurrent folds)
        copied = json.loads(json.dumps(merged))
        with self._lock:
            # the insert itself sits under the instance lock like the
            # fold-side invalidation (version bump + clear): racing the
            # clear could otherwise resurrect a pre-invalidation doc
            # and pin the cache-size accounting stale
            if len(self._merged_cache) > 64:
                self._merged_cache.clear()
            self._merged_cache[key] = copied
        return merged


# -- the per-directory store registry -----------------------------------------

_stores_lock = threading.Lock()
_stores: Dict[Tuple[str, int], "RollupStore"] = {}


def store_for(directory: str, seconds: Optional[int] = None) -> RollupStore:
    """The (create-once) :class:`RollupStore` for a directory. A
    store's instance lock is what serializes concurrent aggregation —
    a scrape-thread evaluation racing a /slo route evaluation through
    two fresh instances would each fold the same new spans into the
    same window rollup (last-writer-wins would keep BOTH folds).
    Callers that want serialization must share the instance; this is
    the one place they get it."""
    key = (os.path.normpath(directory), int(seconds) if seconds else window_seconds())
    store = _stores.get(key)
    if store is not None:
        return store
    with _stores_lock:
        store = _stores.get(key)
        if store is None:
            store = _stores[key] = RollupStore(key[0], seconds=key[1])
    return store


def summarize_rollup(rollup: Dict[str, Any]) -> Dict[str, Any]:
    """The headline numbers of one (merged) rollup: request/error
    counts, latency percentiles, per-stage p50/p95, worst machines."""
    requests = rollup.get("requests") or {}
    count = int(requests.get("count", 0))
    errors = int(requests.get("errors", 0))
    latency = rollup.get("latency_ms") or new_histogram()
    stages = {
        name: {
            "count": histogram.get("count", 0),
            "p50_ms": histogram_percentile(histogram, 0.50),
            "p95_ms": histogram_percentile(histogram, 0.95),
        }
        for name, histogram in sorted((rollup.get("stages") or {}).items())
    }
    machines = {
        name: {
            **counts,
            "error_rate": round(
                counts.get("errors", 0) / counts["requests"], 6
            )
            if counts.get("requests")
            else 0.0,
        }
        for name, counts in sorted((rollup.get("machines") or {}).items())
    }
    stream = rollup.get("stream") or _empty_stream_section()
    stream_lag = stream.get("lag_ms") or new_histogram()
    stream_summary = {
        "rows_in": int(stream.get("rows_in", 0)),
        "rows_scored": int(stream.get("rows_scored", 0)),
        "rows_failed": int(stream.get("rows_failed", 0)),
        "rows_shed": int(stream.get("rows_shed", 0)),
        "flushes": int(stream.get("flushes", 0)),
        "windows": int(stream.get("windows", 0)),
        "flush_p50_ms": histogram_percentile(
            stream.get("flush_ms") or new_histogram(), 0.50
        ),
        "flush_p95_ms": histogram_percentile(
            stream.get("flush_ms") or new_histogram(), 0.95
        ),
        "lag_p50_ms": histogram_percentile(stream_lag, 0.50),
        "lag_p95_ms": histogram_percentile(stream_lag, 0.95),
    }
    return {
        "requests": count,
        "errors": errors,
        "error_rate": round(errors / count, 6) if count else 0.0,
        "latency_p50_ms": histogram_percentile(latency, 0.50),
        "latency_p95_ms": histogram_percentile(latency, 0.95),
        "latency_p99_ms": histogram_percentile(latency, 0.99),
        "stages": stages,
        "machines": machines,
        "build": rollup.get("build"),
        "stream": stream_summary,
        "spans": rollup.get("spans", 0),
    }
