"""
Shape buckets for the micro-batching engine.

Every distinct ``(members, rows)`` shape handed to the fused fleet
program mints one XLA compilation, so both serving axes pad up a small
ladder of allowed sizes — the member axis up powers of two bounded by
``GORDO_TPU_BATCH_MAX_SIZE``, the row axis up
``GORDO_TPU_BATCH_ROW_LADDER`` (taller requests fall back unbatched).

The implementation lives in :mod:`gordo_tpu.planner.ladder` — the build
planner quantizes its bucket shapes with the SAME ladder code, so a
planned fleet warms exactly the shapes this engine batches into. This
module re-exports the serve-facing names for compatibility.
"""

from ..planner.ladder import (  # noqa: F401
    DEFAULT_ROW_LADDER,
    ROW_LADDER_ENV,
    member_ladder,
    pad_to,
    parse_ladder,
    row_ladder,
    snap_rows,
)

__all__ = [
    "DEFAULT_ROW_LADDER",
    "ROW_LADDER_ENV",
    "member_ladder",
    "pad_to",
    "parse_ladder",
    "row_ladder",
    "snap_rows",
]
