"""
Shape buckets for the micro-batching engine.

Every distinct ``(members, rows)`` shape handed to the fused fleet
program mints one XLA compilation. Arbitrary client batch sizes would
therefore grow the jit cache without bound — the standard fix in TPU
serving stacks is to pad each axis up a small geometric *ladder* of
allowed sizes, so the compiled-program count per architecture is capped
at ``len(member_ladder) x len(row_ladder)`` while padding waste stays
bounded by the ladder's growth factor.

Two ladders exist because the two axes grow differently:

- the **member axis** (how many coalesced requests share one program)
  is bounded by ``GORDO_TPU_BATCH_MAX_SIZE`` and padded up powers of
  two (worst-case 2x waste, ~log2(max_size) rungs);
- the **row axis** (rows per request) is open-ended client data and
  pads up ``GORDO_TPU_BATCH_ROW_LADDER`` (default geometric, factor 4).
  Requests taller than the top rung fall back to the unbatched path
  rather than minting an unbounded shape.
"""

import os
from typing import Optional, Sequence, Tuple

#: default row-count rungs: factor-4 geometric — 5 programs per member
#: rung, worst-case 4x row padding, typical sensor payloads (tens to a
#: few thousand rows) land in the first three rungs
DEFAULT_ROW_LADDER: Tuple[int, ...] = (32, 128, 512, 2048, 8192)

ROW_LADDER_ENV = "GORDO_TPU_BATCH_ROW_LADDER"


def parse_ladder(text: str) -> Tuple[int, ...]:
    """A comma-separated rung list as a sorted, deduplicated tuple of
    positive ints; raises ``ValueError`` on anything else."""
    rungs = sorted({int(part) for part in text.split(",") if part.strip()})
    if not rungs or rungs[0] <= 0:
        raise ValueError(f"ladder needs positive rungs, got {text!r}")
    return tuple(rungs)


def row_ladder() -> Tuple[int, ...]:
    """The configured row ladder (``GORDO_TPU_BATCH_ROW_LADDER``, falling
    back to :data:`DEFAULT_ROW_LADDER` on absent or malformed values)."""
    raw = os.getenv(ROW_LADDER_ENV)
    if raw:
        try:
            return parse_ladder(raw)
        except ValueError:
            import logging

            logging.getLogger(__name__).warning(
                "Invalid %s=%r; using %r", ROW_LADDER_ENV, raw, DEFAULT_ROW_LADDER
            )
    return DEFAULT_ROW_LADDER


def member_ladder(max_size: int) -> Tuple[int, ...]:
    """Powers of two up to (and including) the padded ``max_size``:
    the allowed member-axis shapes of one fused batch."""
    rungs = []
    rung = 1
    while rung < max_size:
        rungs.append(rung)
        rung <<= 1
    rungs.append(rung)
    return tuple(rungs)


def pad_to(n: int, ladder: Sequence[int]) -> Optional[int]:
    """The first rung >= ``n``, or None when ``n`` overflows the ladder
    (the caller's cue to fall back to an unbatched path)."""
    for rung in ladder:
        if n <= rung:
            return rung
    return None
