"""
The cross-request micro-batcher: request-lifecycle machinery only.

Concurrent single-model requests enqueue :class:`BatchItem`\\ s keyed by
an opaque batch key (the engine keys by ``(revision fleet, spec)`` — only
same-architecture requests can share a fused program). Dispatcher
thread(s) drain the queues under an adaptive flush policy and hand each
drained batch to the ``runner`` callable the owner supplied; results
travel back through per-request ``concurrent.futures.Future``\\ s.

Flush policy — a key's queue is ready when ANY of:

- **size**: it holds ``max_size`` items (a full program's worth);
- **deadline**: its oldest item has waited ``max_delay_s`` (bounds the
  latency cost of coalescing);
- **pressure**: total queued items across keys reached
  ``pressure_depth`` (under load there is no point waiting for more —
  the queue itself provides the coalescing).

Admission control — overload degrades instead of OOMing the host:

- a full queue (``queue_depth`` items pending) rejects new work with
  :class:`QueueFullError` (the server maps it to 429 + ``Retry-After``);
- each item carries an absolute deadline; items that expire before
  their batch runs get :class:`DeadlineExceeded` (504), and callers
  that stop waiting cancel their future so the runner skips the row.

This module is deliberately device-free (pure stdlib threading) so the
scheduling behavior is testable without JAX in the loop.
"""

import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, Hashable, List, Optional

logger = logging.getLogger(__name__)


class BatchShedError(Exception):
    """Base of the admission-control rejections."""


class QueueFullError(BatchShedError):
    """The batch queue is at capacity; retry after ``retry_after_s``."""

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(f"batch queue full ({depth} requests pending)")
        self.retry_after_s = retry_after_s


class DeadlineExceeded(BatchShedError):
    """The request's batching deadline passed before its batch ran."""


class BatcherStopped(BatchShedError):
    """Submit after shutdown began — callers fall back to unbatched."""


def clone_exception(exc: BaseException) -> BaseException:
    """A fresh exception instance carrying ``exc``'s type and message.

    Futures fan one batch failure out to N waiting request threads; each
    must get its OWN instance (``raise`` mutates the instance's
    ``__traceback__``, so one object re-raised from N handler threads is
    a data race). The original rides along as ``__cause__`` for the
    first-class server log; exception types whose constructor rejects a
    bare message degrade to ``RuntimeError``.
    """
    try:
        clone = type(exc)(*exc.args)
        if not isinstance(clone, type(exc)):  # an odd __new__ contract
            raise TypeError
    except Exception:  # noqa: BLE001 - ctor signature we can't satisfy
        clone = RuntimeError(f"batch runner failed: {exc!r}")
    clone.__cause__ = exc
    return clone


class BatchItem:
    """One enqueued request: the payload the runner scores, the future
    the waiting request thread holds, and the admission bookkeeping.
    ``trace`` optionally carries the submitting request's W3C trace
    context as ``(trace_id, span_id)`` so the fused batch span can link
    back to the request spans it coalesced."""

    __slots__ = (
        "name",
        "payload",
        "future",
        "enqueued_at",
        "deadline",
        "rows",
        "trace",
    )

    def __init__(
        self,
        name: str,
        payload: Any,
        rows: int = 1,
        deadline: Optional[float] = None,
        trace: Optional[tuple] = None,
    ):
        self.name = name
        self.payload = payload
        self.future: "Future[Any]" = Future()
        self.enqueued_at = time.monotonic()
        self.deadline = deadline
        self.rows = rows
        self.trace = trace


class MicroBatcher:
    """Keyed queues + dispatcher thread(s) draining them into ``runner``.

    ``runner(key, items)`` runs on a dispatcher thread and must resolve
    every item's future (the engine's stack→device→scatter). Items whose
    ``future.set_running_or_notify_cancel()`` returns False were
    abandoned by their request thread and are dropped before the runner
    sees them.
    """

    def __init__(
        self,
        runner: Callable[[Hashable, List[BatchItem]], None],
        *,
        max_size: int = 32,
        max_delay_s: float = 0.005,
        queue_depth: int = 512,
        pressure_depth: Optional[int] = None,
        dispatchers: int = 1,
        retry_after_s: float = 1.0,
        name: str = "serve",
        inline_flush: bool = False,
        on_shed: Optional[Callable[[str, int], None]] = None,
        on_depth: Optional[Callable[[int], None]] = None,
    ):
        if max_size < 1 or queue_depth < 1 or dispatchers < 1:
            raise ValueError("max_size, queue_depth and dispatchers must be >= 1")
        self.runner = runner
        self.max_size = max_size
        #: leader/follower mode: the submit that fills a batch to
        #: max_size runs it inline on the submitting thread (no
        #: dispatcher handoff on the saturated path — under load the
        #: wake-up latency of a parked dispatcher is the throughput
        #: ceiling); age/pressure flushes still drain via dispatchers
        self.inline_flush = inline_flush
        self.max_delay_s = max(0.0, max_delay_s)
        self.queue_depth = queue_depth
        self.pressure_depth = (
            pressure_depth
            if pressure_depth is not None
            else max(max_size, queue_depth // 2)
        )
        self.retry_after_s = retry_after_s
        self.name = name
        self._on_shed = on_shed
        self._on_depth = on_depth
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queues: Dict[Hashable, List[BatchItem]] = {}
        self._total = 0
        self._pressured = False
        self._stopping = False
        self._threads = [
            threading.Thread(
                target=self._dispatch_loop,
                name=f"gordo-{name}-dispatch-{i}",
                daemon=True,
            )
            for i in range(dispatchers)
        ]
        for thread in self._threads:
            thread.start()

    # -- submission ---------------------------------------------------------

    def submit(self, key: Hashable, item: BatchItem) -> "Future[Any]":
        """Enqueue ``item`` under ``key``; returns its future. Raises
        :class:`QueueFullError` at capacity and :class:`BatcherStopped`
        once shutdown began."""
        inline = None
        with self._work:
            if self._stopping:
                raise BatcherStopped("micro-batcher is shutting down")
            if self._total >= self.queue_depth:
                self._shed("queue_full")
                raise QueueFullError(self._total, self.retry_after_s)
            self._queues.setdefault(key, []).append(item)
            self._total += 1
            if self.inline_flush and len(self._queues[key]) >= self.max_size:
                # the popped batch may be ANOTHER (older) ready key —
                # notify regardless so nothing ready sits unclaimed
                inline = self._take_batch()
            depth = self._total
            if inline is None or self._total:
                self._work.notify()
        self._depth(depth)
        if inline is not None:
            self._run(*inline)
        return item.future

    def pending(self) -> int:
        with self._lock:
            return self._total

    # -- dispatch -----------------------------------------------------------

    def _ready_key(self, now: float) -> Optional[Hashable]:
        """The key to flush now, or None. Size- and age-ready keys win by
        oldest head; under pressure the largest queue flushes."""
        best = None
        best_age = -1.0
        # Draining counts as pressure: a stopping batcher flushes
        # everything now instead of letting items age to max_delay.
        # Pressure is sticky until the queues fully drain — one flush
        # drops _total below the threshold, but the items it left behind
        # were waiting under load and must not be stranded to max_delay.
        if self._total >= self.pressure_depth:
            self._pressured = True
        elif not self._total:
            self._pressured = False
        pressured = self._stopping or self._pressured
        for key, queue in self._queues.items():
            if not queue:
                continue
            age = now - queue[0].enqueued_at
            if len(queue) >= self.max_size or age >= self.max_delay_s:
                if age > best_age:
                    best, best_age = key, age
        if best is None and pressured:
            candidates = [k for k, q in self._queues.items() if q]
            if candidates:
                best = max(candidates, key=lambda k: len(self._queues[k]))
        return best

    def _next_wakeup(self, now: float) -> Optional[float]:
        deadlines = [
            queue[0].enqueued_at + self.max_delay_s
            for queue in self._queues.values()
            if queue
        ]
        if not deadlines:
            return None
        return max(0.0, min(deadlines) - now)

    def _take_batch(self) -> Optional[tuple]:
        """Pop the next flushable batch as ``(claimed_items, key)``
        (holding the lock); None when there is nothing ready."""
        now = time.monotonic()
        key = self._ready_key(now)
        if key is None:
            return None
        queue = self._queues[key]
        batch, remainder = queue[: self.max_size], queue[self.max_size:]
        if remainder:
            self._queues[key] = remainder
        else:
            del self._queues[key]
        self._total -= len(batch)
        return [self._claim(item) for item in batch], key

    def _claim(self, item: BatchItem) -> Optional[BatchItem]:
        """Claim one popped item for execution: expire past-deadline
        items, drop caller-cancelled ones."""
        if item.deadline is not None and time.monotonic() > item.deadline:
            self._shed("deadline")
            if not item.future.cancel():
                try:
                    item.future.set_exception(
                        DeadlineExceeded("batch deadline passed while queued")
                    )
                except Exception:  # noqa: BLE001 - already resolved: nothing to do
                    pass
            return None
        if not item.future.set_running_or_notify_cancel():
            self._shed("cancelled")
            return None
        return item

    def _dispatch_loop(self):
        while True:
            with self._work:
                taken = self._take_batch()
                while taken is None:
                    if self._stopping and not self._total:
                        return
                    timeout = self._next_wakeup(time.monotonic())
                    if self._stopping:
                        # draining: flush ages out immediately
                        timeout = 0.001
                    self._work.wait(timeout=timeout)
                    taken = self._take_batch()
                    if taken is None and self._stopping and not self._total:
                        return
                batch, key = taken
                depth = self._total
            self._depth(depth)
            self._run(batch, key)

    def _run(self, batch: List[Optional[BatchItem]], key: Hashable) -> None:
        """Run one popped batch (dispatcher thread or inline leader)."""
        live = [item for item in batch if item is not None]
        if not live:
            return
        try:
            self.runner(key, live)
        except BaseException as exc:  # noqa: BLE001 - a runner crash must
            # resolve every waiter (a hung client is worse than an error)
            logger.exception("batch runner failed for key %r", key)
            self._shed("runner_error")
            for item in live:
                try:
                    # each rider gets its OWN exception instance: one
                    # shared exception object (and its traceback) handed
                    # to N request-handler threads is mutated concurrently
                    # by every `raise` that re-renders it — a latent race
                    # and a cross-request information leak
                    item.future.set_exception(clone_exception(exc))
                except Exception:  # noqa: BLE001 - runner resolved some
                    pass

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting work; with ``drain`` the dispatcher(s) flush
        everything still queued before exiting, otherwise queued items
        get :class:`BatcherStopped`."""
        with self._work:
            self._stopping = True
            if not drain:
                for queue in self._queues.values():
                    for item in queue:
                        if not item.future.cancel():
                            try:
                                item.future.set_exception(
                                    BatcherStopped("batcher stopped")
                                )
                            except Exception:  # noqa: BLE001
                                pass
                self._queues.clear()
                self._total = 0
            self._work.notify_all()
        for thread in self._threads:
            thread.join(timeout=timeout)

    # -- hooks --------------------------------------------------------------

    def _shed(self, reason: str) -> None:
        if self._on_shed is not None:
            try:
                self._on_shed(reason, 1)
            except Exception:  # noqa: BLE001 - metrics are advisory
                pass

    def _depth(self, depth: int) -> None:
        if self._on_depth is not None:
            try:
                self._on_depth(depth)
            except Exception:  # noqa: BLE001 - metrics are advisory
                pass
