"""
Per-member circuit breakers for the serving plane.

The engine's batch bisection (``engine.py``) isolates a device failure
down to one member — but without memory, every batch that member rides
pays the whole bisection ladder again, forever. The breaker is that
memory: a per-``(revision fleet, spec, member)`` state machine that
counts consecutive isolated failures, TRIPS the member into a serving
quarantine once they cross a threshold, and probes it back to health on
an exponential-backoff schedule.

State machine (the classic three states):

- **closed** (the steady state): requests flow; an isolated failure
  increments the consecutive-failure count, a success resets it.
  ``GORDO_TPU_BREAKER_THRESHOLD`` consecutive failures trip the breaker.
- **open**: requests for the member are rejected *before* they ride a
  batch (:class:`MemberQuarantined` → the route's 503 + ``Retry-After``)
  for ``cooldown`` seconds. The cooldown starts at
  ``GORDO_TPU_BREAKER_COOLDOWN_S`` and multiplies by
  ``GORDO_TPU_BREAKER_BACKOFF`` on every re-trip, capped at
  ``GORDO_TPU_BREAKER_MAX_COOLDOWN_S``.
- **half-open**: after the cooldown, exactly ONE request is admitted as
  a probe (concurrent requests keep getting 503 with a short
  ``Retry-After``); the probe's success closes the breaker, its failure
  re-opens with the grown cooldown. A probe whose request is shed
  (deadline, cancelled waiter) expires after ``probe_ttl_s`` so a lost
  probe can never wedge the breaker half-open forever.

Keys include the :class:`RevisionFleet` *object*, so breaker state lives
and dies with the served revision exactly like the precision-gate
verdicts: a hot-swap or DELETE drops the fleet, and the replacement
revision starts with a clean slate (a rebuilt member has earned a fresh
chance). Dead fleets are purged via ``weakref.finalize`` — the board
never pins a revision in memory.

Layering: this module is pure stdlib state machinery. It must NOT
import ``gordo_tpu.lifecycle`` — tripped members reach the lifecycle
supervisor through the fleet-health ledger (the telemetry arrow), which
the :class:`~gordo_tpu.serve.engine.ServeEngine` feeds on every
transition.
"""

import collections
import logging
import threading
import time
import weakref
from typing import Any, Callable, Dict, Optional, Tuple

from ..utils.env import env_float, env_int
from .batcher import BatchShedError

logger = logging.getLogger(__name__)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class MemberQuarantined(BatchShedError):
    """The member's circuit breaker is open: the request is rejected
    before riding a batch. The route maps this to **503** with a
    ``Retry-After`` derived from the breaker's remaining cooldown
    (mirroring the 429 ``Retry-After`` contract)."""

    def __init__(self, member: str, retry_after_s: float):
        super().__init__(
            f"model {member!r} is quarantined by its serving circuit "
            f"breaker; retry in {retry_after_s:.0f}s"
        )
        self.member = member
        self.retry_after_s = retry_after_s


class ServeDeviceError(BatchShedError):
    """A device program failed for THIS request/member after the
    engine's bisection isolated it — the innocent riders of the same
    batch already got their results. The route maps this to **500**
    (server-side; the generic text never echoes device internals)."""

    def __init__(self, member: str, cause: Optional[BaseException] = None):
        super().__init__(
            f"device scoring failed for model {member!r} in isolation"
        )
        self.member = member
        # chained for the server log only; routes answer generic text
        self.__cause__ = cause


class BreakerConfig:
    """Breaker knobs, resolved once per board from the environment."""

    __slots__ = (
        "threshold",
        "cooldown_s",
        "backoff",
        "max_cooldown_s",
        "probe_ttl_s",
    )

    def __init__(
        self,
        threshold: int = 3,
        cooldown_s: float = 30.0,
        backoff: float = 2.0,
        max_cooldown_s: float = 600.0,
        probe_ttl_s: Optional[float] = None,
    ):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = max(0.001, float(cooldown_s))
        self.backoff = max(1.0, float(backoff))
        self.max_cooldown_s = max(self.cooldown_s, float(max_cooldown_s))
        #: how long a half-open probe may stay unresolved before another
        #: request is allowed to probe (a shed/cancelled probe must not
        #: wedge the breaker half-open forever)
        self.probe_ttl_s = (
            float(probe_ttl_s)
            if probe_ttl_s is not None
            else max(5.0, self.cooldown_s)
        )

    @classmethod
    def from_env(cls) -> "BreakerConfig":
        return cls(
            threshold=env_int("GORDO_TPU_BREAKER_THRESHOLD", 3),
            cooldown_s=env_float("GORDO_TPU_BREAKER_COOLDOWN_S", 30.0),
            backoff=env_float("GORDO_TPU_BREAKER_BACKOFF", 2.0),
            max_cooldown_s=env_float("GORDO_TPU_BREAKER_MAX_COOLDOWN_S", 600.0),
        )


class _MemberBreaker:
    """One member's breaker record (mutated only under the board lock)."""

    __slots__ = (
        "name",
        "state",
        "failures",
        "trips",
        "opened_at",
        "cooldown_s",
        "probe_at",
        "last_error",
    )

    def __init__(self, name: str):
        self.name = name
        self.state = CLOSED
        self.failures = 0  # consecutive isolated failures
        self.trips = 0
        self.opened_at = 0.0  # monotonic
        self.cooldown_s = 0.0
        self.probe_at: Optional[float] = None
        self.last_error = ""

    def snapshot(self) -> Dict[str, Any]:
        return {
            "member": self.name,
            "state": self.state,
            "failures": self.failures,
            "trips": self.trips,
            "cooldown_s": round(self.cooldown_s, 3),
            "last_error": self.last_error,
        }


class BreakerBoard:
    """The engine's breaker registry, keyed by (fleet, spec, member).

    ``on_transition(member, old_state, new_state, snapshot)`` fires
    (outside the lock) on every state change — the engine wires it to
    the fleet-health ledger, the span recorder and Prometheus. The
    board also carries the engine's **precision degrade set**: buckets
    whose reduced-precision programs started faulting mid-traffic are
    pinned to f32 here (it shares the breaker's fleet-lifetime scoping
    and GC), independent of whether the parity gate is enabled.
    """

    def __init__(
        self,
        config: Optional[BreakerConfig] = None,
        on_transition: Optional[Callable[[str, str, str, dict], None]] = None,
    ):
        self.config = config or BreakerConfig.from_env()
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._members: Dict[Tuple[int, Any, str], _MemberBreaker] = {}
        #: the non-CLOSED subset of ``_members``, maintained by every
        #: transition: board summaries iterate THIS map (plus the live
        #: trip counter below), never the full member map, so their cost
        #: tracks how many members are unhealthy — not fleet size
        self._unhealthy: Dict[Tuple[int, Any, str], _MemberBreaker] = {}
        #: total trips across live members (decremented when a dead
        #: fleet's members are purged, mirroring the old full-map sum)
        self._live_trips = 0
        #: (fleet id, spec, precision) buckets degraded to f32 after
        #: device errors (engine._member_failure); consulted per request
        #: with one set probe
        self._degraded: set = set()
        #: fleet id -> finalizer: purges a dead fleet's keys so an id
        #: reuse can never resurrect another revision's breaker state
        self._fleets: Dict[int, Any] = {}
        #: fleet ids whose finalizer fired, awaiting a locked drain. The
        #: weakref callback runs inside the GC — which can trigger on any
        #: allocation, including one made WHILE this board's lock is
        #: held — so the callback itself must never take the lock
        #: (deadlock) or mutate the maps (concurrent-iteration): it only
        #: appends to this deque, and every locked mutator drains it.
        self._dead: "collections.deque" = collections.deque()

    # -- keying / GC ---------------------------------------------------------

    def _track_fleet(self, fleet: Any) -> int:
        fid = id(fleet)
        if fid not in self._fleets:  # caller holds the lock
            self._fleets[fid] = weakref.finalize(fleet, self._dead.append, fid)
        return fid

    def _drain_dead_locked(self) -> None:
        """Purge dead fleets' state (caller holds the lock); an id freed
        here can be reused by a NEW fleet without ever resurrecting the
        old revision's breaker verdicts."""
        while True:
            try:
                fid = self._dead.popleft()
            except IndexError:
                return
            self._fleets.pop(fid, None)
            for key in [k for k in self._members if k[0] == fid]:
                self._live_trips -= self._members.pop(key).trips
                self._unhealthy.pop(key, None)
            self._degraded = {k for k in self._degraded if k[0] != fid}

    # -- request path --------------------------------------------------------

    def quarantined(self, fleet: Any, spec: Any, member: str) -> Optional[float]:
        """None when the request may proceed (closed, or admitted as the
        half-open probe); otherwise the ``Retry-After`` seconds the 503
        should carry. The steady-state (no breaker for this member) cost
        is one lock-free dict probe."""
        if self._dead:
            with self._lock:
                self._drain_dead_locked()
        key = (id(fleet), spec, member)
        breaker = self._members.get(key)  # lock-free: hot path
        if breaker is None or breaker.state == CLOSED:
            return None
        now = time.monotonic()
        transition = None
        with self._lock:
            self._drain_dead_locked()
            breaker = self._members.get(key)
            if breaker is None or breaker.state == CLOSED:
                return None
            if breaker.state == OPEN:
                remaining = breaker.opened_at + breaker.cooldown_s - now
                if remaining > 0:
                    return max(1.0, remaining)
                # cooldown lapsed: this request becomes the probe
                breaker.state = HALF_OPEN
                breaker.probe_at = now
                transition = (OPEN, HALF_OPEN, breaker.snapshot())
            elif breaker.state == HALF_OPEN:
                probe_at = breaker.probe_at
                if probe_at is not None and now - probe_at < self.config.probe_ttl_s:
                    # a probe is in flight; everyone else waits it out
                    return max(1.0, self.config.probe_ttl_s - (now - probe_at))
                breaker.probe_at = now  # the previous probe was lost
        if transition is not None:
            self._fire(member, *transition)
        return None

    def record_success(self, fleet: Any, spec: Any, member: str) -> None:
        """A member scored cleanly: reset the consecutive-failure count,
        and close a half-open breaker (the probe came back healthy).
        No-op — one dict probe — for untracked members."""
        key = (id(fleet), spec, member)
        if self._members.get(key) is None:  # lock-free: hot path
            return
        transition = None
        with self._lock:
            self._drain_dead_locked()
            breaker = self._members.get(key)
            if breaker is None:
                return
            breaker.failures = 0
            if breaker.state == HALF_OPEN:
                old = breaker.state
                breaker.state = CLOSED
                breaker.probe_at = None
                self._unhealthy.pop(key, None)
                transition = (old, CLOSED, breaker.snapshot())
        if transition is not None:
            logger.info(
                "serving breaker CLOSED for member %s (half-open probe "
                "succeeded after %d trip(s))",
                member,
                transition[2]["trips"],
            )
            self._fire(member, *transition)

    def record_failure(
        self, fleet: Any, spec: Any, member: str, exc: BaseException
    ) -> bool:
        """One isolated device failure for ``member``; returns True when
        this failure TRIPPED the breaker (closed→open or a failed
        half-open probe re-opening)."""
        now = time.monotonic()
        transition = None
        with self._lock:
            self._drain_dead_locked()
            key = (self._track_fleet(fleet), spec, member)
            breaker = self._members.get(key)
            if breaker is None:
                breaker = self._members[key] = _MemberBreaker(member)
            breaker.failures += 1
            breaker.last_error = repr(exc)[:200]
            tripped = False
            if breaker.state == HALF_OPEN:
                tripped = True  # the probe failed: straight back to open
            elif (
                breaker.state == CLOSED
                and breaker.failures >= self.config.threshold
            ):
                tripped = True
            if tripped:
                old = breaker.state
                breaker.state = OPEN
                breaker.trips += 1
                self._live_trips += 1
                self._unhealthy[key] = breaker
                breaker.opened_at = now
                breaker.probe_at = None
                breaker.cooldown_s = min(
                    self.config.max_cooldown_s,
                    self.config.cooldown_s
                    * (self.config.backoff ** (breaker.trips - 1)),
                )
                transition = (old, OPEN, breaker.snapshot())
        if transition is not None:
            logger.warning(
                "serving breaker OPEN for member %s (trip %d, cooldown "
                "%.1fs): %s",
                member,
                transition[2]["trips"],
                transition[2]["cooldown_s"],
                transition[2]["last_error"],
            )
            self._fire(member, *transition)
        return transition is not None

    # -- precision degrade set ----------------------------------------------

    def degrade_bucket(self, fleet: Any, spec: Any, precision: str) -> bool:
        """Pin one (fleet, spec, precision) bucket to f32 after its
        reduced-precision program faulted; True when newly degraded.
        Unlike the parity gate's verdict map this works with the gate
        disabled — device errors degrade unconditionally."""
        with self._lock:
            self._drain_dead_locked()
            key = (self._track_fleet(fleet), spec, precision)
            if key in self._degraded:
                return False
            self._degraded.add(key)
        return True

    def degraded(self, fleet: Any, spec: Any, precision: str) -> bool:
        if self._dead:
            # a dead fleet's id can be REUSED by a new RevisionFleet:
            # drain before the lock-free probe so stale degrade keys can
            # never pin a fresh revision's bucket to f32
            with self._lock:
                self._drain_dead_locked()
        return (id(fleet), spec, precision) in self._degraded  # lock-free

    # -- introspection -------------------------------------------------------

    def summary(self, top_k: int = 10) -> Dict[str, Any]:
        """Bounded board summary for the engine stats / fleet-status
        ``serving`` section: counts by state, total trips, and the
        top-``top_k`` unhealthy members by trip count. Cost is
        O(unhealthy members) — the full member map is only ever
        ``len()``-counted, never iterated, so a 10k-member fleet with
        three tripped breakers pays for three."""
        with self._lock:
            self._drain_dead_locked()
            tracked = len(self._members)
            unhealthy = list(self._unhealthy.values())
            trips = self._live_trips
            degraded = len(self._degraded)
        counts = {OPEN: 0, HALF_OPEN: 0}
        for breaker in unhealthy:
            counts[breaker.state] += 1
        ranked = sorted(unhealthy, key=lambda b: (-b.trips, b.name))
        return {
            "tracked": tracked,
            "open": counts[OPEN],
            "half_open": counts[HALF_OPEN],
            "trips": trips,
            "degraded_buckets": degraded,
            "members": [b.snapshot() for b in ranked[: max(0, top_k)]],
        }

    def snapshot(self, detail_cap: int = 50) -> Dict[str, Any]:
        """Compatibility spelling of :meth:`summary` (same keys; member
        detail capped at ``detail_cap``)."""
        return self.summary(top_k=detail_cap)

    # -- hooks ---------------------------------------------------------------

    def _fire(self, member: str, old: str, new: str, info: dict) -> None:
        if self._on_transition is None:
            return
        try:
            self._on_transition(member, old, new, info)
        except Exception:  # noqa: BLE001 - transition feeds (ledger,
            # metrics, spans) are advisory, never the request's problem
            logger.debug("breaker transition hook failed", exc_info=True)
