"""
The serving engine: fused scoring of coalesced single-model requests.

:class:`ServeEngine` owns a :class:`~gordo_tpu.serve.batcher.MicroBatcher`
keyed by ``(revision fleet, spec)`` and turns each drained batch into ONE
fused ``fleet_forward`` device program (the same program the fleet route
and the Pallas kernel serve):

- **stack**: gather the batch members' rows from the revision's resident
  stacked parameter bucket (``RevisionFleet.spec_bucket``) and pad the
  member axis up a power-of-two ladder (``ladder.py``) so the jit cache
  stays bounded. The ROW axis is padded on the *request* thread (each
  payload lands in the queue already at its row-ladder rung, and the
  batch key includes the rung): request threads are idle waiters anyway,
  while every Python-level op on the dispatcher thread is a GIL handoff
  opportunity against hundreds of active clients — under overload a
  per-item dispatcher padding loop measures tens of ms per batch, a
  single ``np.stack`` does not;
- **device**: run the fused program once for the whole batch;
- **scatter**: slice each member's rows back out and resolve its future.

Requests the engine cannot batch (non-feedforward models, row counts
above the ladder, a draining batcher) return ``None`` from
:func:`ServeEngine.batched_predict` and the caller falls back to the
unbatched path — batching is an optimization, never a gate.

A process-global engine (:func:`ensure_engine` / :func:`get_engine`)
mirrors the fleet store's module-global pattern: gunicorn gthread workers
share one engine per process. The master switch is ``GORDO_TPU_BATCHING``
(default OFF — existing single-program-per-request behavior is the
fallback and the default).
"""

import atexit
import logging
import os
import threading
import time
from concurrent.futures import CancelledError
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..ingest import RawColumns
from ..ingest import compiled_enabled as ingest_compiled
from ..ingest import dlpack_enabled, ingest_stats, to_device
from ..models.spec import FeedForwardSpec
from ..telemetry.device import note_program_execution
from ..telemetry.serving import SERVE_TRACE_FILE, serve_recorder
from ..utils.env import env_bool, env_float, env_int, env_str
from ..utils.faults import FaultInjected, fault_point
from . import ladder, precision
from .batcher import BatcherStopped, BatchItem, DeadlineExceeded, MicroBatcher
from .breaker import BreakerBoard, MemberQuarantined, ServeDeviceError

logger = logging.getLogger(__name__)

BATCHING_ENV = "GORDO_TPU_BATCHING"

#: learned-performance-model consumer knobs (PR 20): each defaults OFF,
#: and each degrades to the exact pre-perfmodel behavior on any model
#: failure — predictions steer, they never gate
PERFMODEL_TABLE_ENV = "GORDO_TPU_PERFMODEL_TABLE"
PERFMODEL_WARMUP_ENV = "GORDO_TPU_PERFMODEL_WARMUP"
PERFMODEL_CAP_ENV = "GORDO_TPU_PERFMODEL_BATCH_CAP_BYTES"
PERFMODEL_BREAKER_ENV = "GORDO_TPU_PERFMODEL_BREAKER"
PERFMODEL_BREAKER_SAFETY_ENV = "GORDO_TPU_PERFMODEL_BREAKER_SAFETY"

# SERVE_TRACE_FILE is re-exported for callers that predate the shared
# serving recorder; telemetry/serving.py owns the name and sink now.
assert SERVE_TRACE_FILE  # imported for re-export


def batching_enabled() -> bool:
    """Master switch: batching is opt-in (``GORDO_TPU_BATCHING=1``)."""
    return env_bool(BATCHING_ENV, False)


class ServeConfig:
    """Engine knobs, resolved once from the environment at creation."""

    __slots__ = (
        "max_size",
        "max_delay_s",
        "queue_depth",
        "pressure_depth",
        "deadline_s",
        "dispatchers",
        "row_ladder",
        "warmup_max_rows",
        "inline_flush",
        "precision",
        "finite_check",
    )

    def __init__(
        self,
        max_size: int = 32,
        max_delay_ms: float = 5.0,
        queue_depth: int = 512,
        pressure_depth: Optional[int] = None,
        deadline_ms: float = 2000.0,
        dispatchers: int = 1,
        row_ladder: Optional[Tuple[int, ...]] = None,
        warmup_max_rows: int = 512,
        inline_flush: bool = True,
        serve_precision: str = "",
        finite_check: bool = True,
    ):
        self.max_size = max(1, int(max_size))
        self.max_delay_s = max(0.0, float(max_delay_ms) / 1000.0)
        self.queue_depth = max(1, int(queue_depth))
        self.pressure_depth = pressure_depth
        self.deadline_s = max(0.001, float(deadline_ms) / 1000.0)
        self.dispatchers = max(1, int(dispatchers))
        self.row_ladder = (
            tuple(row_ladder) if row_ladder is not None else ladder.row_ladder()
        )
        self.warmup_max_rows = int(warmup_max_rows)
        self.inline_flush = bool(inline_flush)
        #: scan every fused batch's output for non-finite rows (NaN/inf):
        #: a member producing them from FINITE input is poisoned and
        #: fails alone instead of silently corrupting anomaly verdicts
        self.finite_check = bool(finite_check)
        #: the engine-default serving precision ("" inherits the
        #: GORDO_TPU_SERVE_PRECISION knob at resolve time); a spec's own
        #: precision: field still wins per request
        self.precision = (
            precision.normalize(serve_precision)
            if serve_precision
            else precision.serve_precision()
        )

    @classmethod
    def from_env(cls) -> "ServeConfig":
        return cls(
            max_size=env_int("GORDO_TPU_BATCH_MAX_SIZE", 32),
            max_delay_ms=env_float("GORDO_TPU_BATCH_MAX_DELAY_MS", 5.0),
            queue_depth=env_int("GORDO_TPU_BATCH_QUEUE_DEPTH", 512),
            deadline_ms=env_float("GORDO_TPU_BATCH_DEADLINE_MS", 2000.0),
            dispatchers=env_int("GORDO_TPU_BATCH_DISPATCHERS", 1),
            warmup_max_rows=env_int("GORDO_TPU_SERVE_WARMUP_ROWS", 512),
            inline_flush=env_bool("GORDO_TPU_BATCH_INLINE_FLUSH", True),
            serve_precision=env_str(precision.PRECISION_ENV, "") or "",
            finite_check=env_bool("GORDO_TPU_SERVE_FINITE_CHECK", True),
        )


class ServeEngine:
    """Process-wide micro-batching scheduler over the fleet store."""

    def __init__(self, config: Optional[ServeConfig] = None, metrics: Any = None):
        self.config = config or ServeConfig.from_env()
        #: duck-typed metric sink (server.prometheus.metrics.ServeMetrics);
        #: late-bound so build_app can attach it after creation
        self.metrics = metrics
        #: the anchor collection dir the breaker feed ledgers against —
        #: late-bound by build_app (which resolves the app's configurable
        #: MODEL_COLLECTION_DIR_ENV_VAR); unset, the transition hook
        #: falls back to the default env var name
        self.ledger_anchor: Optional[str] = None
        self.member_ladder = ladder.member_ladder(self.config.max_size)
        #: the precision-parity arbiter: gate-then-serve, degrade to f32
        #: on failure (serve/precision.py)
        self.governor = precision.PrecisionGovernor()
        #: per-(fleet, spec, member) circuit breakers + the device-error
        #: precision degrade set (serve/breaker.py); transitions feed
        #: the health ledger, the span recorder and Prometheus
        self.breakers = BreakerBoard(on_transition=self._on_breaker_transition)
        self._lock = threading.Lock()
        self._programs: set = set()
        #: (spec, precision) -> demoted member/row caps after a
        #: RESOURCE_EXHAUSTED: an OOMing ladder rung is dropped for the
        #: engine's lifetime instead of being retried on every batch
        self._member_caps: Dict[Tuple, int] = {}
        self._row_caps: Dict[Tuple, int] = {}
        self._counters: Dict[str, int] = {
            "requests": 0,  # batched_predict calls that enqueued
            "fallback": 0,  # ineligible calls answered None
            "batches": 0,  # fused device programs launched
            "coalesced": 0,  # requests scored through fused programs
            "shed_queue_full": 0,
            "shed_deadline": 0,
            "warmup_programs": 0,
            "precision_degraded": 0,  # requests gated down to f32
            # -- failure containment (this set distinguishes device
            # errors from the deadline/queue_full admission sheds) --
            "device_errors": 0,  # fused programs that raised device errors
            "batch_bisects": 0,  # halvings while isolating a failure
            "members_isolated": 0,  # failures pinned to a single member
            "nonfinite_outputs": 0,  # poisoned (NaN/inf) member outputs
            "breaker_rejects": 0,  # requests answered 503 by a breaker
            "breaker_trips": 0,  # closed/half-open -> open transitions
            "rung_demotions": 0,  # ladder rungs dropped after OOM
            "oom_fallbacks": 0,  # single-member OOMs sent unbatched
            # -- device-resident ingest (gordo_tpu.ingest) --
            "ingest_requests": 0,  # requests enqueued as raw wire columns
            "ingest_batches": 0,  # fused batches staged through to_device
            "ingest_replans": 0,  # plan vanished mid-batch: host rebuild
        }
        #: requests coalesced per effective serving precision
        self._precision_counters: Dict[str, int] = {}
        #: (spec, members, rows, precision) -> predicted device ms (the
        #: cost model's serve-step estimate, cached per ladder shape for
        #: the predicted-vs-actual batch-span attributes)
        self._step_predictions: Dict[Tuple, float] = {}
        #: the engine's CostModel (analytic, or carrying the learned
        #: table GORDO_TPU_PERFMODEL_TABLE names) — built lazily ONCE so
        #: every consumer (span predictions, batch caps, OOM demotion,
        #: warmup ordering) measures with the same ruler
        self._cost_model_cache: Optional[Any] = None
        #: (spec, precision) -> predicted-HBM row cap under
        #: GORDO_TPU_PERFMODEL_BATCH_CAP_BYTES (None = uncapped)
        self._model_row_caps: Dict[Tuple, Optional[int]] = {}
        self._batcher = MicroBatcher(
            self._run_batch,
            max_size=self.config.max_size,
            max_delay_s=self.config.max_delay_s,
            queue_depth=self.config.queue_depth,
            pressure_depth=self.config.pressure_depth,
            dispatchers=self.config.dispatchers,
            inline_flush=self.config.inline_flush,
            retry_after_s=max(1.0, self.config.max_delay_s * 4),
            on_shed=self._on_shed,
            on_depth=self._on_depth,
        )

    @property
    def _recorder(self):
        # the process-shared serving recorder (telemetry/serving.py) —
        # the same sink the server's request-span export writes to, so
        # batch spans and the request spans they link to land in ONE
        # serve_trace.jsonl; resolved per use, so telemetry env changes
        # (tests, late configuration) take effect without an engine
        # rebuild
        return serve_recorder()

    # -- request path -------------------------------------------------------

    def eligible_spec(self, fleet, name: str) -> Optional[FeedForwardSpec]:
        """The spec this request batches under, or None: only feedforward
        architectures take the fused path today (windowed LSTMs need the
        order-array program and stay on the unbatched path)."""
        spec = fleet.loaded_specs().get(name)
        return spec if isinstance(spec, FeedForwardSpec) else None

    def batched_predict(
        self,
        collection_dir: str,
        name: str,
        model: Any,
        X,
        timing: Any = None,
        raw: Optional[RawColumns] = None,
    ) -> Optional[np.ndarray]:
        """
        Score one request through the micro-batcher: returns the
        reconstruction rows, or None when the request is not batchable
        (the caller runs the model's own predict instead).

        ``raw`` carries the request's decoded wire columns
        (:class:`gordo_tpu.ingest.RawColumns`) when the view still has
        them; with a compiled preprocessing plan resident for the spec
        (``RevisionFleet.ingest_plan``, f32 serving) the request then
        batches RAW — no host transform, no request-thread pad — and the
        dispatcher stages the columns straight to device, where the
        fused program's prologue does the preprocessing.

        Raises :class:`QueueFullError` (→ 429) when admission control
        rejects the request and :class:`DeadlineExceeded` (→ 504) when
        its batch misses the per-request deadline.
        """
        from ..server.fleet_store import STORE, _find_estimator, _host_transform

        fleet = STORE.fleet(collection_dir)
        spec = self.eligible_spec(fleet, name)
        if spec is None or _find_estimator(model) is None:
            self._count("fallback")
            return None
        # circuit breaker FIRST — before paying the host transform: a
        # quarantined member answers 503 + Retry-After instead of riding
        # batches (its cooldown is serving state, not admission load)
        retry_after = self.breakers.quarantined(fleet, spec, name)
        if retry_after is not None:
            self._count("breaker_rejects")
            raise MemberQuarantined(name, retry_after)
        # row count is decided before the (potentially expensive) host
        # transform: a fallback request must not pay the pipeline twice
        rows = int(len(X))
        padded_rows = ladder.pad_to(rows, self.config.row_ladder)
        if rows == 0 or padded_rows is None:
            # taller than the ladder's top rung: an unbounded shape —
            # serve it unbatched rather than minting a program
            self._count("fallback")
            return None

        # the effective serving precision: the spec's declared (or the
        # engine-default) precision, degraded to f32 when the bucket's
        # reduced program faulted mid-traffic (the breaker board's
        # degrade set — one set probe) or the parity gate failed / has
        # not passed yet (the governor — one COW dict probe)
        desired = precision.resolve_precision(spec, self.config.precision)
        if desired == precision.F32 and not getattr(spec, "precision", ""):
            # nothing pinned a precision: the learned model may nominate
            # a measured-faster rung (GORDO_TPU_PERFMODEL_PRECISION,
            # default off) — still gated and degradable below, exactly
            # like a configured one
            preferred = precision.model_preferred(
                spec, self.member_ladder[-1], padded_rows, self._cost_model()
            )
            if preferred:
                desired = preferred
        prec = desired
        if desired != precision.F32:
            if self.breakers.degraded(fleet, spec, desired):
                prec = precision.F32
            else:
                prec = self.governor.effective_precision(
                    fleet, spec, desired, recorder=self._recorder
                )
            if prec != desired:
                self._count("precision_degraded")

        # an OOM-demoted row rung: requests that would pad to a rung the
        # device already RESOURCE_EXHAUSTED on serve unbatched instead
        # of re-OOMing the same shape forever
        row_cap = self._row_caps.get((spec, prec))  # lock-free dict probe
        # the perfmodel byte budget is a second, PREDICTIVE cap on the
        # same axis: the reactive (post-OOM) and predicted caps merge as
        # min — whichever learned the lower ceiling wins
        model_cap = self._model_row_cap(spec, prec)
        if model_cap is not None and (row_cap is None or model_cap < row_cap):
            row_cap = model_cap
        if row_cap is not None and padded_rows > row_cap:
            self._count("fallback")
            return None

        # compiled-ingest eligibility: a resident preprocessing plan at
        # f32 serving means the host pipeline is already inside the
        # fused program (or provably a no-op) — the request thread then
        # enqueues the RAW columns and does no math at all. Reduced
        # precisions keep the legacy pre-cast payload path: their
        # payload dtype is part of the program contract.
        plan = None
        if prec == precision.F32 and ingest_compiled():
            try:
                plan = fleet.ingest_plan(spec)
            except Exception:  # noqa: BLE001 - planning never gates serving
                plan = None
        if plan is not None:
            if raw is None:
                raw = RawColumns.from_matrix(np.asarray(X, np.float32))
            if raw.rows != rows:
                rows = raw.rows
                padded_rows = ladder.pad_to(rows, self.config.row_ladder)
                if rows == 0 or padded_rows is None or (
                    row_cap is not None and padded_rows > row_cap
                ):
                    self._count("fallback")
                    return None
            payload: Any = raw
        else:
            transformed = _host_transform(model, X)
            if int(transformed.shape[0]) != rows:
                # a row-count-changing transformer: re-derive from the
                # shape the fused program will actually see
                rows = int(transformed.shape[0])
                padded_rows = ladder.pad_to(rows, self.config.row_ladder)
                if rows == 0 or padded_rows is None:
                    self._count("fallback")
                    return None
                if row_cap is not None and padded_rows > row_cap:
                    self._count("fallback")
                    return None

            # row padding happens HERE, on the (otherwise waiting) request
            # thread — the dispatcher then stacks same-rung payloads in one
            # numpy call (see the module docstring for why that matters).
            # The payload dtype is derived from the effective precision
            # (serve/precision.payload_dtype — THE one payload-dtype
            # authority), so the stack path cannot silently upcast a
            # reduced-precision program's inputs.
            dtype = precision.payload_dtype(prec)
            if rows == padded_rows:
                payload = np.ascontiguousarray(transformed, dtype=dtype)
            else:
                payload = np.zeros((padded_rows,) + transformed.shape[1:], dtype)
                payload[:rows] = transformed

        deadline = time.monotonic() + self.config.deadline_s
        # carry the request's trace identity into the queue ONLY when
        # the serving trace is on: with telemetry off nothing span- or
        # link-shaped is constructed anywhere on this path
        trace = None
        if (
            self._recorder.enabled
            and timing is not None
            and getattr(timing, "trace_id", None)
            and getattr(timing, "sampled", True)
        ):
            # only sampled requests' spans exist in the trace — linking
            # an unexported request span would dangle
            trace = (timing.trace_id, getattr(timing, "default_parent_id", None))
        item = BatchItem(name, payload, rows=rows, deadline=deadline, trace=trace)
        try:
            # precision is part of the batch key: an f32 and a bf16
            # request for the same spec/rung must never share a fused
            # program (mixed base/canary traffic during a hot-swap).
            # So is the raw-vs-staged payload mode — a raw-column item
            # and a pre-transformed one must never stack together.
            future = self._batcher.submit(
                (fleet, spec, padded_rows, prec, plan is not None), item
            )
        except BatcherStopped:
            self._count("fallback")
            return None
        self._count("requests")
        if plan is not None:
            self._count("ingest_requests")
        try:
            recon, meta = future.result(timeout=self.config.deadline_s)
        except FutureTimeoutError:
            future.cancel()
            self._count("shed_deadline")
            raise DeadlineExceeded(
                f"request missed the {self.config.deadline_s * 1000:.0f}ms "
                "batching deadline"
            ) from None
        except CancelledError:
            # already counted: the batcher's claim path shed it
            raise DeadlineExceeded("request expired while queued") from None
        if timing is not None:
            for stage, seconds in meta.items():
                timing.record(stage, seconds)
        # recon is None when the member's SMALLEST fused program
        # RESOURCE_EXHAUSTED (the rung was demoted): the caller falls
        # back to the model's own unbatched predict
        return recon

    # -- batch execution (dispatcher thread) --------------------------------

    def _fault_key(self, spec, prec: str, name: str) -> str:
        """The chaos-harness key for one coalesced member:
        ``<spec>:<precision>:<member>`` — rules glob any axis
        (``*:bf16:*``, ``*:*:poison-*``)."""
        return f"{type(spec).__name__}:{prec}:{name}"

    def _run_batch(self, key, items: List[BatchItem]) -> None:
        fleet, spec, padded_rows, prec, raw_mode = key
        flush_start = time.monotonic()
        queue_waits = [flush_start - item.enqueued_at for item in items]
        with self._recorder.span(
            "serve_batch",
            spec=type(spec).__name__,
            n_features=spec.n_features,
            size=len(items),
            precision=prec,
        ) as batch_span:
            with self._recorder.span("stack"):
                stack_start = time.monotonic()
                bucket_names, stacked = fleet.spec_bucket(spec, prec)
                bucket_rows = {n: i for i, n in enumerate(bucket_names)}
                live: List[BatchItem] = []
                for item in items:
                    if item.name in bucket_rows:
                        live.append(item)
                    else:
                        # invalidated/evicted between submit and flush
                        try:
                            item.future.set_exception(
                                KeyError(f"{item.name} left the serving bucket")
                            )
                        except Exception:  # noqa: BLE001 - already resolved
                            pass
                if not live:
                    return
                members = len(live)
                padded_members = ladder.pad_to(members, self.member_ladder)
                plan = None
                if raw_mode:
                    plan = fleet.ingest_plan(spec)
                    if plan is None or plan.names != bucket_names:
                        # the plan (or its membership) changed between
                        # submit and flush — a hot-loaded member with a
                        # non-affine pipeline, say. Rebuild the legacy
                        # staged payloads host-side; correctness never
                        # depends on the plan surviving the queue.
                        self._count("ingest_replans", members)
                        plan = None
                        for item in live:
                            item.payload = self._materialize_host(
                                fleet, item, padded_rows, prec
                            )
                stack_s = time.monotonic() - stack_start

            # results / failures / fallbacks for THIS batch: the scoring
            # ladder below (fused program → bisection → per-member f32
            # retry → breaker) fills them; only failures that survived
            # isolation land in `failures`, each with its own exception
            results: List[Tuple[BatchItem, np.ndarray]] = []
            failures: List[Tuple[BatchItem, BaseException]] = []
            fallbacks: List[BatchItem] = []
            # bisection can run several programs per drained batch, each
            # with its own payload stack — the ladder accumulates that
            # host-side stacking time here so batch_stack keeps measuring
            # stacking (a stack regression must not read as a phantom
            # device slowdown)
            timings = {"stack": 0.0, "device_ingest": 0.0}
            with self._recorder.span(
                "device",
                padded_members=padded_members,
                padded_rows=padded_rows,
                precision=prec,
            ):
                device_start = time.monotonic()
                self._score_live(
                    fleet, spec, prec, padded_rows, live, stacked,
                    bucket_rows, results, failures, fallbacks, timings,
                    plan=plan,
                )
                device_s = (
                    time.monotonic()
                    - device_start
                    - timings["stack"]
                    - timings["device_ingest"]
                )
            stack_s += timings["stack"]
            ingest_s = timings["device_ingest"]
            if plan is not None:
                self._count("ingest_batches")

            with self._lock:
                self._counters["batches"] += 1
                self._counters["coalesced"] += members
                self._precision_counters[prec] = (
                    self._precision_counters.get(prec, 0) + members
                )

            scatter_start = time.monotonic()
            with self._recorder.span("scatter"):
                # each result's rows are a zero-copy VIEW of its fused
                # program's single host buffer, so scatter is pointer
                # bookkeeping — the buffer lives as long as any view
                # does. The per-item clock read is deliberate:
                # batch_scatter must measure the loop's ACTUAL
                # accumulated cost (a constant taken before the loop
                # could never show a scatter regression, which is what
                # the stage exists to surface).
                for item, rows in results:
                    meta = {
                        "queue_wait": flush_start - item.enqueued_at,
                        "batch_stack": stack_s,
                        "batch_device": device_s,
                        "batch_scatter": time.monotonic() - scatter_start,
                    }
                    if ingest_s > 0.0:
                        # raw-column batches split out the wire→device
                        # staging so the compiled path's win is
                        # attributed (the device_ingest stage)
                        meta["device_ingest"] = ingest_s
                    try:
                        fault_point(
                            "serve_scatter",
                            self._fault_key(spec, prec, item.name),
                        )
                        item.future.set_result((rows[: item.rows], meta))
                    except FaultInjected as exc:
                        # one rider's scatter failure is that rider's
                        # problem — the loop keeps resolving the rest
                        try:
                            item.future.set_exception(
                                ServeDeviceError(item.name, exc)
                            )
                        except Exception:  # noqa: BLE001 - waiter gave up
                            pass
                    except Exception:  # noqa: BLE001 - waiter gave up (504'd)
                        pass
                for item in fallbacks:
                    # the member's smallest fused program OOM'd: hand the
                    # request back for the unbatched path (None contract)
                    try:
                        item.future.set_result((None, {}))
                    except Exception:  # noqa: BLE001 - waiter gave up
                        pass
                for item, exc in failures:
                    try:
                        item.future.set_exception(exc)
                    except Exception:  # noqa: BLE001 - waiter gave up
                        pass

            useful = sum(item.rows for item in live)
            waste = 1.0 - useful / float(padded_members * padded_rows)
            try:
                # the spec's static FLOPs feature rides every batch span
                # so serve traces are self-contained perfmodel training
                # rows (features + measured device_ms in one record)
                from ..planner.costmodel import spec_flops_per_sample

                flops_per_sample = spec_flops_per_sample(spec)
            except Exception:  # noqa: BLE001 - telemetry enrichment only
                flops_per_sample = None
            batch_span.set(
                coalesced=members,
                flops_per_sample=flops_per_sample,
                padded_members=padded_members,
                padded_rows=padded_rows,
                padding_waste=round(waste, 4),
                queue_wait_max_ms=round(max(queue_waits) * 1000.0, 3),
                precision=prec,
                # predicted-vs-actual on the precision axis: the cost
                # model's precision-aware serve-step estimate next to the
                # measured device time (the serving counterpart of the
                # build plane's fleet_plan_accuracy)
                predicted_device_ms=self._predicted_step_ms(
                    spec, padded_members, padded_rows, prec
                ),
                device_ms=round(device_s * 1000.0, 3),
                ingest_ms=round(ingest_s * 1000.0, 3),
                isolated_failures=len(failures),
            )
            # link back to every request span this batch coalesced, with
            # the per-request queue wait — the causal edge that makes a
            # batch span attributable request by request in the trace
            for item in live:
                if item.trace is not None:
                    trace_id, span_id = item.trace
                    batch_span.link(
                        trace_id,
                        span_id or "",
                        name=item.name,
                        queue_wait_ms=round(
                            (flush_start - item.enqueued_at) * 1000.0, 3
                        ),
                    )
        if self.metrics is not None:
            try:
                self.metrics.observe_batch(
                    size=members,
                    occupancy=members / float(padded_members),
                    padding_waste=waste,
                )
                self.metrics.set_program_cache()
            except Exception:  # noqa: BLE001 - metrics are advisory
                pass

    # -- failure containment (the scoring ladder) ---------------------------

    def _score_live(
        self,
        fleet,
        spec,
        prec: str,
        padded_rows: int,
        live: List[BatchItem],
        stacked,
        bucket_rows: Dict[str, int],
        results: List,
        failures: List,
        fallbacks: List,
        timings: Optional[Dict[str, float]] = None,
        plan=None,
    ) -> None:
        """
        Score ``live`` with degradation, mirroring the build side's
        ``FleetTrainer._run_bucket_degraded`` ladder: a device error
        (``XlaRuntimeError`` / ``RESOURCE_EXHAUSTED``) from the fused
        program BISECTS the batch and retries each half — an over-packed
        shape resolves by splitting (and its rung is demoted), a
        poisonous member is isolated down to a one-member program whose
        failure is ITS OWN (``_member_failure``: precision degrade, then
        the circuit breaker) instead of 500ing every coalesced rider.
        Host-side exceptions propagate: they are deterministic, would
        fail every half identically, and the batcher's backstop resolves
        every waiter with a per-rider exception clone.
        """
        from ..parallel.fleet import is_device_error

        # an OOM-demoted member rung: chunk oversized batches up front
        # (not a bisect — the ladder already learned this shape's cap)
        cap = self._member_caps.get((spec, prec))
        if cap is not None and len(live) > cap:
            for start in range(0, len(live), cap):
                self._score_live(
                    fleet, spec, prec, padded_rows, live[start:start + cap],
                    stacked, bucket_rows, results, failures, fallbacks,
                    timings, plan=plan,
                )
            return
        try:
            recon = self._fused_live(
                spec, prec, padded_rows, live, stacked, bucket_rows, timings,
                plan=plan,
            )
        except Exception as exc:
            if not is_device_error(exc):
                raise
            self._count("device_errors")
            self._note_resource_exhausted(
                spec, prec, len(live), padded_rows, exc
            )
            if len(live) > 1:
                self._count("batch_bisects")
                self._recorder.event(
                    "serve_bisect",
                    members=len(live),
                    precision=prec,
                    error=repr(exc)[:200],
                )
                logger.warning(
                    "fused serving program failed for %d coalesced "
                    "member(s) (%s); bisecting",
                    len(live),
                    exc,
                )
                mid = len(live) // 2
                self._score_live(
                    fleet, spec, prec, padded_rows, live[:mid], stacked,
                    bucket_rows, results, failures, fallbacks, timings,
                    plan=plan,
                )
                self._score_live(
                    fleet, spec, prec, padded_rows, live[mid:], stacked,
                    bucket_rows, results, failures, fallbacks, timings,
                    plan=plan,
                )
            else:
                self._member_failure(
                    fleet, spec, prec, padded_rows, live[0], exc,
                    results, failures, fallbacks, timings,
                )
            return
        for i, item in enumerate(live):
            rows = recon[i]
            try:
                fault_point(
                    "serve_member_poison",
                    self._fault_key(spec, prec, item.name),
                )
            except FaultInjected:
                rows = np.full_like(np.asarray(rows, np.float32), np.nan)
            if self.config.finite_check and not bool(
                np.isfinite(np.asarray(rows[: item.rows], np.float32)).all()
            ):
                source = (
                    item.payload.host_matrix()
                    if isinstance(item.payload, RawColumns)
                    else item.payload
                )
                payload = np.asarray(source[: item.rows], np.float32)
                if bool(np.isfinite(payload).all()):
                    # finite input, non-finite output: the MEMBER is
                    # poisoned (a NaN'd parameter never crashes the
                    # program — it silently corrupts verdicts), and it
                    # fails alone like a crashing one
                    self._count("nonfinite_outputs")
                    self._member_failure(
                        fleet, spec, prec, padded_rows, item,
                        FloatingPointError(
                            f"non-finite output from member {item.name} "
                            f"({prec}) for finite input"
                        ),
                        results, failures, fallbacks, timings,
                    )
                    continue
                # non-finite INPUT rows are the client's data; the
                # model's own predict would answer NaN exactly the same
            results.append((item, rows))
            self.breakers.record_success(fleet, spec, item.name)

    def _fused_live(
        self, spec, prec: str, padded_rows: int, live: List[BatchItem],
        stacked, bucket_rows: Dict[str, int],
        timings: Optional[Dict[str, float]] = None,
        plan=None,
    ) -> np.ndarray:
        """ONE fused gather program over ``live`` (no degradation —
        `_score_live` owns the ladder); returns the [n_live, padded_rows,
        F] host buffer. Also the serve-side program/compile accounting,
        since bisection means one drained batch can run several shapes.

        With ``plan`` set the items carry raw wire columns: staging goes
        per-item through ``ingest.to_device`` (dlpack when the columns
        allow) and the batch is first assembled DEVICE-side — no host
        ``column_stack``, no host pad. Identity plans then run the
        classic program on the staged float32 batch (bit-for-bit the
        legacy math); non-identity plans run the ingest program variant
        whose prologue applies the compiled preprocessing.
        """
        from ..server.fleet_store import fleet_forward_gather, serving_backend

        for item in live:
            fault_point(
                "serve_device_program", self._fault_key(spec, prec, item.name)
            )
        stack_start = time.monotonic()
        members = len(live)
        padded_members = ladder.pad_to(members, self.member_ladder)
        indices = [bucket_rows[item.name] for item in live]
        indices += [indices[0]] * (padded_members - members)
        if plan is not None:
            import jax.numpy as jnp

            use_dlpack = dlpack_enabled()
            device_rows = [
                to_device(item.payload, padded_rows, dlpack=use_dlpack)
                for item in live
            ]
            if padded_members > members:
                pad_row = jnp.zeros((padded_rows, spec.n_features), jnp.float32)
                device_rows += [pad_row] * (padded_members - members)
            X: Any = jnp.stack(device_rows)
            if timings is not None:
                # wire→device staging is the device_ingest stage, split
                # from both batch_stack (no host stacking happened) and
                # batch_device (the fused program proper)
                timings["device_ingest"] = (
                    timings.get("device_ingest", 0.0)
                    + time.monotonic()
                    - stack_start
                )
        else:
            # payloads arrive pre-padded to this key's row rung at the
            # effective precision's payload dtype (request-thread padding):
            # the whole batch stacks in ONE numpy call, and the stack
            # inherits the dtype — no per-item python work, no silent
            # upcast, on the dispatcher thread
            X = np.stack([item.payload for item in live])
            if padded_members > members:
                padded = np.zeros(
                    (padded_members, padded_rows, spec.n_features),
                    precision.payload_dtype(prec),
                )
                padded[:members] = X
                X = padded
            if timings is not None:
                # stacking is host work: it accrues to the batch_stack
                # stage, not to the device interval wrapping this call
                timings["stack"] += time.monotonic() - stack_start
        # member gather happens INSIDE the program — one device dispatch
        # per (sub-)batch, not one per parameter leaf
        recon = np.asarray(
            fleet_forward_gather(
                spec, stacked, np.asarray(indices, np.int32), X,
                precision=prec,
                ingest=None
                if plan is None or plan.identity
                else (plan.scale, plan.offset),
            )
        )
        variant = (
            "ingest" if plan is not None and not plan.identity else "payload"
        )
        program = (
            spec, serving_backend(prec), padded_members, padded_rows, prec,
            variant,
        )
        with self._lock:
            new_program = program not in self._programs
            self._programs.add(program)
        # serve-side compile-vs-cache-hit accounting (telemetry device
        # console): a shape first seen here paid the XLA compile inside
        # this batch's device call
        note_program_execution(new_program, kind="serve", precision=prec)
        return recon

    def _materialize_host(
        self, fleet, item: BatchItem, padded_rows: int, prec: str
    ) -> np.ndarray:
        """A raw-column item's legacy staged payload (host transform +
        row pad at the precision's payload dtype) — the escape hatch for
        a batch whose compiled plan disappeared between submit and
        flush."""
        from ..server.fleet_store import _host_transform

        model = fleet.model(item.name)
        transformed = _host_transform(
            model, item.payload.host_matrix()[: item.rows]
        )
        dtype = precision.payload_dtype(prec)
        if int(transformed.shape[0]) == padded_rows:
            return np.ascontiguousarray(transformed, dtype=dtype)
        payload = np.zeros((padded_rows,) + transformed.shape[1:], dtype)
        payload[: transformed.shape[0]] = transformed
        return payload

    def _member_failure(
        self,
        fleet,
        spec,
        prec: str,
        padded_rows: int,
        item: BatchItem,
        exc: BaseException,
        results: List,
        failures: List,
        fallbacks: List,
        timings: Optional[Dict[str, float]] = None,
    ) -> None:
        """
        One member failed in ISOLATION (a one-member program, or a
        non-finite output). The remaining ladder, in order:

        1. a pure-OOM failure (``RESOURCE_EXHAUSTED``) hands the request
           back for the UNBATCHED path (its rung was demoted by
           ``_note_resource_exhausted``; the member is not to blame for
           an over-tall shape — and an OOM on a reduced-precision
           program must NOT fail the bucket's parity verdict, nor would
           a double-width f32 retry help);
        2. a reduced-precision bucket DEGRADES to f32 and the member
           retries through the f32 scoring ladder (PR 14's
           ``precision_degraded`` path — a faulting bf16/int8 program
           must not trip the breaker while f32 still serves);
        3. anything else is this member's own failure: the breaker
           records it (tripping into quarantine past the threshold) and
           the rider — only this rider — gets a :class:`ServeDeviceError`.
        """
        if "RESOURCE_EXHAUSTED" in str(exc):
            # an isolated OOM is a SHAPE problem, not member poison:
            # the rung demotion already keeps future requests off it
            self._count("oom_fallbacks")
            fallbacks.append(item)
            return
        if prec != precision.F32:
            self._degrade_bucket(fleet, spec, prec, exc)
            self._count("precision_degraded")
            try:
                names32, stacked32 = fleet.spec_bucket(spec)
            except Exception:  # noqa: BLE001 - no f32 bucket to retry on
                names32, stacked32 = [], None
            if item.name in names32:
                rows32 = {n: i for i, n in enumerate(names32)}
                item.payload = np.ascontiguousarray(item.payload, np.float32)
                self._score_live(
                    fleet, spec, precision.F32, padded_rows, [item],
                    stacked32, rows32, results, failures, fallbacks,
                    timings,
                )
                return
        self._count("members_isolated")
        logger.error(
            "serving device program failed for member %s in isolation: %r",
            item.name,
            exc,
        )
        self._recorder.event(
            "serve_member_isolated",
            member=item.name,
            precision=prec,
            error=repr(exc)[:200],
        )
        self.breakers.record_failure(fleet, spec, item.name, exc)
        failures.append((item, ServeDeviceError(item.name, exc)))

    def _degrade_bucket(self, fleet, spec, prec: str, exc: BaseException) -> None:
        """Pin a faulting reduced-precision bucket to f32: the breaker
        board's degrade set covers the gate-disabled path, and a FAILED
        gate verdict is recorded on the fleet so the governor, the
        fleet-status gate reports and a later hot-swap all agree."""
        if not self.breakers.degrade_bucket(fleet, spec, prec):
            return  # already degraded: don't spam verdicts/logs
        logger.warning(
            "degrading (%s, %s) bucket to f32 after a device error: %r",
            type(spec).__name__,
            prec,
            exc,
        )
        self._recorder.event(
            "precision_degraded",
            collection_dir=getattr(fleet, "collection_dir", ""),
            precision=prec,
            error=repr(exc)[:200],
        )
        try:
            fleet.set_precision_state(
                spec,
                prec,
                {
                    "precision": prec,
                    "spec": type(spec).__name__,
                    "passed": False,
                    "detail": f"device errors while serving {prec}: "
                    f"{exc!r}"[:300],
                },
            )
        except Exception:  # noqa: BLE001 - verdict bookkeeping is advisory
            pass

    def _note_resource_exhausted(
        self, spec, prec: str, members: int, padded_rows: int, exc: BaseException
    ) -> None:
        """OOM containment: a ``RESOURCE_EXHAUSTED`` demotes the ladder
        rung it happened on — the member axis while the batch is still
        splittable, the row axis once a single member OOM'd — so the
        engine stops retrying a shape the device already refused
        (mirroring the planner's bisected-OOM rung drop)."""
        if "RESOURCE_EXHAUSTED" not in str(exc):
            return
        demoted = None
        model_informed = False
        padded = ladder.pad_to(members, self.member_ladder) or members
        if members > 1:
            cap = self._hbm_aware_cap(spec, prec, padded, padded_rows, "members")
            model_informed = cap is not None
            if cap is None:
                cap = max(1, padded // 2)
        else:
            cap = self._hbm_aware_cap(spec, prec, padded, padded_rows, "rows")
            model_informed = cap is not None
            if cap is None:
                lower = [r for r in self.config.row_ladder if r < padded_rows]
                cap = max(lower) if lower else 0
        with self._lock:
            if members > 1:
                current = self._member_caps.get((spec, prec))
                if current is None or cap < current:
                    self._member_caps[(spec, prec)] = cap
                    demoted = ("members", cap)
            else:
                current = self._row_caps.get((spec, prec))
                if current is None or cap < current:
                    self._row_caps[(spec, prec)] = cap
                    demoted = ("rows", cap)
        if demoted is None:
            return
        self._count("rung_demotions")
        axis, cap = demoted
        logger.warning(
            "RESOURCE_EXHAUSTED at (%s members, %s rows, %s): capping the "
            "%s ladder for %s at %d",
            members,
            padded_rows,
            prec,
            axis,
            type(spec).__name__,
            cap,
        )
        self._recorder.event(
            "serve_rung_demoted",
            spec=type(spec).__name__,
            precision=prec,
            axis=axis,
            cap=cap,
            model_informed=model_informed,
            error=repr(exc)[:200],
        )

    def _on_breaker_transition(
        self, member: str, old: str, new: str, info: dict
    ) -> None:
        """Breaker state changes fan out to every observability surface:
        engine counters, the span recorder (trace narration), the
        fleet-health ledger (which the lifecycle supervisor reads to
        nominate tripped members for rebuild), and Prometheus."""
        if new == "open":
            self._count("breaker_trips")
        self._recorder.event(
            "serve_breaker",
            member=member,
            old_state=old,
            new_state=new,
            trips=info.get("trips"),
            cooldown_s=info.get("cooldown_s"),
            error=info.get("last_error", ""),
        )
        try:
            from ..telemetry import ledger_for

            # the ANCHOR collection dir — the operator's stable handle,
            # the same key the server's request feed and the lifecycle
            # supervisor use. build_app wires it through the app's
            # configurable MODEL_COLLECTION_DIR_ENV_VAR; the env read is
            # the engine-without-an-app fallback (the default var name —
            # a deployment contract, not a GORDO_TPU_* knob)
            anchor = self.ledger_anchor or os.environ.get(
                "MODEL_COLLECTION_DIR"
            )
            if anchor:
                ledger_for(anchor).record_breaker(
                    member,
                    new,
                    trips=info.get("trips"),
                    cooldown_s=info.get("cooldown_s"),
                    reason=info.get("last_error") or None,
                )
        except Exception:  # noqa: BLE001 - the ledger is advisory
            logger.debug("breaker ledger feed failed", exc_info=True)
        if self.metrics is not None:
            try:
                self.metrics.observe_breaker(new)
                self.metrics.set_breaker_open(
                    self.breakers.snapshot(detail_cap=0)["open"]
                )
            except Exception:  # noqa: BLE001 - metrics are advisory
                pass

    def _cost_model(self):
        """The engine's cost model, built ONCE per engine: the analytic
        defaults, or the (possibly learned) table that
        ``GORDO_TPU_PERFMODEL_TABLE`` names — a corrupt/missing table
        degrades to the analytic defaults inside ``load_table_safe``, so
        this never raises past construction."""
        model = self._cost_model_cache
        if model is None:
            from ..planner.costmodel import CostModel, load_table_safe

            model = CostModel(
                load_table_safe(env_str(PERFMODEL_TABLE_ENV, None))
            )
            self._cost_model_cache = model
        return model

    def _predicted_step_ms(
        self, spec, members: int, rows: int, prec: str
    ) -> float:
        """The cost model's predicted device milliseconds for one fused
        batch at this ladder shape and precision, cached per shape (the
        planner's estimator is pure arithmetic, but the batch path runs
        at request rates). -1.0 when the estimator is unavailable."""
        key = (spec, members, rows, prec)
        cached = self._step_predictions.get(key)
        if cached is None:
            try:
                cached = round(
                    self._cost_model().predict_serve_step_s(
                        spec, members, rows, prec
                    )
                    * 1000.0,
                    4,
                )
            except Exception:  # noqa: BLE001 - prediction is telemetry,
                # never the batch path's problem
                cached = -1.0
            if len(self._step_predictions) > 4096:
                self._step_predictions.clear()
            self._step_predictions[key] = cached
        return cached

    def _model_row_cap(self, spec, prec: str) -> Optional[int]:
        """The predicted-HBM row cap for one (spec, precision) under
        ``GORDO_TPU_PERFMODEL_BATCH_CAP_BYTES``: the tallest row-ladder
        rung whose WORST-CASE fused batch (full member ladder) stays
        under the byte budget. None (uncapped) when the knob is off or
        the estimate is unavailable; 0 sends every batch unbatched."""
        cap_bytes = env_int(PERFMODEL_CAP_ENV, 0)
        if cap_bytes <= 0:
            return None
        key = (spec, prec)
        if key in self._model_row_caps:
            return self._model_row_caps[key]
        cap: Optional[int] = None
        try:
            model = self._cost_model()
            top_members = self.member_ladder[-1]
            fitting = [
                rung
                for rung in self.config.row_ladder
                if model.predict_serve_hbm_bytes(
                    spec, top_members, rung, prec
                )
                <= cap_bytes
            ]
            cap = max(fitting) if fitting else 0
            if cap != self.config.row_ladder[-1]:
                logger.info(
                    "perfmodel batch cap: (%s, %s) rows capped at %d "
                    "(predicted HBM budget %d bytes)",
                    type(spec).__name__,
                    prec,
                    cap,
                    cap_bytes,
                )
        except Exception:  # noqa: BLE001 - an unpredictable shape stays
            # uncapped rather than unbatched
            cap = None
        with self._lock:
            if len(self._model_row_caps) > 4096:
                self._model_row_caps.clear()
            self._model_row_caps[key] = cap
        return cap

    def _hbm_aware_cap(
        self, spec, prec: str, padded_members: int, padded_rows: int, axis: str
    ) -> Optional[int]:
        """OOM demotion informed by predicted HBM
        (``GORDO_TPU_PERFMODEL_BREAKER``): the largest lower rung on
        ``axis`` whose predicted bytes fit under ``safety ×`` the failed
        shape's prediction — possibly dropping SEVERAL rungs at once
        where the fixed heuristic single-steps toward a shape the model
        already says cannot fit. None defers to the fixed heuristic."""
        if not env_bool(PERFMODEL_BREAKER_ENV, False):
            return None
        try:
            model = self._cost_model()
            safety = env_float(PERFMODEL_BREAKER_SAFETY_ENV, 0.8) or 0.8
            failed = model.predict_serve_hbm_bytes(
                spec, padded_members, padded_rows, prec
            )
            if failed <= 0:
                return None
            budget = failed * float(safety)
            if axis == "members":
                candidates = [
                    v for v in self.member_ladder if v < padded_members
                ]
                fitting = [
                    v
                    for v in candidates
                    if model.predict_serve_hbm_bytes(
                        spec, v, padded_rows, prec
                    )
                    <= budget
                ]
            else:
                candidates = [
                    r for r in self.config.row_ladder if r < padded_rows
                ]
                fitting = [
                    r
                    for r in candidates
                    if model.predict_serve_hbm_bytes(
                        spec, padded_members, r, prec
                    )
                    <= budget
                ]
            return max(fitting) if fitting else None
        except Exception:  # noqa: BLE001 - the fixed heuristic is the
            # fallback, never a crashed demotion
            return None

    # -- warmup -------------------------------------------------------------

    def warmup_collection(
        self, collection_dir: str, names: Optional[List[str]] = None
    ) -> Dict[str, Any]:
        """Load the revision's models and precompile its fused programs
        at every ladder shape a request could hit (rows capped at
        ``warmup_max_rows`` — taller rungs compile on first use), at
        each spec's ACTIVE serving precision: the precision-parity gate
        runs here, off the request path, so the first real reduced-
        precision request finds both a verdict and a warm program."""
        from ..server.fleet_store import STORE

        fleet = STORE.fleet(collection_dir)
        fleet.warm(names)
        return self.warmup_fleet(fleet)

    def warmup_fleet(self, fleet) -> Dict[str, Any]:
        from ..server.fleet_store import fleet_forward_gather, serving_backend

        start = time.monotonic()
        warm_rows = [
            rung
            for rung in self.config.row_ladder
            if rung <= self.config.warmup_max_rows
        ] or [self.config.row_ladder[0]]
        specs = {
            spec
            for spec in fleet.loaded_specs().values()
            if isinstance(spec, FeedForwardSpec)
        }
        compiled = 0
        # warmup order: alphabetical by default; predicted-hot first
        # under GORDO_TPU_PERFMODEL_WARMUP — the specs (and shapes) that
        # will cost the most device time compile first, so an early
        # request is likelier to find ITS program warm when warmup is
        # racing live traffic. repr stays the tie-break: equal
        # predictions keep the deterministic compile order the
        # compile-count tests pin.
        spec_order = sorted(specs, key=repr)
        hot_first = env_bool(PERFMODEL_WARMUP_ENV, False)
        if hot_first:
            try:
                model = self._cost_model()
                top_rows = max(warm_rows)
                top_members = self.member_ladder[-1]
                spec_order = sorted(
                    specs,
                    key=lambda s: (
                        -model.predict_serve_step_s(
                            s, top_members, top_rows, precision.F32
                        ),
                        repr(s),
                    ),
                )
            except Exception:  # noqa: BLE001 - ordering is advisory
                spec_order = sorted(specs, key=repr)
        for spec in spec_order:
            # the gate decides which precision this spec's ladder warms:
            # a passed gate warms the reduced programs, a failed one
            # warms the f32 programs the degraded traffic will hit
            desired = precision.resolve_precision(spec, self.config.precision)
            if desired == precision.F32 and not getattr(spec, "precision", ""):
                # mirror the request path's learned nomination so warmup
                # compiles the programs live traffic will actually hit
                preferred = precision.model_preferred(
                    spec,
                    self.member_ladder[-1],
                    max(warm_rows),
                    self._cost_model(),
                )
                if preferred:
                    desired = preferred
            prec = (
                self.governor.effective_precision(
                    fleet, spec, desired, recorder=self._recorder
                )
                if desired != precision.F32
                else precision.F32
            )
            backend = serving_backend(prec)
            try:
                bucket_names, stacked = fleet.spec_bucket(spec, prec)
            except KeyError:
                continue
            n_bucket = len(bucket_names)
            dtype = precision.payload_dtype(prec)
            # with a compiled non-identity plan resident, f32 traffic
            # runs the INGEST program variant — warm that one too, so
            # the first raw-column batch finds its prologue compiled
            plan = None
            if prec == precision.F32 and ingest_compiled():
                try:
                    plan = fleet.ingest_plan(spec)
                except Exception:  # noqa: BLE001 - warmup is best-effort
                    plan = None
            variants = [("payload", None)]
            if plan is not None and not plan.identity:
                variants.append(("ingest", (plan.scale, plan.offset)))
            # within a spec, hot-first walks the ladders top-down (the
            # tallest shapes carry the highest predicted device cost)
            member_order = (
                list(reversed(self.member_ladder))
                if hot_first
                else self.member_ladder
            )
            rows_order = (
                list(reversed(warm_rows)) if hot_first else warm_rows
            )
            for padded_members in member_order:
                indices = np.arange(padded_members, dtype=np.int32) % n_bucket
                for padded_rows in rows_order:
                    for variant, ingest_arrays in variants:
                        program = (
                            spec, backend, padded_members, padded_rows, prec,
                            variant,
                        )
                        with self._lock:
                            new = program not in self._programs
                            if new:
                                self._programs.add(program)
                        if not new:
                            continue
                        X = np.zeros(
                            (padded_members, padded_rows, spec.n_features),
                            np.float32 if variant == "ingest" else dtype,
                        )
                        with self._recorder.span(
                            "warmup_program",
                            padded_members=padded_members,
                            padded_rows=padded_rows,
                            precision=prec,
                        ):
                            np.asarray(
                                fleet_forward_gather(
                                    spec, stacked, indices, X, precision=prec,
                                    ingest=ingest_arrays,
                                )
                            )
                        note_program_execution(
                            True, kind="serve", precision=prec
                        )
                        compiled += 1
        self._count("warmup_programs", compiled)
        if self.metrics is not None:
            try:
                self.metrics.set_program_cache()
            except Exception:  # noqa: BLE001 - metrics are advisory
                pass
        seconds = time.monotonic() - start
        logger.info(
            "serve warmup: %d program(s) over %d spec bucket(s) in %.2fs",
            compiled,
            len(specs),
            seconds,
        )
        return {"programs": compiled, "specs": len(specs), "seconds": seconds}

    # -- introspection / lifecycle ------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            stats = dict(self._counters)
            stats["programs"] = len(self._programs)
            stats["precision"] = {
                "config": self.config.precision,
                "coalesced": dict(self._precision_counters),
            }
            demotions = {
                "members": {
                    f"{type(s).__name__}:{p}": cap
                    for (s, p), cap in self._member_caps.items()
                },
                "rows": {
                    f"{type(s).__name__}:{p}": cap
                    for (s, p), cap in self._row_caps.items()
                },
            }
        stats["pending"] = self._batcher.pending()
        stats["breaker"] = self.breakers.summary()
        stats["demoted_rungs"] = demotions
        stats["ingest"] = {
            "compiled": ingest_compiled(),
            "dlpack": dlpack_enabled(),
            **ingest_stats(),
        }
        return stats

    def program_shapes(self) -> List[Tuple]:
        with self._lock:
            return sorted(
                (repr(s), b, m, r, p, v)
                for (s, b, m, r, p, v) in self._programs
            )

    def shutdown(self, drain: bool = True) -> None:
        """Stop the dispatcher(s); with ``drain`` everything already
        queued still scores before the threads exit. The trace recorder
        is process-shared (the server's request export writes there
        too), so the engine does not close it."""
        self._batcher.shutdown(drain=drain)

    # -- internal hooks -----------------------------------------------------

    def _count(self, key: str, by: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + by

    def _on_shed(self, reason: str, n: int) -> None:
        if reason == "queue_full":
            self._count("shed_queue_full", n)
        elif reason == "deadline":
            self._count("shed_deadline", n)
        elif reason == "runner_error":
            # the batcher's backstop fired: a non-device runner crash
            # resolved every rider (distinct from device_errors, which
            # the containment ladder caught and isolated)
            self._count("shed_runner_error", n)
        if self.metrics is not None:
            try:
                self.metrics.observe_shed(reason, n)
            except Exception:  # noqa: BLE001 - metrics are advisory
                pass

    def _on_depth(self, depth: int) -> None:
        if self.metrics is not None:
            try:
                self.metrics.set_queue_depth(depth)
            except Exception:  # noqa: BLE001 - metrics are advisory
                pass


# -- the process-global engine ----------------------------------------------

_engine: Optional[ServeEngine] = None
_engine_lock = threading.Lock()


def get_engine() -> Optional[ServeEngine]:
    """The installed engine, or None (batching off / not configured)."""
    return _engine


def ensure_engine() -> Optional[ServeEngine]:
    """Create-and-install the process engine when ``GORDO_TPU_BATCHING``
    is on (idempotent); None when batching is off."""
    global _engine
    if not batching_enabled():
        return None
    with _engine_lock:
        if _engine is None:
            _engine = ServeEngine()
            atexit.register(_shutdown_at_exit)
            logger.info(
                "micro-batching engine on: max_size=%d max_delay=%.1fms "
                "queue_depth=%d row_ladder=%s",
                _engine.config.max_size,
                _engine.config.max_delay_s * 1000.0,
                _engine.config.queue_depth,
                _engine.config.row_ladder,
            )
        return _engine


def install_engine(engine: Optional[ServeEngine]) -> None:
    """Install a specific engine (tests; pass None to uninstall)."""
    global _engine
    with _engine_lock:
        _engine = engine


def reset_engine(drain: bool = True) -> None:
    """Shut down and uninstall the process engine (tests, reload)."""
    global _engine
    with _engine_lock:
        engine, _engine = _engine, None
    if engine is not None:
        engine.shutdown(drain=drain)


def _shutdown_at_exit() -> None:
    engine = _engine
    if engine is not None:
        try:
            engine.shutdown(drain=True)
        except Exception:  # noqa: BLE001 - interpreter is going down anyway
            pass


# -- streaming-plane breaker sharing ----------------------------------------

_stream_breakers: Optional[BreakerBoard] = None


def stream_breaker_board(
    on_transition: Optional[Callable[..., None]] = None,
) -> BreakerBoard:
    """The per-member breaker board the STREAMING plane quarantines
    through. With the micro-batching engine installed this is the
    engine's OWN board, so the HTTP and stream planes share one
    quarantine truth — a member tripped by request traffic is
    immediately quarantined on every stream, and a stream-probed
    recovery reopens the request path too. Without an engine (batching
    is off by default) a process-global standalone board is created on
    first use: streaming fault containment must not depend on the
    batching switch. ``on_transition`` is only adopted when this call
    creates the standalone board (the engine's board keeps the engine's
    own observability fan-out)."""
    engine = get_engine()
    if engine is not None:
        return engine.breakers
    global _stream_breakers
    with _engine_lock:
        if _stream_breakers is None:
            _stream_breakers = BreakerBoard(on_transition=on_transition)
        return _stream_breakers


def reset_stream_breakers() -> None:
    """Drop the standalone stream breaker board (tests, reload)."""
    global _stream_breakers
    with _engine_lock:
        _stream_breakers = None
