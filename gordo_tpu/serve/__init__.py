"""
Cross-request micro-batching for the model server.

Thousands of concurrent single-model predict/anomaly requests each
launching their own tiny device program leave the accelerator idle
between launches. This package coalesces them: requests enqueue keyed by
``(revision, spec bucket)``, a dispatcher drains the queue under an
adaptive flush policy, and every drained batch runs as ONE fused
``fleet_forward`` program — with shape-ladder padding (bounded jit
cache), startup warmup, and admission control (429/504 backpressure).

Master switch: ``GORDO_TPU_BATCHING`` (default off — the unbatched
per-request path is the fallback and the default). See
``docs/serving.md`` for the full knob catalog.
"""

from .batcher import (
    BatcherStopped,
    BatchItem,
    BatchShedError,
    DeadlineExceeded,
    MicroBatcher,
    QueueFullError,
    clone_exception,
)
from .breaker import (
    BreakerBoard,
    BreakerConfig,
    MemberQuarantined,
    ServeDeviceError,
)
from .engine import (
    ServeConfig,
    ServeEngine,
    batching_enabled,
    ensure_engine,
    get_engine,
    install_engine,
    reset_engine,
    reset_stream_breakers,
    stream_breaker_board,
)
from .ladder import member_ladder, pad_to, parse_ladder, row_ladder
from .precision import (
    PRECISIONS,
    ParityConfig,
    PrecisionGovernor,
    evaluate_parity,
    payload_dtype,
    recon_agreement,
    resolve_precision,
    serve_precision,
    verdict_agreement,
)

__all__ = [
    "BatchItem",
    "BatchShedError",
    "BatcherStopped",
    "BreakerBoard",
    "BreakerConfig",
    "DeadlineExceeded",
    "MemberQuarantined",
    "MicroBatcher",
    "PRECISIONS",
    "ParityConfig",
    "PrecisionGovernor",
    "QueueFullError",
    "ServeConfig",
    "ServeDeviceError",
    "ServeEngine",
    "clone_exception",
    "batching_enabled",
    "ensure_engine",
    "evaluate_parity",
    "get_engine",
    "install_engine",
    "member_ladder",
    "pad_to",
    "parse_ladder",
    "payload_dtype",
    "recon_agreement",
    "reset_engine",
    "reset_stream_breakers",
    "resolve_precision",
    "row_ladder",
    "serve_precision",
    "stream_breaker_board",
    "verdict_agreement",
]
