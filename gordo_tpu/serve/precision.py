"""
The serving precision ladder: per-revision bf16 / int8 inference programs
behind a precision-parity gate.

Every inference program used to run f32. The TPU serving literature
(PAPERS.md: the Gemma-on-TPU serving case study) puts the real serving
throughput in reduced precision — bf16 halves the weight bytes each
fused batch re-reads from HBM, int8 weight-only quantization quarters
them — so the serve engine's shape ladder gains a **precision axis**:

- the precision *vocabulary* (:data:`PRECISIONS`, :func:`normalize`)
  and its resolution order — a per-spec ``precision:`` field from the
  config surface wins, else the ``GORDO_TPU_SERVE_PRECISION`` knob,
  else ``f32`` (the default, byte-identical to the pre-precision
  serving path);
- *casting* (:func:`cast_bucket_params`): the revision's resident f32
  bucket is cast (bf16) or per-channel weight-quantized (int8) ONCE at
  fleet load and cached on the :class:`RevisionFleet` COW maps — never
  per request;
- the *parity gate* (:func:`evaluate_parity`, :class:`PrecisionGovernor`):
  a reduced-precision bucket only serves after its anomaly verdicts
  agree with f32 within tolerance on a deterministic probe window; a
  failed gate **degrades that bucket to f32** (logged + counted, never
  an error). The verdict-agreement math (:func:`recon_agreement` /
  :func:`verdict_agreement`) is shared with the lifecycle canary gate
  (``lifecycle/gates.py``) and the f32-vs-bf16 model parity tests.

Dtype contract (mirrors models/nn.py): weights and activations run at
the serving precision, the program OUTPUT is always float32 — the
DiffBased threshold/confidence math downstream never sees a reduced
dtype.
"""

import logging
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..planner.costmodel import PRECISION_ALIASES as _ALIASES
from ..utils.env import env_bool, env_float, env_int, env_str

logger = logging.getLogger(__name__)

PRECISION_ENV = "GORDO_TPU_SERVE_PRECISION"
GATE_ENV = "GORDO_TPU_PRECISION_GATE"
PERFMODEL_PRECISION_ENV = "GORDO_TPU_PERFMODEL_PRECISION"

#: the serving precision ladder, widest first; ``f32`` is the default
#: and the degrade target. ``int8`` is per-channel weight-only
#: quantization (activations run bf16) and is EXPERIMENTAL. The alias
#: vocabulary is owned by ``planner.costmodel`` (the lowest layer that
#: speaks precision — planner may not import serve) so the engine and
#: the cost model can never disagree about a precision's name.
PRECISIONS: Tuple[str, ...] = ("f32", "bf16", "int8")

F32 = "f32"

#: (raw value) spellings already warned about — malformed knob values
#: warn once, not once per request
_warned: set = set()


def normalize(value: Optional[str], default: str = F32) -> str:
    """The canonical precision name for ``value`` (``float32`` → ``f32``,
    ``bfloat16`` → ``bf16``, ...); unknown spellings warn once and fall
    back to ``default`` — a typo'd knob must degrade to f32, never take
    the serving path down."""
    if not value:
        return default
    name = _ALIASES.get(str(value).strip().lower())
    if name is None:
        if value not in _warned:
            _warned.add(value)
            logger.warning(
                "Unknown serving precision %r; using %r (known: %s)",
                value,
                default,
                "/".join(PRECISIONS),
            )
        return default
    return name


def serve_precision() -> str:
    """The process-default serving precision
    (``GORDO_TPU_SERVE_PRECISION``, default ``f32``)."""
    return normalize(env_str(PRECISION_ENV, F32))


def resolve_precision(spec: Any, default: Optional[str] = None) -> str:
    """The precision ``spec`` serves at: the spec's own ``precision:``
    field (the config surface — set via the model factory kwarg) wins;
    an unset field inherits ``default`` (the engine's configured knob,
    else the env)."""
    if default is None:
        default = serve_precision()
    declared = getattr(spec, "precision", "")
    return normalize(declared, normalize(default)) if declared else normalize(default)


def gate_enabled() -> bool:
    """The parity gate master switch (``GORDO_TPU_PRECISION_GATE``,
    default ON — reduced precision must EARN traffic)."""
    return env_bool(GATE_ENV, True)


def model_preferred(
    spec: Any, members: int, rows: int, cost_model: Any
) -> Optional[str]:
    """The precision rung the LEARNED performance model predicts fastest
    for this spec at a representative fused shape, or None to keep the
    configured resolution. Deliberately narrow:

    - gated on ``GORDO_TPU_PERFMODEL_PRECISION`` (default off);
    - answers only from MEASURED evidence — every candidate rung must
      have an in-domain learned ``fleet_forward`` prediction. The
      analytic per-precision factors are priors that ALWAYS say reduced
      is faster; steering on them would flip the f32 default for every
      deployment the moment the knob turns on, learned table or not;
    - advisory only: the winner still rides the parity gate and the
      breaker degrade set downstream, exactly like a configured
      precision.
    """
    if not env_bool(PERFMODEL_PRECISION_ENV, False):
        return None
    try:
        from ..planner.costmodel import (
            learned_feature_vector,
            spec_flops_per_sample,
        )

        flops = spec_flops_per_sample(spec)
        best: Optional[Tuple[float, str]] = None
        for candidate in PRECISIONS:
            predicted = cost_model.table.learned_predict(
                "device_ms",
                "fleet_forward",
                learned_feature_vector(flops, members, rows, 1, candidate),
            )
            if predicted is None:
                return None  # partial evidence: keep the configured rung
            if best is None or predicted < best[0]:
                best = (predicted, candidate)
        if best is None or best[1] == F32:
            return None
        return best[1]
    except Exception:  # noqa: BLE001 - advisory path, never a gate
        return None


# -- payload dtypes -----------------------------------------------------------

_payload_dtypes: Dict[str, Any] = {}


def payload_dtype(precision: str = F32):
    """
    The numpy dtype request payloads are staged in for one precision —
    THE one place the serve engine derives its stack/padding dtypes
    from, so the batch path cannot silently upcast a reduced-precision
    program's inputs: ``f32`` → float32; ``bf16`` and ``int8``
    (activations run bf16 under weight-only quantization) → ml_dtypes'
    bfloat16, halving the host-side stack and the host→device transfer.
    Falls back to float32 when the bfloat16 numpy dtype is unavailable
    (the device program casts its inputs either way).
    """
    precision = normalize(precision)
    cached = _payload_dtypes.get(precision)
    if cached is not None:
        return cached
    dtype = np.float32
    if precision in ("bf16", "int8"):
        try:
            import ml_dtypes

            dtype = np.dtype(ml_dtypes.bfloat16)
        except Exception:  # noqa: BLE001 - optional fast path only
            dtype = np.float32
    _payload_dtypes[precision] = dtype
    return dtype


# -- bucket casting / quantization -------------------------------------------


def cast_bucket_params(stacked: Any, precision: str):
    """
    One revision bucket's stacked f32 params at ``precision``: bf16 is a
    whole-tree cast; int8 replaces every weight matrix with a per-member,
    per-output-channel symmetric quantization (``W ≈ Wq * scale``, Wq
    int8, scale f32 ``[..., 1, d_out]``) while biases stay f32. Runs
    once per (revision, spec, precision) at fleet load — the result is
    cached on the RevisionFleet, never rebuilt per request.
    """
    import jax
    import jax.numpy as jnp

    # strict: this is an internal API handed already-normalized names;
    # silently serving f32 for a typo here would mask an engine bug
    requested = precision
    precision = _ALIASES.get(str(precision).strip().lower())
    if precision is None:
        raise ValueError(f"unknown serving precision {requested!r}")
    if precision == F32:
        return stacked
    if precision == "bf16":
        return jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16), stacked
        )
    if precision == "int8":
        quantized = {}
        for layer, leaves in stacked.items():
            W = jnp.asarray(leaves["W"], jnp.float32)
            # symmetric per-channel scales over the input axis; the
            # tiny clamp keeps a dead (all-zero) channel from minting
            # NaNs out of 0/0
            scale = jnp.max(jnp.abs(W), axis=-2, keepdims=True) / 127.0
            scale = jnp.maximum(scale, 1e-12)
            quantized[layer] = {
                "W": jnp.clip(jnp.round(W / scale), -127, 127).astype(jnp.int8),
                "scale": scale,
                "b": jnp.asarray(leaves["b"], jnp.float32),
            }
        return quantized
    raise ValueError(f"unknown serving precision {precision!r}")


def forward_feedforward_quantized(spec: Any, params: Dict, x):
    """
    The int8 weight-quantized serving forward for ONE member (the fused
    program vmaps it over the gathered bucket): weights dequantize in
    registers (``Wq * scale`` in bf16), activations run bf16, output is
    float32 — the same output contract as every other serving program.
    Inference-only: no activity penalty (mirrors the Pallas kernel).
    """
    import jax.numpy as jnp

    from ..ops.activations import resolve_activation

    compute = jnp.bfloat16
    h = x.astype(compute)
    for i in range(len(spec.dims)):
        layer = params[f"dense_{i}"]
        W = layer["W"].astype(compute) * layer["scale"].astype(compute)
        h = resolve_activation(spec.activations[i])(
            h @ W + layer["b"].astype(compute)
        )
    out_layer = params["out"]
    W = out_layer["W"].astype(compute) * out_layer["scale"].astype(compute)
    out = h @ W + out_layer["b"].astype(compute)
    return resolve_activation(spec.out_activation)(out).astype(jnp.float32)


# -- parity math (shared with lifecycle gates and the model parity tests) ----


@dataclass
class ParityConfig:
    """Precision-parity gate knobs, env-overridable (``from_env``)."""

    #: minimum per-member verdict/row agreement fraction
    agreement: float = 0.98
    #: relative tolerance for the reconstruction-closeness fallback
    #: (members without a fitted detector threshold); the absolute floor
    #: is 1% of reconstruction space — near-zero rows otherwise read
    #: bf16's last-place noise as divergence
    rtol: float = 0.05
    atol: float = 0.01
    #: probe window height (rows scored per member)
    probe_rows: int = 128

    @classmethod
    def from_env(cls) -> "ParityConfig":
        return cls(
            agreement=env_float("GORDO_TPU_GATE_PRECISION_AGREEMENT", 0.98),
            rtol=env_float("GORDO_TPU_GATE_PRECISION_RTOL", 0.05),
            probe_rows=max(8, env_int("GORDO_TPU_GATE_PRECISION_PROBE_ROWS", 128)),
        )


def recon_agreement(
    recon_a: np.ndarray,
    recon_b: np.ndarray,
    rtol: float = 0.05,
    atol: float = 1e-3,
) -> Dict[str, Any]:
    """
    Row-wise closeness of two reconstructions of the SAME input: the
    fraction of rows whose max absolute difference stays within
    ``atol + rtol * row magnitude``. This is the tolerance-based
    f32-vs-bf16 parity check (it replaced the seed-luck convergence
    assert the bf16 suite used to carry) and the gate's fallback for
    members without a fitted anomaly threshold.
    """
    a = np.asarray(recon_a, np.float64)
    b = np.asarray(recon_b, np.float64)
    if a.shape != b.shape:
        return {"mode": "recon", "agreement": 0.0, "rows": 0,
                "detail": f"shape mismatch {a.shape} vs {b.shape}"}
    if a.ndim == 1:
        a, b = a[:, None], b[:, None]
    # leading axes (e.g. a stacked [members, rows, features] batch)
    # flatten into one row axis: a "row" is one feature vector
    a = a.reshape(-1, a.shape[-1])
    b = b.reshape(-1, b.shape[-1])
    diff = np.abs(a - b).max(axis=-1)
    budget = atol + rtol * np.abs(a).max(axis=-1)
    rows = int(diff.shape[0])
    agree = int(np.count_nonzero(diff <= budget))
    return {
        "mode": "recon",
        "agreement": round(agree / rows, 6) if rows else 1.0,
        "rows": rows,
        "max_diff": round(float(diff.max()), 6) if rows else 0.0,
    }


def verdict_agreement(
    recon_a: np.ndarray,
    recon_b: np.ndarray,
    y: np.ndarray,
    scaler: Any = None,
    threshold: Optional[float] = None,
    rtol: float = 0.05,
    atol: float = 1e-3,
) -> Dict[str, Any]:
    """
    Anomaly-VERDICT agreement between two reconstructions: each is
    turned into the DiffBased detector's per-row scaled mse (f32 math —
    thresholds and anomaly arithmetic never run reduced) and compared
    against ``threshold``; agreement is the fraction of rows whose
    anomalous/normal verdict matches. Falls back to
    :func:`recon_agreement` when there is no scaler/threshold to take a
    verdict from.
    """
    if scaler is None or not threshold or threshold <= 0:
        return recon_agreement(recon_a, recon_b, rtol=rtol, atol=atol)
    try:
        scaled_y = np.asarray(scaler.transform(y), np.float64)
        scaled_a = np.asarray(scaler.transform(recon_a), np.float64)
        scaled_b = np.asarray(scaler.transform(recon_b), np.float64)
    except Exception:  # noqa: BLE001 - an unfit/odd scaler: fall back to
        # the thresholdless closeness check rather than failing the gate
        # on gate machinery
        return recon_agreement(recon_a, recon_b, rtol=rtol, atol=atol)
    mse_a = np.mean(np.square(scaled_a - scaled_y), axis=1)
    mse_b = np.mean(np.square(scaled_b - scaled_y), axis=1)
    verdict_a = mse_a > threshold
    verdict_b = mse_b > threshold
    rows = int(len(mse_a))
    agree = int(np.count_nonzero(verdict_a == verdict_b))
    return {
        "mode": "verdict",
        "agreement": round(agree / rows, 6) if rows else 1.0,
        "rows": rows,
        "flagged_f32": int(np.count_nonzero(verdict_a)),
        "flagged_reduced": int(np.count_nonzero(verdict_b)),
    }


def _probe_rows(model: Any, n_features: int, rows: int, seed: int) -> np.ndarray:
    """A deterministic probe window in model-input space: uniform inside
    the detector scaler's learned data range when one is fit (in-
    distribution rows make the verdict comparison meaningful), else
    standard normal. Seeded per member — the gate's answer for a given
    revision never depends on evaluation order."""
    rng = np.random.default_rng(seed)
    scaler = getattr(model, "scaler", None)
    lo = getattr(scaler, "data_min_", None)
    hi = getattr(scaler, "data_max_", None)
    if lo is not None and hi is not None and len(lo) == n_features:
        lo = np.asarray(lo, np.float64)
        hi = np.asarray(hi, np.float64)
        span = np.where(hi > lo, hi - lo, 1.0)
        return (lo + rng.random((rows, n_features)) * span).astype(np.float32)
    return rng.standard_normal((rows, n_features)).astype(np.float32)


def evaluate_parity(
    fleet: Any,
    spec: Any,
    precision: str,
    config: Optional[ParityConfig] = None,
) -> Dict[str, Any]:
    """
    The precision-parity gate for one revision's spec bucket: score a
    deterministic probe window through the f32 bucket AND the
    ``precision`` bucket (both fused programs — the exact code path a
    served batch takes) and require every member's anomaly verdicts to
    agree within tolerance. Returns a JSON-able report
    (``{"passed": bool, "precision", "agreement_min", "members", ...}``)
    that the caller caches on the fleet; the fused reduced-precision
    program compiled here is the same one warmup would mint, so gating
    doubles as precompilation.
    """
    from ..server.fleet_store import _host_transform, fleet_forward_gather

    config = config or ParityConfig.from_env()
    precision = normalize(precision)
    report: Dict[str, Any] = {
        "precision": precision,
        "spec": type(spec).__name__,
        "n_features": getattr(spec, "n_features", None),
        "passed": True,
        "members": {},
    }
    if precision == F32:
        report["detail"] = "f32 is the reference; nothing to gate"
        return report

    # ONE consistent membership snapshot: the f32 and the cast bucket
    # are two separate reads, and a concurrent model load between them
    # would pair recon_f32[i] with a DIFFERENT member's recon_lp[i]
    # (verdicts collapse, the gate records a spurious fail). Retake both
    # until the membership (and the fleet's bucket epoch, when it has
    # one) agrees across the pair.
    for _ in range(4):
        epoch = getattr(fleet, "_bucket_epoch", None)
        names, stacked = fleet.spec_bucket(spec)
        cast_names, cast = fleet.spec_bucket(spec, precision)
        if cast_names == names and getattr(fleet, "_bucket_epoch", None) == epoch:
            break
    else:
        raise RuntimeError(
            "bucket membership kept changing during parity evaluation"
        )
    report["bucket_epoch"] = epoch
    rows = int(config.probe_rows)
    payloads = []
    models = []
    probes = []
    for i, name in enumerate(names):
        model = fleet.model(name)
        probe = _probe_rows(model, spec.n_features, rows, seed=i + 1)
        transformed = _host_transform(model, probe)
        if transformed.shape != (rows, spec.n_features):
            # a row/width-changing host pipeline: probe in transformed
            # space directly so every member still stacks to one shape
            transformed = np.asarray(
                np.random.default_rng(i + 1).standard_normal(
                    (rows, spec.n_features)
                ),
                np.float32,
            )
            probe = transformed
        payloads.append(transformed)
        probes.append(probe)
        models.append(model)

    indices = np.arange(len(names), dtype=np.int32)
    X32 = np.stack(payloads).astype(np.float32)
    Xlp = X32.astype(payload_dtype(precision))
    recon_f32 = np.asarray(fleet_forward_gather(spec, stacked, indices, X32))
    recon_lp = np.asarray(
        fleet_forward_gather(spec, cast, indices, Xlp, precision=precision)
    )

    agreements = []
    for i, name in enumerate(names):
        model = models[i]
        y = probes[i]
        threshold = getattr(model, "aggregate_threshold_", None)
        scaler = getattr(model, "scaler", None)
        a, b = recon_f32[i], recon_lp[i]
        if y.shape[-1] != a.shape[-1]:
            member = recon_agreement(a, b, rtol=config.rtol, atol=config.atol)
        else:
            member = verdict_agreement(
                a, b, y, scaler=scaler,
                threshold=float(threshold) if threshold else None,
                rtol=config.rtol, atol=config.atol,
            )
        if not np.all(np.isfinite(b)):
            member["agreement"] = 0.0
            member["detail"] = "non-finite reduced-precision output"
        report["members"][name] = member
        agreements.append(member["agreement"])

    report["agreement_min"] = min(agreements) if agreements else 1.0
    report["agreement_threshold"] = config.agreement
    report["probe_rows"] = rows
    if report["agreement_min"] < config.agreement:
        report["passed"] = False
        worst = min(report["members"], key=lambda n: report["members"][n]["agreement"])
        report["detail"] = (
            f"{precision} verdicts diverge from f32: member {worst} agrees "
            f"on {report['members'][worst]['agreement']:.2%} of the probe "
            f"window (gate {config.agreement:.2%})"
        )
    return report


# -- the governor: gate-then-serve, degrade on failure ------------------------


class PrecisionGovernor:
    """
    The serve engine's precision arbiter: the first time a (revision
    fleet, spec, precision) combination is requested it runs
    :func:`evaluate_parity`, caches the verdict on the fleet's COW
    state map, and from then on answers with one dict probe. A FAILED
    gate degrades that bucket to f32 — requests keep flowing, nothing
    5xxes — and the degrade is visible in the engine counters, the
    batch spans and the gate report on the fleet.
    """

    def __init__(self):
        self._lock = threading.Lock()  # guards the per-key lock registry
        #: (id(fleet), spec, precision) -> evaluation lock: gating one
        #: bucket (probe compiles + scoring, seconds on first touch)
        #: must not convoy every OTHER fleet/spec's first request behind
        #: one process-wide lock
        self._evaluating: Dict[Tuple, threading.Lock] = {}

    def effective_precision(
        self, fleet: Any, spec: Any, desired: str, recorder: Any = None
    ) -> str:
        desired = normalize(desired)
        if desired == F32:
            return F32
        if not gate_enabled():
            return desired
        state = fleet.precision_state(spec, desired)
        if state is None:
            key = (id(fleet), spec, desired)
            with self._lock:
                key_lock = self._evaluating.setdefault(key, threading.Lock())
            with key_lock:  # one evaluation per bucket, however many threads
                state = fleet.precision_state(spec, desired)
                if state is None:
                    state = self._evaluate(fleet, spec, desired, recorder)
            with self._lock:
                self._evaluating.pop(key, None)
        return desired if state.get("passed") else F32

    def _evaluate(self, fleet, spec, precision: str, recorder) -> Dict[str, Any]:
        try:
            report = evaluate_parity(fleet, spec, precision)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as exc:  # noqa: BLE001 - an unevaluable gate is a
            # failed gate (degrade to f32), never a crashed request
            report = {
                "precision": normalize(precision),
                "passed": False,
                "detail": f"parity evaluation crashed: {exc!r}",
            }
        # stamp the verdict with the membership epoch it was EVALUATED
        # at (a member loading mid-evaluation bumps the epoch and the
        # verdict reads as absent → next request re-gates)
        fleet.set_precision_state(
            spec, precision, report, epoch=report.get("bucket_epoch")
        )
        if report.get("passed"):
            logger.info(
                "precision gate PASSED: %s serving at %s "
                "(verdict agreement >= %.2f%% on %s members)",
                fleet.collection_dir,
                report["precision"],
                100.0 * report.get("agreement_min", 1.0),
                len(report.get("members", {})),
            )
        else:
            logger.warning(
                "precision gate FAILED for %s at %s — degrading to f32: %s",
                fleet.collection_dir,
                report["precision"],
                report.get("detail", "verdict divergence"),
            )
        if recorder is not None:
            try:
                recorder.event(
                    "precision_gate",
                    collection_dir=fleet.collection_dir,
                    precision=report["precision"],
                    passed=bool(report.get("passed")),
                    agreement_min=report.get("agreement_min"),
                    detail=report.get("detail", ""),
                )
            except Exception:  # noqa: BLE001 - telemetry is advisory
                pass
        return report
