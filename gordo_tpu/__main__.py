"""``python -m gordo_tpu`` — the CLI entry point."""

from gordo_tpu.cli import gordo_tpu_cli

if __name__ == "__main__":
    gordo_tpu_cli()
