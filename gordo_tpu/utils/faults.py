"""
Deterministic fault injection for the fleet build path.

The reference gets fault tolerance for free from Argo/Kubernetes (one
pod per machine, ``failFast:false``, per-pod ``retryStrategy``); the
chip-fan-out build collapses thousands of machines into one process, so
its crash-safety paths — atomic artifact renames, the build journal,
bucket bisection, data-fetch retry — need an in-process way to be
*exercised on demand*. This registry provides that: production code
calls :func:`fault_point` at named sites, which is a no-op unless a
matching :class:`FaultRule` is active; tests (and the bench) install
rules via the :func:`inject` context manager or the ``GORDO_TPU_FAULTS``
environment variable and get byte-reproducible failures on CPU.

Sites instrumented today:

- ``data_fetch`` — before each machine's ``dataset.get_data()`` attempt
  (key: machine name); exercises the retry/backoff path.
- ``device_program`` — before each fleet bucket's device program runs,
  once per member (key: member name); exercises bucket bisection and
  the sequential-builder degradation.
- ``dump_artifact`` — inside the atomic artifact dump, after the files
  are written into the ``.<name>.tmp-*`` staging dir but before the
  rename (key: artifact dir name); simulates a crash mid-write.
- ``process_kill_after_n_machines`` — after each machine's artifact
  lands and is journaled (key: machine name); with ``after=N`` the
  first N machines complete and the next one dies — the in-process
  analog of a host preemption at machine N of the fleet.
- ``drift_eval`` — before each machine's drift verdict is computed
  (key: machine name); exercises the lifecycle loop's contract that a
  broken drift evaluation never takes scoring (or the loop) down.
- ``canary_build`` — before the lifecycle loop launches the partial
  rebuild of the stale members (key: canary revision); a crash here
  must leave serving on the last-good revision with the canary
  resumable from its journal.
- ``promote_swap`` — after the canary passed its gates, immediately
  before the hot-swap installs it as the served revision (key: canary
  revision); a crash here must leave serving on the last-good revision
  and the promotion re-runnable.
- ``rollback`` — before a failed canary's rollback actions run (key:
  canary revision); a crash here must leave the rollback resumable so
  a restart still converges on the last-good revision.
- ``serve_device_program`` — before a fused SERVING batch program runs,
  once per coalesced member (key: ``<spec>:<precision>:<member>``, e.g.
  ``FeedForwardSpec:f32:machine-3`` — glob any axis: ``*:bf16:*`` faults
  every bf16 program, ``*:*:poison-*`` one member at every precision);
  exercises the serve engine's batch bisection, precision degradation
  and the per-member circuit breaker. The default exception's message
  carries ``RESOURCE_EXHAUSTED`` (OOM-shaped — drives rung demotion);
  use ``exc=InjectedDeviceError`` for a poison-member (non-OOM) fault.
- ``serve_member_poison`` — after a fused serving program succeeds, once
  per coalesced member (same key form); the engine converts a firing
  into NaN output rows for that member, exercising non-finite-output
  detection (a NaN-poisoned member must fail alone, not crash or
  corrupt its batch).
- ``serve_scatter`` — inside the engine's scatter loop, once per
  resolved member (same key form); a scatter failure for one rider must
  never leak into the other riders' futures.
- ``stream_ingest`` — before a machine's decoded rows land in its
  streaming ring buffer (key: ``<stream-id>:<member>``); one poisoned
  machine entry must error alone in the ingest ack while the other
  machines' rows keep landing (stream containment mirrors the fleet
  route's per-machine isolation).
- ``stream_score`` — before a machine's watermark window is handed to
  the fused scorer (key: ``<stream-id>:<member>``); repeated firings
  drive the member's serving circuit breaker open mid-stream, so the
  drill can watch the ``quarantined`` control event, the innocent
  members' uninterrupted scoring, and half-open recovery on the live
  stream.
- ``stream_emit`` — before an event is appended to a session's outbox
  ring (key: ``<stream-id>:<event-kind>``); an emit failure is counted
  and dropped without ever stalling ingest or scoring.

Rules fire deterministically: each rule counts the calls matching its
(site, key-glob) and fires on calls ``after < i <= after + times``.

>>> with inject(FaultRule("data_fetch", match="m-*", times=1)):
...     try:
...         fault_point("data_fetch", "m-1")
...     except FaultInjected:
...         print("fired")
...     fault_point("data_fetch", "m-1")  # times exhausted: passes
fired

Env form (``;``-separated rules, fields ``site[:key-glob][:opt...]``)::

    GORDO_TPU_FAULTS="device_program:poison-*:times=inf"
    GORDO_TPU_FAULTS="process_kill_after_n_machines:*:after=500:kill"
    GORDO_TPU_FAULTS="serve_device_program:*poison-1:exc=InjectedDeviceError"

The env glob itself cannot contain ``:`` (it is the field separator);
for the serving sites' composite ``<spec>:<precision>:<member>`` keys
use a colon-free glob — ``*`` matches across ``:`` in fnmatch, so
``*poison-1`` targets one member at every spec/precision (tests and the
bench target single axes with :class:`FaultRule` via :func:`inject`).

``kill`` makes the rule ``os._exit(137)`` instead of raising — a true
mid-build death for end-to-end resume drills; tests prefer the default
raising form (``process_kill_after_n_machines`` raises ``SystemExit``,
which the build never swallows into per-machine errors).
"""

import fnmatch
import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

logger = logging.getLogger(__name__)

ENV_VAR = "GORDO_TPU_FAULTS"

SITES = (
    "data_fetch",
    "device_program",
    "dump_artifact",
    "process_kill_after_n_machines",
    "drift_eval",
    "canary_build",
    "promote_swap",
    "rollback",
    "serve_device_program",
    "serve_member_poison",
    "serve_scatter",
    "stream_ingest",
    "stream_score",
    "stream_emit",
)


class FaultInjected(RuntimeError):
    """An injected fault (default exception for most sites)."""


class InjectedDeviceError(FaultInjected):
    """Injected stand-in for a device-program ``XlaRuntimeError`` — the
    message carries ``RESOURCE_EXHAUSTED`` so every detection path (type
    or message) classifies it as a device error."""


#: exception names accepted by the env form's ``exc=`` option
_EXC_TYPES = {
    "FaultInjected": FaultInjected,
    "InjectedDeviceError": InjectedDeviceError,
    "RuntimeError": RuntimeError,
    "OSError": OSError,
    "MemoryError": MemoryError,
    "SystemExit": SystemExit,
    "KeyboardInterrupt": KeyboardInterrupt,
}


@dataclass
class FaultRule:
    """One deterministic failure: fire on matching calls
    ``after < i <= after + times`` of ``site`` whose key globs ``match``."""

    site: str
    match: str = "*"
    times: Optional[int] = 1  # None = every matching call past ``after``
    after: int = 0
    exc: Optional[Any] = None  # exception class/instance/factory(site, key)
    kill: bool = False  # os._exit(137) instead of raising
    seen: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def make_exc(self, key: str) -> BaseException:
        exc = self.exc
        if exc is not None:
            if isinstance(exc, BaseException):
                return exc
            return exc(f"injected fault at {self.site}:{key}")
        if self.site in ("device_program", "serve_device_program"):
            return InjectedDeviceError(
                f"RESOURCE_EXHAUSTED: injected device fault ({key})"
            )
        if self.site == "process_kill_after_n_machines":
            return SystemExit(137)
        return FaultInjected(f"injected fault at {self.site}:{key}")


_lock = threading.Lock()
_installed: List[FaultRule] = []
#: (raw env string, parsed rules) — parsed once per distinct value so rule
#: counters persist across fault_point calls within a process
_env_cache: Tuple[Optional[str], List[FaultRule]] = (None, [])


def parse_rules(spec: str) -> List[FaultRule]:
    """Parse the ``GORDO_TPU_FAULTS`` string form.

    >>> [r.after for r in parse_rules("dump_artifact:*:after=2:exc=SystemExit")]
    [2]
    """
    rules = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        site = parts[0]
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r} (known: {SITES})")
        rule = FaultRule(site=site)
        opts = parts[1:]
        if opts and "=" not in opts[0] and opts[0] != "kill":
            rule.match = opts[0]
            opts = opts[1:]
        for opt in opts:
            if opt == "kill":
                rule.kill = True
            elif opt.startswith("times="):
                value = opt.split("=", 1)[1]
                rule.times = None if value in ("inf", "all") else int(value)
            elif opt.startswith("after="):
                rule.after = int(opt.split("=", 1)[1])
            elif opt.startswith("exc="):
                name = opt.split("=", 1)[1]
                if name not in _EXC_TYPES:
                    raise ValueError(
                        f"unknown exc {name!r} (known: {sorted(_EXC_TYPES)})"
                    )
                rule.exc = _EXC_TYPES[name]
            else:
                raise ValueError(f"unknown fault option {opt!r}")
        rules.append(rule)
    return rules


def _env_rules() -> List[FaultRule]:
    global _env_cache
    from .env import env_raw

    raw = env_raw(ENV_VAR)
    if not raw:
        if _env_cache[0] is not None:
            _env_cache = (None, [])
        return []
    if raw != _env_cache[0]:
        _env_cache = (raw, parse_rules(raw))
    return _env_cache[1]


def install(*rules: FaultRule) -> None:
    """Activate rules for the rest of the process (tests prefer
    :func:`inject`, which scopes them)."""
    with _lock:
        _installed.extend(rules)


def clear() -> None:
    """Deactivate every installed rule and forget the env cache."""
    global _env_cache
    with _lock:
        _installed.clear()
        _env_cache = (None, [])


class inject:
    """Context manager scoping a set of :class:`FaultRule`\\ s.

    Re-entrant and nestable; rules installed by an inner scope are
    removed on exit without disturbing outer scopes.
    """

    def __init__(self, *rules: FaultRule):
        self.rules = rules

    def __enter__(self) -> "inject":
        install(*self.rules)
        return self

    def __exit__(self, *_exc_info) -> None:
        with _lock:
            for rule in self.rules:
                # identity, not equality: dataclass __eq__ ignores the
                # counters, so list.remove(rule) could pop an EQUAL rule
                # an outer scope installed and leave this one active
                for i, installed in enumerate(_installed):
                    if installed is rule:
                        del _installed[i]
                        break


def fault_point(site: str, key: str = "") -> None:
    """Fire any active rule matching ``(site, key)``; no-op otherwise.

    Instrumentation sites call this with a stable per-unit key (machine
    or member name) so rules can target one poisonous unit out of a
    fleet. Threads share rule counters under a lock, so ``after``/
    ``times`` stay exact even from the dump/data thread pools.
    """
    with _lock:
        rules = _installed + _env_rules()
        to_fire = None
        for rule in rules:
            if rule.site != site or not fnmatch.fnmatchcase(key, rule.match):
                continue
            rule.seen += 1
            i = rule.seen
            if i <= rule.after:
                continue
            if rule.times is not None and i > rule.after + rule.times:
                continue
            rule.fired += 1
            to_fire = rule
            break
    if to_fire is None:
        return
    logger.warning(
        "Fault injection: firing %s at %s:%s (match %r, fired %d)",
        "os._exit(137)" if to_fire.kill else "exception",
        site,
        key,
        to_fire.match,
        to_fire.fired,
    )
    if to_fire.kill:
        os._exit(137)
    raise to_fire.make_exc(key)
