"""
Opt-in device profiling (SURVEY.md §5 "Tracing / profiling": the reference
records only coarse wall-clock durations — query_duration_sec and
model_training_duration_sec in build metadata, the Server-Timing response
header. Those fields all exist here too; this module adds the TPU-native
layer the reference had no analog for: XLA device traces).

Set ``GORDO_TPU_PROFILE_DIR`` and every labeled region writes a
TensorBoard-loadable trace (``jax.profiler``) under
``$GORDO_TPU_PROFILE_DIR/<label>/``; unset, the context manager is free.

This is the heavyweight, opt-in layer: raw XLA device traces for deep
kernel work. The always-on, aggregated layer — phase spans, compile/run
attribution, the live build-status surface — is ``gordo_tpu.telemetry``
(docs/observability.md); the two compose (a ``maybe_trace`` region can
enclose spans and vice versa).
"""
# gt-lint: file-disable=jax-stdlib-only -- this module IS the jax.profiler
# wrapper; the import stays lazy so the utils package imports clean on
# hosts without jax

import contextlib
import logging
import os

from .env import env_str

logger = logging.getLogger(__name__)

PROFILE_DIR_ENV = "GORDO_TPU_PROFILE_DIR"


@contextlib.contextmanager
def maybe_trace(label: str):
    """Trace the enclosed region to ``$GORDO_TPU_PROFILE_DIR/<label>``
    when profiling is enabled; no-op otherwise."""
    trace_dir = env_str(PROFILE_DIR_ENV, None)
    if not trace_dir:
        yield
        return
    import jax

    path = os.path.join(trace_dir, label)
    logger.info("Profiling %s -> %s", label, path)
    with jax.profiler.trace(path):
        yield


def annotate(label: str):
    """A ``jax.profiler.TraceAnnotation`` (shows up as a named region in the
    trace viewer) when profiling is on; a null context otherwise."""
    if not env_str(PROFILE_DIR_ENV, None):
        return contextlib.nullcontext()
    import jax

    return jax.profiler.TraceAnnotation(label)
