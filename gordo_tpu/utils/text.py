"""ASCII scrubbing used by the exception reporter (reference: gordo/util/text.py)."""


def replace_all_non_ascii_chars(text: str, replacement: str = "?") -> str:
    """
    Replace every non-ASCII character in ``text`` with ``replacement``.

    The k8s termination-message path only reliably stores ASCII, so the CLI's
    exception reports are scrubbed before being written.

    >>> replace_all_non_ascii_chars("øre 100%", "?")
    '?re 100%'
    """
    return "".join(ch if ord(ch) < 128 else replacement for ch in text)
