"""
Minimal ``dataclasses_json.dataclass_json`` stand-in.

The metadata tree (machine/metadata.py) only uses the decorator's
``to_dict``/``from_dict`` pair; this fallback implements exactly that
subset — a recursive encode of dataclass fields and a type-hint-driven
decode that rebuilds nested dataclasses and ignores unknown keys (the
same tolerance the real library shows for artifacts written by newer
schema versions). Used only when ``dataclasses_json`` is not installed.

>>> from dataclasses import dataclass, field
>>> @dataclass_json
... @dataclass
... class Inner:
...     n: int = 0
>>> @dataclass_json
... @dataclass
... class Outer:
...     inner: Inner = field(default_factory=Inner)
>>> Outer.from_dict({"inner": {"n": 3}, "unknown": 1}).inner.n
3
>>> Outer(inner=Inner(n=2)).to_dict()
{'inner': {'n': 2}}
"""

import dataclasses
import typing


def _encode(value):
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {
            f.name: _encode(getattr(value, f.name))
            for f in dataclasses.fields(value)
        }
    if isinstance(value, dict):
        return {k: _encode(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    return value


def _unwrap_optional(hint):
    """``Optional[T]`` → ``T`` (the only generic the metadata tree uses
    around dataclass fields)."""
    if typing.get_origin(hint) is typing.Union:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return hint


def _decode(cls, data):
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        value = data[f.name]
        ftype = _unwrap_optional(hints.get(f.name))
        if dataclasses.is_dataclass(ftype) and isinstance(value, dict):
            from_dict = getattr(ftype, "from_dict", None)
            value = from_dict(value) if from_dict else _decode(ftype, value)
        kwargs[f.name] = value
    return cls(**kwargs)


def dataclass_json(cls):
    """Attach ``to_dict``/``from_dict``, clobbering any body-defined ones
    — mirroring the real decorator's (documented-in-metadata.py)
    unconditional assignment, so the post-decoration override pattern
    behaves identically under both implementations."""

    def to_dict(self, **_kwargs):
        return _encode(self)

    def from_dict(klass, data, **_kwargs):
        return _decode(klass, data)

    cls.to_dict = to_dict
    cls.from_dict = classmethod(from_dict)
    return cls
