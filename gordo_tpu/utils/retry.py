"""
Exponential-backoff retry for the fleet's host-side data plane.

The reference DAG retried a failed builder pod wholesale (per-pod
``retryStrategy`` with backoff in argo-workflow.yml.template); in the
chip-fan-out build the only genuinely flaky host-side phase is the
per-machine data fetch, so retry lives there as a plain function wrap —
bounded attempts, exponential backoff, and an optional per-call
deadline bounding how long the retry ladder keeps going. The deadline
cannot interrupt a call already in flight (no safe cross-thread cancel
in Python): a provider that can block forever must carry its own socket
timeout, which every bundled provider does.

>>> calls = []
>>> def flaky():
...     calls.append(1)
...     if len(calls) < 3:
...         raise OSError("transient")
...     return "ok"
>>> retry_call(flaky, attempts=3, backoff=0)
'ok'
>>> len(calls)
3
"""

import time
from typing import Any, Callable, Optional, Tuple, Type


def retry_call(
    fn: Callable[[], Any],
    attempts: int = 3,
    backoff: float = 0.5,
    factor: float = 2.0,
    max_backoff: float = 30.0,
    deadline: Optional[float] = None,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    no_retry: Tuple[Type[BaseException], ...] = (),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
) -> Any:
    """
    Call ``fn`` up to ``attempts`` times; sleep
    ``min(backoff * factor**(attempt-1), max_backoff)`` between tries.

    ``no_retry`` exceptions re-raise immediately (deterministic config
    errors — retrying an InsufficientDataError just burns the backoff).
    ``deadline`` caps total elapsed-plus-next-sleep seconds; when the
    next sleep would cross it, the last error re-raises instead. It is
    checked BETWEEN attempts only — it does not (cannot) interrupt an
    ``fn()`` call that blocks; timeouts inside ``fn`` are its own job.
    ``on_retry(attempt, exc)`` fires before each sleep (retry counters).
    ``KeyboardInterrupt``/``SystemExit`` always propagate.
    """
    start = time.monotonic()
    attempt = 1
    while True:
        try:
            return fn()
        except no_retry:
            raise
        except retry_on as exc:
            if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                raise
            if attempt >= attempts:
                raise
            delay = min(backoff * factor ** (attempt - 1), max_backoff)
            if (
                deadline is not None
                and time.monotonic() - start + delay > deadline
            ):
                raise
            if on_retry is not None:
                on_retry(attempt, exc)
            if delay > 0:
                time.sleep(delay)
            attempt += 1
