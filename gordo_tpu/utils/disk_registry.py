"""
File-per-key registry used as the model build cache index.

Reference parity: gordo/util/disk_registry.py — a minimal KV store where each
key is a file in a directory. Concurrent writes to the *same* key are not
atomic (documented there at lines 9-14); concurrent writes to different keys
are fine, which is all the builder needs.
"""

import logging
import os
import re
from pathlib import Path
from typing import Optional, Union

logger = logging.getLogger(__name__)

_VALID_KEY_RE = re.compile(r"^[A-Za-z0-9._\-]+$")


def _key_path(registry_dir: Union[os.PathLike, str], key: str) -> Path:
    if not _VALID_KEY_RE.match(key):
        raise ValueError(f"Invalid registry key: {key!r}")
    return Path(registry_dir) / key


def write_key(registry_dir: Union[os.PathLike, str], key: str, val: str):
    """Write ``val`` under ``key``, creating the registry dir if needed."""
    path = _key_path(registry_dir, key)
    path.parent.mkdir(parents=True, exist_ok=True)
    logger.debug("Registry write %s -> %s", key, val)
    path.write_text(str(val))


def get_value(registry_dir: Union[os.PathLike, str], key: str) -> Optional[str]:
    """Return the value stored under ``key``, or None if absent/unreadable."""
    path = _key_path(registry_dir, key)
    try:
        return path.read_text()
    except (FileNotFoundError, NotADirectoryError):
        return None
    except OSError:
        logger.warning("Failed reading registry key %s", key, exc_info=True)
        return None


def delete_value(registry_dir: Union[os.PathLike, str], key: str) -> bool:
    """Delete ``key``; returns True if it existed."""
    path = _key_path(registry_dir, key)
    try:
        path.unlink()
        return True
    except FileNotFoundError:
        return False
