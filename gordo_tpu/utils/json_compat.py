"""
``simplejson`` pass-through with a stdlib fallback.

The artifact writers (serializer, server JSON responses) want
``simplejson``'s ``ignore_nan=True`` — NaN/Inf floats become ``null``
instead of the invalid-JSON ``NaN`` literal the stdlib emits. Containers
without ``simplejson`` (it is a pyproject dependency, but the baked
image may predate it) fall back to ``json`` plus an explicit
NaN-sanitizing walk, so artifacts stay valid JSON either way.

>>> loads(dumps({"a": float("nan"), "b": 1.5}, ignore_nan=True))
{'a': None, 'b': 1.5}
"""
# gt-lint: file-disable=jax-stdlib-only -- this module IS the simplejson
# shim: the guarded import is the fallback mechanism, not a dependency

import math

try:  # pragma: no cover - exercised only where simplejson is installed
    from simplejson import dump, dumps, load, loads  # noqa: F401

    HAVE_SIMPLEJSON = True
except ImportError:
    import json as _json

    HAVE_SIMPLEJSON = False

    def _sanitize(value):
        """Replace non-finite floats with None, recursively (the
        ``ignore_nan`` contract). numpy float scalars subclass ``float``,
        so fleet metadata's np.float64 NaNs are covered too."""
        if isinstance(value, float):
            return value if math.isfinite(value) else None
        if isinstance(value, dict):
            return {k: _sanitize(v) for k, v in value.items()}
        if isinstance(value, (list, tuple)):
            return [_sanitize(v) for v in value]
        return value

    def dumps(obj, default=None, ignore_nan=False, **kwargs):
        if ignore_nan:
            obj = _sanitize(obj)
        return _json.dumps(obj, default=default, **kwargs)

    def dump(obj, fp, default=None, ignore_nan=False, **kwargs):
        fp.write(dumps(obj, default=default, ignore_nan=ignore_nan, **kwargs))

    def load(fp, **kwargs):
        return _json.load(fp, **kwargs)

    def loads(s, **kwargs):
        return _json.loads(s, **kwargs)
