"""
Docker-image-tag grammar used by the workflow generator to pick image sets.

Reference parity: gordo/util/version.py:87-130 — tags are one of: a release
(``1.2.3`` with optional suffix), a special tag (``latest`` / ``stable``), a
PR tag (``pr-123``), or a bare git SHA.
"""

import re
from abc import ABC, abstractmethod
from dataclasses import dataclass
from enum import Enum
from typing import Optional


class Special(Enum):
    LATEST = "latest"
    STABLE = "stable"


class Version(ABC):
    @abstractmethod
    def get_version(self) -> str:
        ...


@dataclass(frozen=True)
class GordoRelease(Version):
    major: int
    minor: int
    patch: int
    suffix: Optional[str] = None

    def get_version(self) -> str:
        version = f"{self.major}.{self.minor}.{self.patch}"
        return version + self.suffix if self.suffix else version

    def without_patch(self) -> bool:
        return False

    def only_major(self) -> bool:
        return False

    def only_major_minor(self) -> bool:
        return False


@dataclass(frozen=True)
class GordoSpecial(Version):
    special: Special

    def get_version(self) -> str:
        return self.special.value


@dataclass(frozen=True)
class GordoPR(Version):
    number: int

    def get_version(self) -> str:
        return f"pr-{self.number}"


@dataclass(frozen=True)
class GordoSHA(Version):
    sha: str

    def get_version(self) -> str:
        return self.sha


_RELEASE_RE = re.compile(
    r"^(?P<major>\d+)\.(?P<minor>\d+)\.(?P<patch>\d+)(?P<suffix>[.\-+][0-9A-Za-z.\-+]+)?$"
)
_PR_RE = re.compile(r"^pr-(?P<number>\d+)$")
_SHA_RE = re.compile(r"^[0-9a-f]{7,40}$")


def parse_version(tag: str) -> Version:
    """
    Parse a docker tag into one of the ``Version`` variants.

    >>> parse_version("1.2.3")
    GordoRelease(major=1, minor=2, patch=3, suffix=None)
    >>> parse_version("latest")
    GordoSpecial(special=<Special.LATEST: 'latest'>)
    >>> parse_version("pr-42")
    GordoPR(number=42)
    """
    for special in Special:
        if tag == special.value:
            return GordoSpecial(special)
    match = _RELEASE_RE.match(tag)
    if match:
        return GordoRelease(
            int(match.group("major")),
            int(match.group("minor")),
            int(match.group("patch")),
            match.group("suffix"),
        )
    match = _PR_RE.match(tag)
    if match:
        return GordoPR(int(match.group("number")))
    if _SHA_RE.match(tag):
        return GordoSHA(tag)
    raise ValueError(f"Unparseable docker tag: {tag!r}")
