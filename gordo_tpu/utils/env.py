"""
Environment-knob parsing and the knob REGISTRY: one warn-and-fall-back
implementation for every ``GORDO_TPU_*`` knob instead of a per-call-site
copy, plus the single declared catalog of every knob the codebase reads.

Every ``GORDO_TPU_*`` environment read in the package must go through
one of the typed accessors here (``env_int``/``env_float``/``env_bool``/
``env_str``/``env_raw``), and every knob name must be declared in
:data:`KNOBS` — both invariants are enforced statically by the
``env-registry`` rule of ``gordo-tpu lint`` (see
``docs/static-analysis.md``), and the reference table in
``docs/configuration.md`` is generated from this registry
(``python docs/generate_env_docs.py``).

Malformed values never raise: they log ONE warning per distinct
(name, value) pair and fall back to the call-site default.

>>> import os
>>> os.environ["GORDO_TPU_DOCTEST_KNOB"] = "not-a-number"
>>> env_int("GORDO_TPU_DOCTEST_KNOB", 7)
7
>>> os.environ["GORDO_TPU_DOCTEST_KNOB"] = "maybe"
>>> env_bool("GORDO_TPU_DOCTEST_KNOB", False)
False
>>> del os.environ["GORDO_TPU_DOCTEST_KNOB"]
"""

import logging
import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)

#: truthy / falsy spellings accepted by :func:`env_bool`
_TRUE_STRINGS = frozenset(("1", "true", "on", "yes"))
_FALSE_STRINGS = frozenset(("0", "false", "off", "no"))

#: (name, raw) pairs already warned about — malformed knobs warn once,
#: not once per read (hot paths re-read knobs per request/batch)
_warned: set = set()


def _warn_once(name: str, raw: str, default) -> None:
    key = (name, raw)
    if key not in _warned:
        _warned.add(key)
        logger.warning("Invalid %s=%r; using %r", name, raw, default)


def env_int(name: str, default: int) -> int:
    """``int(os.environ[name])`` with warn-once fallback to ``default``."""
    raw = os.environ.get(name)
    if raw:
        try:
            return int(raw)
        except ValueError:
            _warn_once(name, raw, default)
    return default


def env_float(name: str, default: Optional[float]) -> Optional[float]:
    """``float(os.environ[name])`` with warn-once fallback to ``default``."""
    raw = os.environ.get(name)
    if raw:
        try:
            return float(raw)
        except ValueError:
            _warn_once(name, raw, default)
    return default


def env_bool(name: str, default: bool) -> bool:
    """Boolean knob: ``1/true/on/yes`` → True, ``0/false/off/no`` →
    False, unset or empty (a blanked-out manifest var) → ``default``;
    anything else warns once and falls back."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    value = raw.strip().lower()
    if not value:
        return default
    if value in _TRUE_STRINGS:
        return True
    if value in _FALSE_STRINGS:
        return False
    _warn_once(name, raw, default)
    return default


def env_str(name: str, default: Optional[str]) -> Optional[str]:
    """String knob: the raw value, with unset/empty falling back to
    ``default`` (paths, strategy names, comma-lists)."""
    raw = os.environ.get(name)
    return raw if raw else default


def env_raw(name: str) -> Optional[str]:
    """The unparsed value (or None) — for call sites that cache a parsed
    knob keyed on the raw string and only re-parse when it changes."""
    return os.environ.get(name)


@dataclass(frozen=True)
class Knob:
    """One declared ``GORDO_TPU_*`` environment knob.

    ``type`` is the accessor family (``int``/``float``/``bool``/``str``),
    ``default`` the call-site fallback, ``doc`` the one-line reference
    description (the docs table row), and ``section`` the grouping header
    in ``docs/configuration.md``.
    """

    name: str
    type: str
    default: object
    doc: str
    section: str = "General"


def _knobs(*knobs: Knob) -> Dict[str, Knob]:
    table: Dict[str, Knob] = {}
    for knob in knobs:
        if knob.name in table:
            raise ValueError(f"duplicate knob declaration: {knob.name}")
        table[knob.name] = knob
    return table


#: The registry: every ``GORDO_TPU_*`` knob the package reads, in docs
#: order. Adding a read without declaring it here fails `gordo-tpu lint`
#: (env-registry rule) and the docs drift test.
KNOBS: Dict[str, Knob] = _knobs(
    # -- Training / device performance ------------------------------------
    Knob(
        "GORDO_TPU_LSTM_UNROLL", "int", 4,
        "Recurrence scan unroll factor for LSTM models.",
        "Performance",
    ),
    Knob(
        "GORDO_TPU_LSTM_SEGMENTED", "int", 0,
        "Opt-in segmented (stateful-scan) LSTM training: segments per "
        "update; must divide `batch_size`, requires `shuffle: false` "
        "(see `docs/architecture.md`).",
        "Performance",
    ),
    Knob(
        "GORDO_TPU_CV_CHUNK_BYTES", "int", 1 << 30,
        "Fleet CV super-bucket memory budget in bytes.",
        "Performance",
    ),
    Knob(
        "GORDO_TPU_PACKING", "str", None,
        "Block-diagonal packing factor for fleet programs, or `auto`.",
        "Performance",
    ),
    Knob(
        "GORDO_TPU_COMPILE_CACHE", "str", None,
        "Directory for JAX's persistent compilation cache — repeated "
        "`build-fleet` runs and server restarts reload compiled programs "
        "from disk instead of recompiling (applied at every mesh/backend "
        "init; the min-compile-time threshold is zeroed so small fleet "
        "programs are cached too).",
        "Performance",
    ),
    Knob(
        "GORDO_TPU_DISABLE_PALLAS", "bool", False,
        "Force the plain-XLA fleet forward program even where the Pallas "
        "kernel is available.",
        "Performance",
    ),
    Knob(
        "GORDO_TPU_RING_PREDICT_ROWS", "int", 65_536,
        "Row threshold past which windowed models shard the prediction "
        "time axis over the device mesh (`parallel/sequence.py`).",
        "Performance",
    ),
    Knob(
        "GORDO_TPU_PLATFORM", "str", None,
        "Device platform override for the CLI (`gordo-tpu --platform`; "
        "read by click, not `os.environ`).",
        "Performance",
    ),
    # -- Bucket planner ----------------------------------------------------
    Knob(
        "GORDO_TPU_PLAN_STRATEGY", "str", "naive",
        "Bucket-construction strategy: `naive` (historical exact-key "
        "grouping, default) or `packed` (cost-model bin packing).",
        "Planner",
    ),
    Knob(
        "GORDO_TPU_PLAN_PAD_RATIO", "float", 1.25,
        "Geometric growth ratio for the packed strategy's dense sample "
        "axis.",
        "Planner",
    ),
    Knob(
        "GORDO_TPU_SERIES_PAD_RATIO", "float", 1.25,
        "Geometric growth ratio for the windowed (LSTM) series axis — "
        "applies to BOTH strategies; replaces the old pow2 time-axis "
        "padding.",
        "Planner",
    ),
    Knob(
        "GORDO_TPU_PLAN_COMPILE_BUDGET", "int", 0,
        "Hard cap on planned program count for `packed` (0 = stop rung "
        "merging at the cost model's compile-vs-padding break-even).",
        "Planner",
    ),
    Knob(
        "GORDO_TPU_PLAN_HBM_CAP_BYTES", "int", 4 << 30,
        "Per-bucket predicted resident-bytes cap for `packed` — buckets "
        "split *before* they would OOM.",
        "Planner",
    ),
    # -- Build robustness --------------------------------------------------
    Knob(
        "GORDO_TPU_DATA_RETRIES", "int", 2,
        "Extra data-fetch attempts per machine; deterministic config "
        "errors never retry.",
        "Robustness",
    ),
    Knob(
        "GORDO_TPU_DATA_BACKOFF", "float", 0.5,
        "Base backoff seconds between fetch attempts, doubling per "
        "attempt.",
        "Robustness",
    ),
    Knob(
        "GORDO_TPU_DATA_DEADLINE", "float", None,
        "Optional per-machine fetch deadline in seconds — retries stop "
        "once the next backoff would cross it.",
        "Robustness",
    ),
    Knob(
        "GORDO_TPU_FAULTS", "str", None,
        "Deterministic fault injection for drills/tests, e.g. "
        "`device_program:poison-*:times=inf` (sites: `data_fetch`, "
        "`device_program`, `dump_artifact`, `drift_eval`, `canary_build`, "
        "`promote_swap`, `rollback`, `process_kill_after_n_machines`, "
        "and the serving sites `serve_device_program`, "
        "`serve_member_poison`, `serve_scatter` keyed "
        "`<spec>:<precision>:<member>`).",
        "Robustness",
    ),
    # -- Telemetry ---------------------------------------------------------
    Knob(
        "GORDO_TPU_TELEMETRY", "bool", True,
        "Telemetry master switch: spans, traces, build-status heartbeat.",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_TELEMETRY_DIR", "str", None,
        "Span-sink directory (`build_trace.jsonl` / `serve_trace.jsonl`); "
        "builds default to the build output dir, serving has no default.",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_TELEMETRY_HEARTBEAT", "float", 0.5,
        "`build_status.json` heartbeat throttle seconds (0 = write "
        "exactly per completion; used by the fault drills).",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_TELEMETRY_MAX_BYTES", "int", 256 * 1024 * 1024,
        "Trace-sink rotation threshold per generation.",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_TELEMETRY_KEEP", "int", 3,
        "Rotated trace generations kept per sink (older are deleted).",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_TRACE_SAMPLE_RATE", "float", 0.05,
        "Head-sampling rate for exported request traces (ids/logs/RED "
        "metrics see all traffic; an upstream sampled flag or "
        "`?profile=1` always exports).",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_PROFILE_SAMPLE_RATE", "float", 0.0,
        "Fraction of requests host-profiled by the sampling profiler "
        "(`?profile=1` forces one request).",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_PROFILE_INTERVAL_MS", "float", 5.0,
        "Sampling profiler frame-capture interval.",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_PROFILE_DIR", "str", None,
        "Directory for `jax.profiler` device traces "
        "(`utils/profiling.py`; `?profile=device` on the server).",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_FLEET_HEALTH", "bool", True,
        "Per-member fleet health ledger master switch "
        "(`fleet_health.json` snapshots + the `fleet-status` surface; "
        "also requires `GORDO_TPU_TELEMETRY`).",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_HEALTH_HEARTBEAT", "float", 2.0,
        "Seconds between throttled `fleet_health.json` snapshot writes "
        "(state transitions — drift verdicts, quarantines — always "
        "write).",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_HEALTH_WINDOW", "int", 100_000,
        "Rows after which a machine's rolling serving-residual window "
        "decays (halves), so the ledger's residual mean tracks the "
        "present.",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_HEALTH_SHARDS", "int", 0,
        "Fleet-health snapshot shard count (`fleet_health.d/`): 0 "
        "(default) sizes adaptively — monolithic `fleet_health.json` "
        "for small fleets, then ~512 machines per shard up to 64 "
        "shards — so a dirty-shard flush rewrites one bounded file, "
        "not the whole fleet. Any positive value pins the count.",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_FLEET_STATUS_MAX_MACHINES", "int", 500,
        "Per-machine records inlined in the fleet-status document only "
        "while the fleet is at most this large (past it: summary + "
        "top-K offenders); also the hard cap on one `?machines=` page.",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_FLEET_STATUS_TOP_K", "int", 10,
        "Offender rows (unhealthiest machines) carried by the bounded "
        "fleet-status health section.",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_DEVICE_TELEMETRY", "bool", True,
        "Device-utilization sampling (`Device.memory_stats()` around "
        "fleet programs and at Prometheus scrape time); the "
        "compile-cache hit counters stay on with telemetry itself.",
        "Telemetry",
    ),
    Knob(
        "GORDO_TPU_WORKER_SINKS", "bool", "auto",
        "Per-process telemetry sinks: `serve_trace.jsonl` / "
        "`fleet_health.json` get a `-<pid>` suffix so N gunicorn "
        "workers stop overwriting one shared path (readers merge every "
        "variant). Default: on exactly when `PROMETHEUS_MULTIPROC_DIR` "
        "is configured — the existing multi-worker deployment signal.",
        "Telemetry",
    ),
    # -- SLO engine --------------------------------------------------------
    Knob(
        "GORDO_TPU_SLO_CONFIG", "str", None,
        "Path to a `slos.toml` declaring objectives and burn-rate "
        "alert rules (default: `<telemetry dir>/slos.toml`, then the "
        "packaged defaults).",
        "SLO",
    ),
    Knob(
        "GORDO_TPU_SLO_WINDOW_SECONDS", "int", 60,
        "Rollup window size for the cross-worker telemetry reducer "
        "(`rollups/<window>.json`); boundaries align to it, so rollups "
        "from different workers/hosts merge bucket-for-bucket.",
        "SLO",
    ),
    Knob(
        "GORDO_TPU_ROLLUP_MANIFEST", "bool", True,
        "Maintain `rollups/manifest.json` (window -> file map + "
        "per-sink span windows) so merged-window reads and "
        "`--since`/`--last` queries open only the rollup files they "
        "need instead of walking the directory.",
        "SLO",
    ),
    Knob(
        "GORDO_TPU_SLO_ROLLUP_KEEP", "int", 50_000,
        "Rollup windows retained on disk (oldest pruned past this); "
        "the default covers a 30d SLO window at 60s granularity.",
        "SLO",
    ),
    Knob(
        "GORDO_TPU_SLO_SINK_GC_AGE", "float", 86400.0,
        "Seconds a dead worker's fully-consumed trace-sink chain must "
        "sit unwritten before the rollup reducer deletes it; 0 "
        "disables sink GC (use that for aggregators running in "
        "another pid namespace/host, where the liveness probe is "
        "blind).",
        "SLO",
    ),
    Knob(
        "GORDO_TPU_SLO_SCRAPE_REFRESH", "float", 60.0,
        "Minimum seconds between scrape-driven SLO re-evaluations of a "
        "watched telemetry dir (`gordo_slo_*` gauges); 0 = scrapes "
        "report the cached status only.",
        "SLO",
    ),
    # -- Serving / micro-batching -----------------------------------------
    Knob(
        "GORDO_TPU_BATCHING", "bool", False,
        "Cross-request micro-batching master switch (`gordo_tpu.serve`).",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_BATCH_MAX_SIZE", "int", 32,
        "Member-axis batch capacity per fused program.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_BATCH_MAX_DELAY_MS", "float", 5.0,
        "Max time a request waits in the batch queue before a flush.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_BATCH_QUEUE_DEPTH", "int", 512,
        "Admission-control queue depth; overflow sheds with 429 + "
        "Retry-After.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_BATCH_DEADLINE_MS", "float", 2000.0,
        "Per-request queue deadline; expiry sheds with 504.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_BATCH_DISPATCHERS", "int", 1,
        "Dispatcher threads per batching engine.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_BATCH_ROW_LADDER", "str", "32,128,512,2048,8192",
        "Row-axis padding ladder (comma list, ascending); requests "
        "taller than the top rung fall back unbatched.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_BATCH_INLINE_FLUSH", "bool", True,
        "Let the request thread that fills a batch flush it inline "
        "instead of waking a dispatcher.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_SERVE_WARMUP", "bool", True,
        "Precompile the batch-ladder programs in a background thread at "
        "server boot (only when batching is on).",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_SERVE_WARMUP_ROWS", "int", 512,
        "Tallest row rung warmed at boot.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_SERVE_PRECISION", "str", "f32",
        "Default serving precision for the fused batch programs: `f32` "
        "(default, byte-identical to pre-precision serving), `bf16`, or "
        "`int8` (experimental per-channel weight quantization; "
        "activations run bf16). A spec's own `precision:` field "
        "overrides per model; reduced precision only serves behind a "
        "passed precision-parity gate and degrades to f32 on failure.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_PRECISION_GATE", "bool", True,
        "Gate reduced-precision serving on f32 verdict parity "
        "(`gordo_tpu.serve.precision`); off serves the requested "
        "precision ungated (benches/tests).",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_WIRE_COLUMNAR", "bool", True,
        "Columnar response fast path on the prediction/anomaly/fleet "
        "routes: vectorized numpy assembly + dict-free wire encoders "
        "(byte-identical JSON). Off = the legacy pandas assembly.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_WIRE_ARROW", "bool", True,
        "Serve and accept Arrow-IPC request/response bodies when "
        "pyarrow is importable (`Accept`/`Content-Type: "
        "application/vnd.apache.arrow.stream`). Off drills the "
        "JSON-only fallback.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_WIRE_STREAM", "bool", False,
        "Stream JSON response bodies as WSGI chunks (encode overlaps "
        "the socket write). Off by default: streamed serialize time "
        "lands outside the request's exported stage spans (see "
        "`docs/serving.md`).",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_INGEST_COMPILED", "bool", True,
        "Compiled preprocessing plans (`gordo_tpu.ingest`): per-member "
        "scaler affines are extracted into stacked device arrays cached "
        "on the revision fleet, and scale/transform runs inside the "
        "fused gather program. Off = every route materializes "
        "transformed inputs host-side (the legacy path).",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_INGEST_DLPACK", "bool", True,
        "Per-column dlpack device transfer for raw wire columns "
        "(`gordo_tpu.ingest.to_device`) — skips the intermediate host "
        "`column_stack`. Only engages on accelerator backends: on CPU "
        "both rungs stage through host memory, so host staging is the "
        "fast rung regardless of this knob. Any per-request dlpack "
        "failure (and off) falls back to host staging, counted by "
        "reason in `ingest_stats()['fallback_reasons']`.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_SERVE_FINITE_CHECK", "bool", True,
        "Scan every fused batch's output for non-finite (NaN/inf) rows: "
        "a member producing them from FINITE input is poisoned and "
        "fails alone (feeding its circuit breaker) instead of silently "
        "corrupting anomaly verdicts.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_BREAKER_THRESHOLD", "int", 3,
        "Consecutive isolated device failures that trip a member's "
        "serving circuit breaker into quarantine (503 + Retry-After).",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_BREAKER_COOLDOWN_S", "float", 30.0,
        "Initial quarantine cooldown before a tripped member's breaker "
        "half-opens and admits one probe request.",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_BREAKER_BACKOFF", "float", 2.0,
        "Cooldown multiplier applied on every re-trip (a failed "
        "half-open probe re-opens with a longer cooldown).",
        "Serving",
    ),
    Knob(
        "GORDO_TPU_BREAKER_MAX_COOLDOWN_S", "float", 600.0,
        "Cap on the exponential breaker cooldown.",
        "Serving",
    ),
    # -- Streaming ---------------------------------------------------------
    Knob(
        "GORDO_TPU_STREAM_ENABLED", "bool", True,
        "Master switch for the always-on streaming scoring plane "
        "(`/stream/...` routes). Disabled, stream routes answer 503.",
        "Streaming",
    ),
    Knob(
        "GORDO_TPU_STREAM_RING_ROWS", "int", 8192,
        "Per-machine row-ring capacity on a stream session. Ingest "
        "beyond it sheds oldest-first (counted, surfaced as a `shed` "
        "control frame) — bounded memory, never a stall.",
        "Streaming",
    ),
    Knob(
        "GORDO_TPU_STREAM_WINDOW_ROWS", "int", 64,
        "Watermark window height: a machine scores once it has this "
        "many buffered rows, through the same fused gather programs as "
        "the request path.",
        "Streaming",
    ),
    Knob(
        "GORDO_TPU_STREAM_OUTBOX_EVENTS", "int", 1024,
        "Per-session outbox ring capacity (scored anomalies + control "
        "frames). A consumer slower than the ring gets a `shed` "
        "scope-`outbox` frame with the evicted count on catch-up.",
        "Streaming",
    ),
    Knob(
        "GORDO_TPU_STREAM_SESSION_TTL_S", "float", 3600.0,
        "Idle seconds before a stream session (no ingest, no "
        "subscriber activity) is expired with a terminal `end` frame.",
        "Streaming",
    ),
    Knob(
        "GORDO_TPU_STREAM_HEARTBEAT_S", "float", 15.0,
        "SSE keep-alive comment interval on an idle event feed (keeps "
        "proxies from reaping the long-lived response).",
        "Streaming",
    ),
    Knob(
        "GORDO_TPU_STREAM_MAX_SESSIONS", "int", 64,
        "Live stream sessions the plane admits before answering 429 + "
        "Retry-After (admission control for the standing plane).",
        "Streaming",
    ),
    Knob(
        "GORDO_TPU_STREAM_SHED_RETRY_S", "float", 1.0,
        "Retry-After hint (seconds) in backpressure ingest acks and "
        "429 saturation responses.",
        "Streaming",
    ),
    # -- Lifecycle ---------------------------------------------------------
    Knob(
        "GORDO_TPU_DRIFT_SIGMA", "float", 2.0,
        "Per-feature drift threshold in baseline standard deviations.",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_DRIFT_FEATURE_QUORUM", "float", 0.25,
        "Fraction of features that must drift before a machine counts as "
        "drifted.",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_DRIFT_RESIDUAL_RATIO", "float", 2.0,
        "Serving-mse ratio over the calibrated baseline that marks "
        "residual drift.",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_DRIFT_MIN_SAMPLES", "int", 64,
        "Rows a drift window must accumulate before it is evaluated.",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_DRIFT_CALIBRATION", "int", 3,
        "Scoring batches used to calibrate the residual baseline.",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_GATE_MAX_ERROR_RATE", "float", 0.0,
        "Canary gate: max tolerated canary scoring error rate.",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_GATE_THRESHOLD_RATIO", "float", 4.0,
        "Canary gate: max rebuilt-vs-base anomaly-threshold ratio.",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_GATE_RESIDUAL_RATIO", "float", 2.0,
        "Canary gate: max canary-vs-base residual ratio.",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_GATE_PRECISION_AGREEMENT", "float", 0.98,
        "Precision-parity gate: minimum reduced-vs-f32 anomaly-verdict "
        "agreement fraction on the probe window (serve-time bucket "
        "gating AND the canary promotion gate).",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_GATE_PRECISION_RTOL", "float", 0.05,
        "Precision-parity gate: relative row tolerance for the "
        "reconstruction-closeness fallback (members without a fitted "
        "anomaly threshold).",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_GATE_PRECISION_PROBE_ROWS", "int", 128,
        "Precision-parity gate: probe window height scored per member.",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_CANARY_FRACTION", "float", 0.25,
        "Fraction of requests routed to a published canary revision.",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_QUARANTINE_COOLDOWN", "float", 3600.0,
        "Seconds a rolled-back machine stays quarantined before it may "
        "canary again (wall-clock: quarantine spans process restarts).",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_GATE_SLO_BURN", "bool", True,
        "Hold lifecycle auto-promotions while a page-severity SLO "
        "burn-rate alert is firing (the canary keeps its traffic "
        "slice; `lifecycle promote --force` bypasses).",
        "Lifecycle",
    ),
    Knob(
        "GORDO_TPU_LIFECYCLE_BREAKER_REBUILD", "bool", True,
        "Nominate members whose serving circuit breaker tripped (the "
        "health ledger's `breaker` section) as rebuild candidates "
        "alongside drifted ones.",
        "Lifecycle",
    ),
    # -- Learned performance model -----------------------------------------
    Knob(
        "GORDO_TPU_PERFMODEL", "bool", False,
        "Master switch for the learned performance model: cost tables "
        "carrying a fitted `learned` section answer in-domain "
        "predictions (device ms / compile ms / HBM bytes) from the "
        "trace-trained log-linear regressors instead of the analytic "
        "formula. Off: the section is inert — plans and ladder choices "
        "are byte-identical to the analytic model's.",
        "Performance model",
    ),
    Knob(
        "GORDO_TPU_PERFMODEL_TABLE", "str", None,
        "Path to the `cost_table.json` the SERVING plane's estimators "
        "(batch-span predictions, stream flush predictions, the "
        "model-informed consumers below) load; unreadable or "
        "mis-versioned tables warn once and degrade to the analytic "
        "defaults. Unset: the analytic defaults.",
        "Performance model",
    ),
    Knob(
        "GORDO_TPU_PERFMODEL_WARMUP", "bool", False,
        "Order serve warmup by predicted cost, hottest first (specs by "
        "predicted step time at the top warm shape, then per-spec "
        "shapes descending) so the most expensive compiles happen "
        "earliest in the warmup budget.",
        "Performance model",
    ),
    Knob(
        "GORDO_TPU_PERFMODEL_BATCH_CAP_BYTES", "int", 0,
        "Per-spec predicted-HBM batch cap in bytes: row rungs whose "
        "predicted fused-batch footprint (at the full member ladder) "
        "exceeds the budget are never batched into — requests taller "
        "than the allowed rungs serve unbatched. 0 = off.",
        "Performance model",
    ),
    Knob(
        "GORDO_TPU_PERFMODEL_BREAKER", "bool", False,
        "Predicted-HBM-aware OOM demotion: a RESOURCE_EXHAUSTED batch "
        "demotes to the largest ladder rung whose predicted footprint "
        "is safely below the failed shape's, instead of the fixed "
        "halve-members / drop-one-row-rung heuristic.",
        "Performance model",
    ),
    Knob(
        "GORDO_TPU_PERFMODEL_BREAKER_SAFETY", "float", 0.8,
        "Safety factor for predicted-HBM-aware demotion: the demoted "
        "rung's predicted bytes must be <= this fraction of the failed "
        "shape's predicted bytes.",
        "Performance model",
    ),
    Knob(
        "GORDO_TPU_PERFMODEL_PRECISION", "bool", False,
        "Model-informed precision rung choice: when neither the spec "
        "nor `GORDO_TPU_SERVE_PRECISION` pins a serving precision, pick "
        "the rung with the lowest predicted step time for the bucket's "
        "shape (the parity gate still decides whether reduced may "
        "actually serve).",
        "Performance model",
    ),
    Knob(
        "GORDO_TPU_PERFMODEL_RECAL", "bool", False,
        "Online recalibration: each lifecycle cycle refits the learned "
        "sections from the telemetry corpus and promotes the new table "
        "only if its holdout error beats the incumbent's "
        "(`gordo_tpu.perfmodel.service.maybe_recalibrate`).",
        "Performance model",
    ),
    Knob(
        "GORDO_TPU_PERFMODEL_MIN_SAMPLES", "int", 32,
        "Minimum training rows per (target, program) before a learned "
        "model is fitted for it; thinner populations stay analytic.",
        "Performance model",
    ),
    # -- Reporters ---------------------------------------------------------
    Knob(
        "GORDO_TPU_MLFLOW_DIR", "str", None,
        "Local MLflow tracking root (default: `<tmpdir>/gordo-mlruns`).",
        "Reporters",
    ),
    # -- Static analysis ---------------------------------------------------
    Knob(
        "GORDO_TPU_LOCK_TRACE", "str", None,
        "Opt-in lock-order tracing (`gordo_tpu.analysis.lockgraph`): a "
        "`.jsonl` path (or `1` for `./lock_trace.jsonl`) wraps every "
        "lock created after install in an instrumented wrapper that "
        "records per-thread acquisition-ordering edges into a "
        "pid-suffixed sink; `gordo-tpu lockgraph` analyzes the sinks "
        "and fails on ordering cycles (potential deadlocks). Off by "
        "default — zero overhead unless set.",
        "Static analysis",
    ),
    # -- Testing -----------------------------------------------------------
    Knob(
        "GORDO_TPU_DOCTEST_KNOB", "int", 7,
        "Reserved for the `utils.env` doctests and the lint fixture "
        "suite; never read by production code.",
        "Testing",
    ),
)


def knob_sections() -> Tuple[str, ...]:
    """Section names in declaration order (the docs-table grouping)."""
    seen: Dict[str, None] = {}
    for knob in KNOBS.values():
        seen.setdefault(knob.section)
    return tuple(seen)
