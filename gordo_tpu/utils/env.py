"""
Environment-knob parsing: one warn-and-fall-back implementation for every
``GORDO_TPU_*`` numeric knob instead of a per-call-site copy.

>>> import os
>>> os.environ["GORDO_TPU_DOCTEST_KNOB"] = "not-a-number"
>>> env_int("GORDO_TPU_DOCTEST_KNOB", 7)
7
>>> del os.environ["GORDO_TPU_DOCTEST_KNOB"]
"""

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)


def env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw:
        try:
            return int(raw)
        except ValueError:
            logger.warning("Invalid %s=%r; using %r", name, raw, default)
    return default


def env_float(name: str, default: Optional[float]) -> Optional[float]:
    raw = os.environ.get(name)
    if raw:
        try:
            return float(raw)
        except ValueError:
            logger.warning("Invalid %s=%r; using %r", name, raw, default)
    return default
