"""
The post-fork reset registry: one place for module-level state that must
NOT survive ``os.fork()`` into a child process.

The bug class this closes (the ``fork-safety`` lint rule enforces it):
a module memoizes state derived from process identity — a pid-suffixed
sink path (``worker_sink_path``), a ledger snapshotting to
``fleet_health-<pid>.json``, a trace recorder whose writer thread only
exists in the parent — and a gunicorn ``--preload`` master builds it
once, then forks N workers that all inherit the parent's frozen value
and clobber one shared file (or enqueue spans to a writer thread that
does not exist on their side of the fork; threads never survive fork).

Modules register a zero-arg reset callable at import time::

    from ..utils.postfork import register_postfork_reset

    register_postfork_reset(_reset_after_fork, name="telemetry.serving")

The first registration installs one ``os.register_at_fork``
``after_in_child`` hook that runs every registered reset, newest last.
Resets run in the CHILD only, must not raise (failures are logged and
swallowed — a broken reset must not kill a fresh worker), and should
only drop references: closing inherited file handles would flush the
parent's buffered bytes a second time.

Stdlib-only (``utils`` sits below every other package) and a no-op on
platforms without ``fork``.
"""

import logging
import os
import threading
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger(__name__)

_registry_lock = threading.Lock()
_resets: List[Tuple[str, Callable[[], None]]] = []
_hook_installed = False


def register_postfork_reset(
    reset: Callable[[], None], name: Optional[str] = None
) -> None:
    """Run ``reset()`` in every child this process forks, after the
    fork. Registration is idempotent per callable (re-imports under
    test reloaders must not stack duplicates)."""
    global _hook_installed
    with _registry_lock:
        if any(existing is reset for _, existing in _resets):
            return
        _resets.append((name or getattr(reset, "__qualname__", "reset"), reset))
        if not _hook_installed and hasattr(os, "register_at_fork"):
            os.register_at_fork(after_in_child=run_postfork_resets)
            _hook_installed = True


def run_postfork_resets() -> None:
    """Run every registered reset (the child-side fork hook; tests call
    it directly to simulate a fork)."""
    with _registry_lock:
        resets = list(_resets)
    for name, reset in resets:
        try:
            reset()
        except Exception:  # noqa: BLE001 - a broken reset must not kill
            # the freshly forked worker it exists to protect
            logger.exception("post-fork reset %s failed", name)


def registered_resets() -> List[str]:
    """The registered reset names, registration order (introspection —
    the thread-shutdown audit test asserts the serving stack's are in)."""
    with _registry_lock:
        return [name for name, _ in _resets]
