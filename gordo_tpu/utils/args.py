"""
Init-argument capture for config round-tripping.

Reference parity: gordo/util/utils.py:6-48 (``capture_args``) — reporters and
other serializer-aware objects need ``get_params`` to return exactly the
arguments they were constructed with.
"""

import functools
import inspect


def capture_args(method):
    """
    Decorator for ``__init__`` which stores the bound call arguments on the
    instance as ``self._params`` so that ``get_params`` / ``to_dict`` can
    round-trip the object through the serializer.

    Examples
    --------
    >>> class Thing:
    ...     @capture_args
    ...     def __init__(self, a, b=2, *args, **kwargs):
    ...         pass
    >>> Thing(1, b=3, extra="x")._params
    {'a': 1, 'b': 3, 'args': [], 'extra': 'x'}
    """

    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        bound = inspect.signature(method).bind(self, *args, **kwargs)
        bound.apply_defaults()
        params = {}
        for name, value in bound.arguments.items():
            if name == "self":
                continue
            kind = inspect.signature(method).parameters[name].kind
            if kind is inspect.Parameter.VAR_POSITIONAL:
                params["args"] = list(value)
            elif kind is inspect.Parameter.VAR_KEYWORD:
                params.update(value)
            else:
                params[name] = value
        self._params = params
        return method(self, *args, **kwargs)

    return wrapper
