from .args import capture_args
from .text import replace_all_non_ascii_chars

__all__ = ["capture_args", "replace_all_non_ascii_chars"]
