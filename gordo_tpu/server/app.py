"""
The model server: a werkzeug-native WSGI application.

Reference parity: gordo/server/server.py — same env-driven config
(``MODEL_COLLECTION_DIR``, ``EXPECTED_MODELS``, ``ENABLE_PROMETHEUS``,
``PROJECT``), Envoy/Ambassador prefix-rewrite middleware, per-request
revision resolution from ``?revision=``/header with 410 on a missing
revision, revision stamped into every JSON body and response header,
``Server-Timing`` header, ``/healthcheck`` and ``/server-version`` routes,
plus the base + anomaly route sets.

Engine difference: Flask isn't a dependency here — routing is a werkzeug
``Map`` and per-request state is an explicit :class:`RequestContext` passed
to handlers instead of the ``flask.g`` ambient global. The JSON encoder is
simplejson with ``ignore_nan`` so NaN heads of smoothed anomaly columns
serialize as null.
"""

import contextlib
import logging
import os
import time
import timeit
import typing
from functools import wraps
from typing import Any, Dict, Optional

from ..utils import json_compat as simplejson
import yaml
from werkzeug.exceptions import HTTPException
from werkzeug.routing import Map, Rule
from werkzeug.wrappers import Request, Response

import gordo_tpu

from ..telemetry import SpanRecorder, tracing
from ..telemetry import serving as serve_trace
from ..utils.env import env_bool
from ..telemetry.profiler import SamplingProfiler, should_profile
from . import utils as server_utils
from .utils import ServerError
from .views import anomaly, base
from .views import stream as stream_views

logger = logging.getLogger(__name__)


def enable_prometheus() -> bool:
    return os.getenv("ENABLE_PROMETHEUS", "false") != "false"


def default_config() -> Dict[str, Any]:
    """Server config resolved from the environment (reference server.py:36-43)."""
    return {
        "MODEL_COLLECTION_DIR_ENV_VAR": "MODEL_COLLECTION_DIR",
        "EXPECTED_MODELS": yaml.safe_load(os.getenv("EXPECTED_MODELS", "[]")),
        "ENABLE_PROMETHEUS": enable_prometheus(),
        "PROJECT": os.getenv("PROJECT"),
    }


class RequestContext:
    """
    Per-request state: the request, resolved revision/collection dir, and
    whatever the handlers load (model, metadata, X, y). The explicit
    equivalent of the reference's ``flask.g``.

    Every request owns a W3C trace identity: ``trace_id`` continues an
    incoming ``traceparent`` header (so a gateway's trace flows through
    the model server) or starts fresh; ``span_id`` names the request's
    root span. The per-request ``timing`` recorder adopts that identity,
    so the stage spans it collects nest under the request span — and at
    finalization the whole set exports into the process-shared
    ``serve_trace.jsonl`` (telemetry/serving.py).
    """

    __slots__ = (
        "request",
        "config",
        "start_time",
        "start_wall",
        "timing",
        "trace_id",
        "span_id",
        "remote_parent_id",
        "sampled",
        "current_stage",
        "profiler",
        "endpoint",
        "gordo_name",
        "collection_dir",
        "current_revision",
        "revision",
        "model",
        "metadata",
        "info",
        "resolution",
        "deferred_stage",
        "X",
        "y",
        "ingest",
    )

    def __init__(self, request: Request, config: Dict[str, Any]):
        self.request = request
        self.config = config
        self.start_time = timeit.default_timer()
        self.start_wall = time.time()
        incoming = tracing.parse_traceparent(
            request.headers.get(tracing.TRACEPARENT_HEADER)
        )
        if incoming is not None:
            self.trace_id = incoming.trace_id
            self.remote_parent_id = incoming.span_id
            # export sampling: the upstream decision is honored; locally
            # originated traces decide in _dispatch_bound (None =
            # undecided)
            self.sampled: Optional[bool] = incoming.sampled
            self.span_id = tracing.new_span_id()
        else:
            fresh = tracing.new_trace_context()
            self.trace_id = fresh.trace_id
            self.span_id = fresh.span_id
            self.remote_parent_id = None
            self.sampled = None
        # Per-request span recorder (telemetry/recorder.py, in-memory
        # only): handlers wrap their stages in ``ctx.stage(...)`` and
        # _finalize turns the recorded durations into Server-Timing
        # entries, so every response carries its own stage breakdown.
        self.timing = SpanRecorder(
            service="gordo-tpu-server", trace_id=self.trace_id
        )
        self.timing.default_parent_id = self.span_id
        self.current_stage: Optional[str] = None
        self.profiler: Optional[SamplingProfiler] = None
        self.endpoint: Optional[str] = None
        self.gordo_name: Optional[str] = None
        self.collection_dir: Optional[str] = None
        self.current_revision: Optional[str] = None
        self.revision: Optional[str] = None
        self.model = None
        self.metadata: Optional[dict] = None
        self.info: Optional[dict] = None
        self.resolution = None  # fleet ModelResolution (resolve_model)
        # (name, start_time) of a stage that ends WITH the request —
        # the wire fast path's serialize: after the encode there is
        # only response construction (~30µs), but under thread load the
        # GIL preemption a long encode earns lands exactly after the
        # stage's closing clock read, so a conventional span would leak
        # the parked tail into unattributed walltime (measured ~20ms
        # p50 at 16 threads — the whole attribution-coverage gap).
        # _finalize closes the interval at the request's own end clock.
        self.deferred_stage: Optional[tuple] = None
        self.X = None
        self.y = None
        # Raw wire columns (ingest.RawColumns) stashed by the Arrow
        # decode when they align with the model's tag order — the
        # device-resident ingest path scores them without the host
        # column_stack staging copy.
        self.ingest = None

    @contextlib.contextmanager
    def stage(self, name: str):
        """Span over one request stage (``model_resolve``, ``data_decode``,
        ``inference``, ``response_assemble``, ``serialize``); surfaces in
        Server-Timing, the exported request trace, and — while a sampling
        profiler is attached — as the stage axis of its self-time
        aggregation (``current_stage`` is read from the sampling thread)."""
        previous = self.current_stage
        self.current_stage = name
        try:
            with self.timing.span(name) as handle:
                yield handle
        finally:
            self.current_stage = previous

    # -- response builders --------------------------------------------------

    def json_response(self, payload: dict, status: int = 200) -> Response:
        # Revision is stamped here, at serialization time, rather than by
        # re-parsing the body in an after-request hook: prediction payloads
        # can be multi-MB and a loads/dumps round-trip would triple the
        # serialization cost of the hot path.
        if self.revision is not None and isinstance(payload, dict):
            payload = {**payload, "revision": self.revision}
        with self.stage("serialize"):
            body = simplejson.dumps(payload, default=str, ignore_nan=True)
        return Response(body, status=status, mimetype="application/json")

    def raw_response(
        self, body, mimetype: str, status: int = 200
    ) -> Response:
        """A pre-serialized response: the wire fast path encodes inside
        the handler's own ``serialize`` stage (JSON bytes, Arrow IPC, or
        a streamed chunk iterator) and hands the finished body here —
        re-serializing through :meth:`json_response` would walk the
        payload again."""
        return Response(body, status=status, mimetype=mimetype)

    def file_response(
        self, data: bytes, download_name: Optional[str] = None
    ) -> Response:
        response = Response(data, mimetype="application/octet-stream")
        if download_name:
            response.headers["Content-Disposition"] = (
                f"attachment; filename={download_name}"
            )
        return response


def adapt_proxy_deployment(wsgi_app: typing.Callable) -> typing.Callable:
    """
    WSGI middleware fixing behind-proxy routing on k8s/Envoy: the proxy
    forwards the full prefixed path (``/gordo/v0/<project>/<name>/metadata``)
    in ``HTTP_X_ENVOY_ORIGINAL_PATH`` while ``PATH_INFO`` holds the local
    route; reconstruct ``SCRIPT_NAME``/``PATH_INFO`` accordingly
    (reference server.py:46-118).
    """

    @wraps(wsgi_app)
    def wrapper(environ, start_response):
        script_name = environ.get("HTTP_X_ENVOY_ORIGINAL_PATH", "")
        if script_name:
            path_info = environ.get("PATH_INFO", "")
            if path_info.rstrip("/"):
                script_name = script_name.replace(path_info, "")
            environ["SCRIPT_NAME"] = script_name
            if path_info.startswith(script_name):
                environ["PATH_INFO"] = path_info[len(script_name):]

        scheme = environ.get("HTTP_X_FORWARDED_PROTO", "")
        if scheme:
            environ["wsgi.url_scheme"] = scheme
        return wsgi_app(environ, start_response)

    return wrapper


PREFIX = "/gordo/v0"

URL_MAP = Map(
    [
        Rule("/healthcheck", endpoint="healthcheck", methods=["GET"]),
        Rule("/server-version", endpoint="server-version", methods=["GET"]),
        Rule(
            f"{PREFIX}/<gordo_project>/<gordo_name>/prediction",
            endpoint="prediction",
            methods=["POST"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/<gordo_name>/anomaly/prediction",
            endpoint="anomaly-prediction",
            methods=["POST"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/<gordo_name>/metadata",
            endpoint="metadata",
            methods=["GET"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/<gordo_name>/healthcheck",
            endpoint="model-healthcheck",
            methods=["GET"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/<gordo_name>/download-model",
            endpoint="download-model",
            methods=["GET"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/<gordo_name>/revision/<revision>",
            endpoint="delete-revision",
            methods=["DELETE"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/prediction/fleet",
            endpoint="fleet-prediction",
            methods=["POST"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/stream/<stream_id>/ingest",
            endpoint="stream-ingest",
            methods=["POST"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/stream/<stream_id>/events",
            endpoint="stream-events",
            methods=["GET"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/stream/status",
            endpoint="stream-status",
            methods=["GET"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/stream/<stream_id>",
            endpoint="stream-close",
            methods=["DELETE"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/build-status",
            endpoint="build-status",
            methods=["GET"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/fleet-health",
            endpoint="fleet-health",
            methods=["GET"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/slo",
            endpoint="slo",
            methods=["GET"],
        ),
        Rule(f"{PREFIX}/<gordo_project>/models", endpoint="models", methods=["GET"]),
        Rule(
            f"{PREFIX}/<gordo_project>/revisions",
            endpoint="revisions",
            methods=["GET"],
        ),
        Rule(
            f"{PREFIX}/<gordo_project>/expected-models",
            endpoint="expected-models",
            methods=["GET"],
        ),
    ],
    strict_slashes=False,
)

HANDLERS = {
    "prediction": base.post_prediction,
    "anomaly-prediction": anomaly.post_anomaly_prediction,
    "fleet-prediction": base.post_fleet_prediction,
    "metadata": base.get_metadata,
    "model-healthcheck": base.get_metadata,
    "download-model": base.get_download_model,
    "delete-revision": base.delete_model_revision,
    "models": base.get_model_list,
    "revisions": base.get_revision_list,
    "expected-models": base.get_expected_models,
    "build-status": base.get_build_status,
    "fleet-health": base.get_fleet_health,
    "slo": base.get_slo_status,
    "stream-ingest": stream_views.post_stream_ingest,
    "stream-events": stream_views.get_stream_events,
    "stream-status": stream_views.get_stream_status,
    "stream-close": stream_views.delete_stream,
}


class GordoServerApp:
    """The WSGI application serving a model-collection directory."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = default_config()
        if config is not None:
            self.config.update(config)
        self.prometheus_metrics = None
        # Graceful-shutdown flag: once draining, /healthcheck answers 503
        # (load balancers stop sending) while every already-accepted
        # request — including everything queued in the micro-batcher —
        # still gets a real response (drain_and_stop).
        import threading

        self._draining = threading.Event()

    def begin_drain(self) -> None:
        self._draining.set()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    # -- request lifecycle --------------------------------------------------

    def _resolve_revision(self, ctx: RequestContext) -> Optional[Response]:
        """
        Point the context at the served (or requested) revision directory;
        410 for bad/missing revisions (reference server.py:169-195).

        Requests that do NOT pin a revision route through the fleet
        store's lifecycle routing (``STORE.route``): a hot-swapped
        (promoted) revision or the canary traffic slice resolves HERE,
        once per request, so every artifact the request touches comes
        from one revision — explicitly pinned revisions bypass routing.
        """
        ctx.collection_dir = os.environ[self.config["MODEL_COLLECTION_DIR_ENV_VAR"]]
        ctx.current_revision = os.path.basename(ctx.collection_dir)

        request = ctx.request
        revision = request.args.get("revision") or request.headers.get("revision")
        if revision:
            # Validate before adopting: a malformed revision must never be
            # echoed into response headers (newlines would crash werkzeug).
            if not server_utils.validate_revision(revision):
                return ctx.json_response(
                    {"error": "Revision should only contains numbers."}, status=410
                )
            ctx.revision = revision
            ctx.collection_dir = os.path.join(ctx.collection_dir, "..", revision)
            try:
                os.listdir(ctx.collection_dir)
            except FileNotFoundError:
                return ctx.json_response(
                    {"error": f"Revision '{revision}' not found."}, status=410
                )
        else:
            from .fleet_store import STORE

            routed = STORE.route(ctx.collection_dir)
            if routed != ctx.collection_dir:
                ctx.collection_dir = routed
                # the response honestly stamps the revision that SERVED it
                ctx.current_revision = os.path.basename(
                    os.path.normpath(routed)
                )
            ctx.revision = ctx.current_revision
        return None

    #: endpoints whose request traces would only add noise and volume
    #: (load balancers hit /healthcheck every few seconds)
    UNTRACED_ENDPOINTS = (None, "healthcheck", "server-version")

    def _finalize(self, ctx: RequestContext, response: Response) -> Response:
        """Stamp the revision + ``traceparent`` headers, add
        Server-Timing — one entry per recorded request stage
        (milliseconds, per the Server-Timing spec) plus the
        reference-parity ``request_walltime_s`` total (seconds, kept
        last under its original name/unit for existing dashboards) —
        then export the finished request into the shared serving trace
        and hand the stage durations to the Prometheus observer."""
        if ctx.revision is not None:
            response.headers["revision"] = ctx.revision
        response.headers[tracing.TRACEPARENT_HEADER] = tracing.format_traceparent(
            ctx.trace_id, ctx.span_id, sampled=bool(ctx.sampled)
        )

        runtime_s = timeit.default_timer() - ctx.start_time
        if ctx.deferred_stage is not None:
            name, stage_start = ctx.deferred_stage
            ctx.deferred_stage = None
            ctx.timing.record(
                name, max(0.0, timeit.default_timer() - stage_start)
            )
        logger.debug("Total runtime for request: %ss", runtime_s)
        durations = ctx.timing.durations()
        entries = [
            f"{name};dur={round(seconds * 1000.0, 2)}"
            for name, seconds in durations.items()
        ]
        entries.append(f"request_walltime_s;dur={runtime_s}")
        response.headers["Server-Timing"] = ", ".join(entries)

        # RED attribution for wsgi_app's Prometheus observer: the stage
        # breakdown and route identity ride the response object (the
        # observer sees only (request, response, duration)).
        response.gordo_stage_durations = durations
        response.gordo_endpoint = ctx.endpoint
        response.gordo_model_name = ctx.gordo_name

        profile_report = None
        if ctx.profiler is not None:
            profile_report = ctx.profiler.stop()
            ctx.profiler = None
        self._record_health(ctx, response)
        if ctx.sampled and ctx.endpoint not in self.UNTRACED_ENDPOINTS:
            serve_trace.export_request_trace(
                ctx.timing,
                span_id=ctx.span_id,
                parent_id=ctx.remote_parent_id,
                start=ctx.start_wall,
                duration_s=runtime_s,
                attributes={
                    "http.method": ctx.request.method,
                    "http.route": ctx.endpoint,
                    "http.status_code": response.status_code,
                    "gordo_name": ctx.gordo_name or "",
                    "revision": ctx.revision or "",
                },
                error=(
                    f"HTTP {response.status_code}"
                    if response.status_code >= 500
                    else None
                ),
                profile=profile_report,
            )
        return response

    #: endpoints whose outcomes feed the per-member health ledger —
    #: scoring traffic only (metadata/listing requests say nothing about
    #: a machine's serving health)
    HEALTH_ENDPOINTS = ("prediction", "anomaly-prediction")

    def _record_health(self, ctx: RequestContext, response: Response) -> None:
        """Per-machine request/error counts into the fleet health ledger
        (telemetry/fleet_health.py), keyed to the ANCHOR collection dir
        (the env var, not the routed revision) so counts survive
        lifecycle hot-swaps. 5xx marks the machine; 4xx is the client's
        problem. Best-effort and throttled — the ledger must never cost
        the request path more than a dict update.

        Gated on a RESOLVED model: ``gordo_name`` is client-supplied URL
        text, and recording it unconditionally would let a scanner mint
        one ledger record (and one 'healthy' machine in the Prometheus
        counts) per random path — the same request-derived-identity
        cardinality class the ``{unmatched}`` label collapse guards
        against. A name that never loaded a model is not a machine."""
        if (
            ctx.endpoint not in self.HEALTH_ENDPOINTS
            or not ctx.gordo_name
            or ctx.model is None
        ):
            return
        try:
            from ..telemetry import ledger_for

            anchor = os.environ.get(self.config["MODEL_COLLECTION_DIR_ENV_VAR"])
            if not anchor:
                return
            # 503 is backpressure (a breaker-quarantined member shedding
            # its own traffic), not NEW failure evidence — the trip that
            # caused it was already recorded by the breaker feed; letting
            # every rejected retry mark an error would ratchet the
            # machine's health down for the whole quarantine
            ledger_for(anchor, project=self.config.get("PROJECT") or "").record_request(
                ctx.gordo_name,
                error=response.status_code >= 500
                and response.status_code != 503,
            )
        except Exception:  # noqa: BLE001 - health telemetry is advisory
            logger.debug("health ledger request not recorded", exc_info=True)

    def dispatch(self, request: Request) -> Response:
        ctx = RequestContext(request, self.config)
        token = tracing.bind(ctx.trace_id)
        try:
            return self._dispatch_bound(ctx, request)
        finally:
            tracing.unbind(token)

    def _dispatch_bound(self, ctx: RequestContext, request: Request) -> Response:
        profile_arg = request.args.get("profile")
        try:
            endpoint_adapter = URL_MAP.bind_to_environ(request.environ)
            endpoint, view_args = endpoint_adapter.match()
            ctx.endpoint = endpoint
            ctx.gordo_name = view_args.get("gordo_name")

            if endpoint == "healthcheck":
                if self.draining:
                    response = Response("draining", status=503)
                else:
                    response = Response("", status=200)
                return self._finalize(ctx, response)
            if endpoint == "server-version":
                response = ctx.json_response({"version": gordo_tpu.__version__})
                return self._finalize(ctx, response)

            # trace-export sampling: with the serving sink on, honor an
            # upstream traceparent decision, else head-sample locally
            # (GORDO_TPU_TRACE_SAMPLE_RATE) — every request still gets a
            # trace id; sampling gates only span export
            if serve_trace.serve_recorder().enabled:
                if ctx.sampled is None:
                    ctx.sampled = serve_trace.sample_trace()
                # host-pipeline sampling profiler: per-request
                # (?profile=1) or a random slice
                # (GORDO_TPU_PROFILE_SAMPLE_RATE); a profiled request is
                # always exported — the report's destination is a
                # `profile` span in serve_trace.jsonl
                if should_profile(profile_arg):
                    ctx.sampled = True
                    ctx.profiler = SamplingProfiler().start(
                        stage_getter=lambda: ctx.current_stage
                    )
            else:
                ctx.sampled = False
            # the engine reads this to decide whether batch spans should
            # link back to this request's (exported) spans
            ctx.timing.sampled = ctx.sampled

            error_response = self._resolve_revision(ctx)
            if error_response is not None:
                return self._finalize(ctx, error_response)

            if profile_arg == "device":
                # the heavyweight opt-in layer: a TensorBoard-loadable
                # XLA device trace for this one request (no-op unless
                # GORDO_TPU_PROFILE_DIR is set)
                from ..utils.profiling import maybe_trace

                with maybe_trace(f"request-{ctx.trace_id[:16]}"):
                    response = HANDLERS[endpoint](ctx, **view_args)
            else:
                response = HANDLERS[endpoint](ctx, **view_args)
        except ServerError as exc:
            response = ctx.json_response(exc.payload, status=exc.status)
        except HTTPException as exc:
            response = ctx.json_response(
                {"error": exc.description}, status=exc.code or 500
            )
        except Exception:
            logger.exception("Unhandled server error")
            response = ctx.json_response({"error": "Internal Server Error"}, status=500)
        return self._finalize(ctx, response)

    def wsgi_app(self, environ, start_response):
        request = Request(environ)
        start = timeit.default_timer()
        response = self.dispatch(request)
        if self.prometheus_metrics is not None:
            self.prometheus_metrics.observe(
                request, response, timeit.default_timer() - start
            )
        return response(environ, start_response)

    def __call__(self, environ, start_response):
        return self._wsgi_entry(environ, start_response)

    # build_app replaces this per-instance with the proxy-adapted entry.
    _wsgi_entry = wsgi_app


def build_app(
    config: Optional[Dict[str, Any]] = None,
    prometheus_registry=None,
) -> GordoServerApp:
    """
    Build the server application with proxy adaptation applied and, when
    enabled, prometheus request metrics and the cross-request
    micro-batching engine (``GORDO_TPU_BATCHING`` — see
    ``gordo_tpu.serve``), including its startup warmup pass.
    """
    app = GordoServerApp(config)
    app._wsgi_entry = adapt_proxy_deployment(app.wsgi_app)
    # every in-request log record carries its trace_id from here on
    tracing.install_trace_log_stamping()

    if app.config["ENABLE_PROMETHEUS"]:
        from .prometheus.metrics import create_prometheus_metrics

        app.prometheus_metrics = create_prometheus_metrics(
            project=app.config.get("PROJECT"), registry=prometheus_registry
        )
    elif prometheus_registry is not None:
        logger.warning("Ignoring non empty prometheus_registry argument")

    # Lifecycle continuity: a promotion the supervisor recorded before
    # this process booted (state.json beside the revisions) is
    # re-installed as a hot-swap redirect, so a restarted server keeps
    # serving the promoted revision even when its env var still points
    # at the original one. BEFORE engine warmup, which warms whatever
    # the store routes to.
    collection_dir = os.environ.get(app.config["MODEL_COLLECTION_DIR_ENV_VAR"])
    if collection_dir and os.path.isdir(collection_dir):
        try:
            from ..lifecycle import restore_serving_state

            restore_serving_state(collection_dir)
        except Exception:  # noqa: BLE001 - serving state restore is
            # advisory; a torn state file must not take the server down
            logger.exception("lifecycle serving-state restore failed")

    # SLO exposition: mark the serving telemetry dir watched so /metrics
    # scrapes keep gordo_slo_* fresh (throttled re-evaluation; see
    # GORDO_TPU_SLO_SCRAPE_REFRESH). No-op with telemetry off.
    try:
        from ..telemetry import slo as slo_engine

        slo_engine.watch(slo_engine.slo_directory(collection_dir))
    except Exception:  # noqa: BLE001 - SLO exposition is advisory
        logger.debug("slo watch registration failed", exc_info=True)

    # Micro-batching engine: process-global (gthread workers share it,
    # like STORE); created here so the server lifecycle owns warmup and
    # the atexit drain. Default-off — without the env switch this is a
    # no-op and serving behaves exactly as before.
    from .. import serve

    engine = serve.ensure_engine()
    if engine is not None:
        if app.prometheus_metrics is not None and engine.metrics is None:
            from .prometheus.metrics import serve_metrics

            engine.metrics = serve_metrics(
                project=app.config.get("PROJECT"),
                registry=app.prometheus_metrics.registry,
            )
        # the ANCHOR dir the breaker feed should ledger against — wired
        # through the app's configurable env-var name, the same
        # indirection every other health feed resolves through (the
        # engine's own fallback reads the default MODEL_COLLECTION_DIR)
        if collection_dir:
            engine.ledger_anchor = collection_dir
        _start_serve_warmup(app, engine)
    return app


def drain_and_stop(app: GordoServerApp, server=None, engine=None) -> None:
    """Graceful shutdown: flip the app to draining (healthcheck 503 so
    load balancers stop routing here), drain the micro-batching engine —
    every queued and in-flight batch resolves its futures, new batched
    work falls back to the still-running unbatched path — then stop the
    HTTP server's accept loop. Queued requests never die unanswered with
    the process."""
    from .. import serve

    app.begin_drain()
    # standing streams FIRST: every live SSE subscriber gets its
    # terminal `drain` frame and flushes its outbox tail while the
    # batcher below is still resolving in-flight futures — a long-lived
    # stream socket closes cleanly instead of dying mid-frame
    try:
        from ..stream import get_plane

        plane = get_plane()
        if plane is not None:
            plane.drain()
    except Exception:  # noqa: BLE001 - stream drain is best-effort; the
        # engine drain and server stop below must still run
        logger.exception("stream plane drain failed")
    engine = engine if engine is not None else serve.get_engine()
    if engine is not None:
        logger.info("draining micro-batcher before shutdown")
        engine.shutdown(drain=True)
    # the serving trace is write-buffered; the drained batches' spans
    # and the final requests' traces must reach disk before exit
    serve_trace.serve_recorder().flush()
    if server is not None:
        server.shutdown()
    # close (not just flush) the shared trace recorder: close() joins
    # its async writer thread, so SIGTERM leaves no gordo-owned thread
    # alive — every remaining thread at this point is daemon by the
    # thread-lifecycle lint contract (the regression test in
    # tests/server/test_shutdown_threads.py pins both properties)
    serve_trace.reset_serve_recorder()


def install_graceful_shutdown(app: GordoServerApp, server=None):
    """SIGTERM/SIGINT → :func:`drain_and_stop` on a background thread
    (signal handlers must return fast). No-op outside the main thread
    (embedded/test servers manage their own lifecycle)."""
    import signal
    import threading

    def handler(_signum, _frame):
        threading.Thread(
            target=drain_and_stop,
            args=(app, server),
            name="gordo-drain",
            daemon=True,
        ).start()

    try:
        signal.signal(signal.SIGTERM, handler)
        signal.signal(signal.SIGINT, handler)
    except ValueError:  # not the main thread
        return None
    return handler


def serve_warmup_enabled() -> bool:
    """Startup precompile of the served buckets' ladder programs: on by
    default whenever batching is on (``GORDO_TPU_SERVE_WARMUP=0`` skips)."""
    return env_bool("GORDO_TPU_SERVE_WARMUP", True)


def _start_serve_warmup(app: GordoServerApp, engine) -> Optional[object]:
    """Kick off the engine's warmup for the served collection dir in the
    background, so the first request after boot hits compiled programs
    without the boot itself blocking on XLA."""
    import threading

    if not serve_warmup_enabled():
        return None
    collection_dir = os.environ.get(app.config["MODEL_COLLECTION_DIR_ENV_VAR"])
    if not collection_dir or not os.path.isdir(collection_dir):
        return None

    def warm():
        try:
            from .fleet_store import STORE

            # warm what requests will actually resolve: the lifecycle
            # routing may point this dir at a promoted revision
            engine.warmup_collection(STORE.route(collection_dir))
        except Exception:  # noqa: BLE001 - warmup is an optimization; a bad
            # artifact must not take the server down (requests would just
            # pay first-call compiles, as without warmup)
            logger.exception("serve warmup failed for %s", collection_dir)

    thread = threading.Thread(target=warm, name="gordo-serve-warmup", daemon=True)
    thread.start()
    return thread


# -- process runner ---------------------------------------------------------


def build_gunicorn_cmd(
    host: str,
    port: int,
    workers: int,
    log_level: str,
    config_module: Optional[str] = None,
    worker_connections: Optional[int] = None,
    threads: Optional[int] = None,
    worker_class: str = "gthread",
    server_app: str = "gordo_tpu.server.app:build_app()",
) -> list:
    """The gunicorn argv the reference would exec (server.py:240-304)."""
    cmd = [
        "gunicorn",
        "--bind",
        f"{host}:{port}",
        "--log-level",
        log_level,
        "--error-logfile",
        "-",
        "--access-logfile",
        "-",
        "--worker-class",
        worker_class,
        "--worker-tmp-dir",
        "/dev/shm",
        "--workers",
        str(workers),
    ]
    if config_module is not None:
        cmd.extend(("--config", "python:" + config_module))
    if worker_class == "gthread":
        if threads is not None:
            cmd.extend(("--threads", str(threads)))
    else:
        if worker_connections is not None:
            cmd.extend(("--worker-connections", str(worker_connections)))
    cmd.append(server_app)
    return cmd


def run_cmd(cmd):
    """Run a shell command, surfacing stderr on stdout."""
    import subprocess

    subprocess.check_call(cmd, stderr=subprocess.STDOUT)


def run_server(
    host: str,
    port: int,
    workers: int,
    log_level: str,
    config_module: Optional[str] = None,
    worker_connections: Optional[int] = None,
    threads: Optional[int] = None,
    worker_class: str = "gthread",
    server_app: str = "gordo_tpu.server.app:build_app()",
):
    """
    Serve via gunicorn when installed (production parity with the
    reference); otherwise fall back to werkzeug's threaded server — models
    live on an accelerator, so thread workers sharing the one in-process
    JAX runtime is the natural single-host deployment anyway.
    """
    import shutil as _shutil

    if _shutil.which("gunicorn"):
        run_cmd(
            build_gunicorn_cmd(
                host=host,
                port=port,
                workers=workers,
                log_level=log_level,
                config_module=config_module,
                worker_connections=worker_connections,
                threads=threads,
                worker_class=worker_class,
                server_app=server_app,
            )
        )
        return

    logger.warning("gunicorn not found; serving with werkzeug (threaded)")
    from werkzeug.serving import make_server

    logging.getLogger().setLevel(log_level.upper())
    # make_server (not run_simple): the graceful-shutdown path needs the
    # server handle so SIGTERM can drain the micro-batcher queues and
    # in-flight batches BEFORE the accept loop stops — queued request
    # futures must resolve, not die with the process.
    app = build_app()
    server = make_server(host, port, app, threaded=True)
    install_graceful_shutdown(app, server)
    logger.info("serving on %s:%d (werkzeug threaded)", host, port)
    server.serve_forever()
