from .app import GordoServerApp, adapt_proxy_deployment, build_app, run_server

__all__ = ["GordoServerApp", "adapt_proxy_deployment", "build_app", "run_server"]
