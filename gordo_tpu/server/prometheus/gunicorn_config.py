"""
Gunicorn config hooks for multiprocess prometheus metrics (reference:
gordo/server/prometheus/gunicorn_config.py): dead workers' mmap'd metric
files must be cleaned up or the multiprocess registry grows forever.

Used via ``gunicorn --config python:gordo_tpu.server.prometheus.gunicorn_config``.
"""

from prometheus_client import multiprocess


def child_exit(server, worker):
    multiprocess.mark_process_dead(worker.pid)
