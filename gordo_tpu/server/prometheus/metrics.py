"""
Prometheus request metrics for the model server.

Reference parity: gordo/server/prometheus/metrics.py — request counter and
duration histogram labeled (method, path rule, status, gordo model name,
project, version), with multiprocess-registry support so gunicorn's worker
fleet aggregates into one scrape target.
"""

import logging
import os
import re
import weakref
from typing import Dict, Optional, Tuple

from prometheus_client import (
    REGISTRY,
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
)

import gordo_tpu

logger = logging.getLogger(__name__)

# Extract the model name from a request path under the API prefix:
# /gordo/v0/<project>/<name>/...
_MODEL_PATH_RE = re.compile(r"^/gordo/v0/(?P<project>[^/]+)/(?P<name>[^/]+)(?:/|$)")

# Routes that would only add scrape noise.
DEFAULT_IGNORE_PATHS = ("/healthcheck",)

PROJECT_LEVEL_ROUTES = (
    "models",
    "revisions",
    "expected-models",
    "build-status",
    "fleet-health",
    "slo",
)

#: request-stage latency buckets: stages span sub-millisecond metadata
#: lookups to second-scale inference+serialize on fat payloads — the
#: default request buckets start at 5ms and would flatten the fast half
_STAGE_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def _ensure_multiproc_dir() -> Optional[str]:
    """
    The configured ``PROMETHEUS_MULTIPROC_DIR`` (either env spelling),
    created if missing — prometheus_client crashes at first metric write
    when the mmap dir doesn't exist.
    """
    multiproc_dir = os.getenv("PROMETHEUS_MULTIPROC_DIR") or os.getenv(
        "prometheus_multiproc_dir"
    )
    if multiproc_dir:
        os.makedirs(multiproc_dir, exist_ok=True)
    return multiproc_dir


def multiprocess_registry() -> Optional[CollectorRegistry]:
    """
    A multiprocess collector registry when ``PROMETHEUS_MULTIPROC_DIR`` is
    configured (gunicorn worker fan-in), else None.
    """
    if _ensure_multiproc_dir():
        from prometheus_client import multiprocess

        registry = CollectorRegistry()
        multiprocess.MultiProcessCollector(registry)
        # Scrape-time collectors have no mmap backing, so the worker
        # fan-in alone would silently drop them: they must ride every
        # registry that answers scrapes.
        register_program_cache_collector(registry)
        register_fleet_console_collectors(registry)
        return registry
    return None


class GordoServerPrometheusMetrics:
    """The serving RED metric set, keyed by route/model/status:

    - **Rate** — ``gordo_server_requests_total`` (as before);
    - **Errors** — ``gordo_server_request_errors_total``, the explicit
      error counter (4xx = ``kind="client"``, 5xx = ``kind="server"``)
      so an error-rate panel is one PromQL ratio, no status-code regex;
    - **Duration** — the full-route latency histogram plus
      ``gordo_server_stage_duration_seconds{endpoint,stage}``: the same
      per-stage breakdown Server-Timing carries per response, as
      aggregable histograms — where the route's time goes, fleet-wide.
    """

    def __init__(
        self,
        project: Optional[str] = None,
        ignore_paths: Tuple[str, ...] = DEFAULT_IGNORE_PATHS,
        registry: Optional[CollectorRegistry] = None,
    ):
        _ensure_multiproc_dir()
        self.project = project
        self.ignore_paths = tuple(ignore_paths)
        self.registry = registry if registry is not None else REGISTRY

        label_names = ["method", "path", "status_code", "gordo_name", "project"]
        self.request_count = Counter(
            "gordo_server_requests_total",
            "Total number of requests to the gordo model server",
            labelnames=label_names,
            registry=self.registry,
        )
        self.request_duration = Histogram(
            "gordo_server_request_duration_seconds",
            "Request processing wall-time",
            labelnames=label_names,
            registry=self.registry,
        )
        self.error_count = Counter(
            "gordo_server_request_errors_total",
            "Requests answered with an error status (kind=client for "
            "4xx — including 429/504 batching backpressure — and "
            "kind=server for 5xx)",
            labelnames=label_names + ["kind"],
            registry=self.registry,
        )
        # stage labels are bounded: endpoint is the route map's endpoint
        # name, stage the handler-instrumented pipeline stage set
        # (model_resolve/data_decode/device_ingest/inference/
        # response_assemble/serialize + the micro-batcher's
        # queue_wait/batch_* intervals); data_decode is wire→host parse
        # only — the wire→device staging it used to hide is the
        # device_ingest stage
        self.stage_duration = Histogram(
            "gordo_server_stage_duration_seconds",
            "Per-request pipeline-stage wall-time (one observation per "
            "stage per request — the aggregable form of the "
            "Server-Timing response header)",
            labelnames=["project", "endpoint", "stage"],
            buckets=_STAGE_BUCKETS,
            registry=self.registry,
        )
        self.info = Gauge(
            "gordo_server_info",
            "Server build information",
            labelnames=["version", "project"],
            registry=self.registry,
            multiprocess_mode="max",
        )
        self.info.labels(
            version=gordo_tpu.__version__, project=project or ""
        ).set(1)
        # the fleet console's scrape-time aggregates (health states,
        # score histogram, device memory, compile-cache hit counters)
        # ride every scrape registry, batching on or off
        register_fleet_console_collectors(self.registry)
        # label-child caches: prometheus_client's .labels() rebuilds a
        # key tuple and takes the metric lock per call (~10us); on the
        # request hot path that is paid 2-7 times per request. Children
        # are stable objects — cache them per label tuple (bounded by
        # the same cardinality guards as the metrics themselves).
        self._request_children: dict = {}
        self._stage_children: dict = {}
        #: raw (method, path, status) -> computed labels dict; the two
        #: regex passes in _labels_uncached are ~6us per request and
        #: the distinct raw paths are bounded by models x routes
        self._labels_cache: dict = {}

    def _labels(self, request, response) -> Optional[dict]:
        key = (request.method, request.path, response.status_code)
        try:
            return self._labels_cache[key]
        except KeyError:
            labels = self._labels_uncached(request, response)
            if len(self._labels_cache) < 4096:
                self._labels_cache[key] = labels
            return labels

    def _labels_uncached(self, request, response) -> Optional[dict]:
        path = request.path
        if path in self.ignore_paths:
            return None
        gordo_name = ""
        project = self.project or ""
        match = _MODEL_PATH_RE.match(path)
        if match:
            project = project or match.group("project")
            name = match.group("name")
            if name not in PROJECT_LEVEL_ROUTES:
                gordo_name = name
                # Collapse the per-model path to its route shape so label
                # cardinality stays bounded by route count, not model count;
                # revision IDs are collapsed for the same reason.
                path = _MODEL_PATH_RE.sub("/gordo/v0/{project}/{name}/", path, count=1)
                path = re.sub(r"revision/\d+$", "revision/{revision}", path)
            else:
                path = _MODEL_PATH_RE.sub("/gordo/v0/{project}/" + name, path, count=1)
        elif path not in ("/healthcheck", "/server-version"):
            # Unmatched paths (scanners, typos) must not mint timeseries.
            path = "{unmatched}"
        return {
            "method": request.method,
            "path": path,
            "status_code": str(response.status_code),
            "gordo_name": gordo_name,
            "project": project,
        }

    def observe(self, request, response, duration_s: float):
        labels = self._labels(request, response)
        if labels is None:
            return
        key = (
            labels["method"],
            labels["path"],
            labels["status_code"],
            labels["gordo_name"],
            labels["project"],
        )
        children = self._request_children.get(key)
        if children is None:
            children = self._request_children[key] = (
                self.request_count.labels(**labels),
                self.request_duration.labels(**labels),
            )
        count_child, duration_child = children
        count_child.inc()
        duration_child.observe(duration_s)
        status = response.status_code
        if status >= 400:
            self.error_count.labels(
                **labels, kind="server" if status >= 500 else "client"
            ).inc()
        # per-stage durations ride the response object (_finalize stashes
        # them — the WSGI observer never sees the request context)
        stages = getattr(response, "gordo_stage_durations", None)
        if stages:
            endpoint = getattr(response, "gordo_endpoint", None) or "{unmatched}"
            for stage, seconds in stages.items():
                stage_key = (endpoint, stage)
                child = self._stage_children.get(stage_key)
                if child is None:
                    child = self._stage_children[stage_key] = (
                        self.stage_duration.labels(
                            project=labels["project"],
                            endpoint=endpoint,
                            stage=stage,
                        )
                    )
                child.observe(seconds)


def create_prometheus_metrics(
    project: Optional[str] = None, registry: Optional[CollectorRegistry] = None
) -> GordoServerPrometheusMetrics:
    if registry is None:
        registry = multiprocess_registry() or REGISTRY
    return GordoServerPrometheusMetrics(project=project, registry=registry)


#: (metric suffix, help) per fleet-build robustness counter — the
#: chip-fan-out analogs of the reference DAG's per-pod retry visibility
#: (a retried/failed pod shows in `argo get`; an in-process retry must
#: show in /metrics instead).
_BUILD_ROBUSTNESS_COUNTERS = (
    (
        "fleet_retries",
        "gordo_fleet_build_member_retries_total",
        "Diverged fleet members retrained with a reseeded RNG",
    ),
    (
        "bucket_bisects",
        "gordo_fleet_build_bucket_bisects_total",
        "Device-program bucket bisection (split-retry) events",
    ),
    (
        "data_fetch_retries",
        "gordo_fleet_build_data_fetch_retries_total",
        "Per-machine data fetch retry attempts",
    ),
    (
        "sequential_degraded",
        "gordo_fleet_build_sequential_degraded_total",
        "Machines degraded to the sequential builder after isolated "
        "device failures",
    ),
)

#: duration buckets for build phases — builds span sub-second host
#: phases to multi-minute device training, so the default request
#: buckets (capped at 10s) would flatten everything interesting
_PHASE_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
    120.0, 300.0, 600.0, 1800.0, 3600.0,
)
#: first-call durations span quick XLA compiles to compile+first-run of
#: multi-minute training programs — the tail must stay resolvable
_COMPILE_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0, 120.0, 300.0, 600.0, 1800.0, 3600.0,
)
#: final training losses of normalized autoencoder fleets
_LOSS_BUCKETS = (
    1e-4, 1e-3, 1e-2, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 100.0,
)

#: one metric set per LIVE CollectorRegistry. A WeakKeyDictionary, not a
#: dict keyed by ``id(registry)``: a garbage-collected registry can hand
#: its id to a NEW registry, which would then silently receive the old
#: (unregistered-with-it) metric objects — increments that no scrape of
#: the new registry ever sees. Weak keys die with their registry, so a
#: fresh registry always builds (and owns) fresh metrics.
_build_metrics: "weakref.WeakKeyDictionary[CollectorRegistry, dict]" = (
    weakref.WeakKeyDictionary()
)


def fleet_build_metrics(registry: Optional[CollectorRegistry] = None) -> dict:
    """The full fleet-build metric set for ``registry`` (default: the
    global REGISTRY), created once per live registry: the robustness
    Counters, the phase/compile duration and member-final-loss
    Histograms, and the live machine-progress Gauges."""
    target = registry if registry is not None else REGISTRY
    if target not in _build_metrics:
        _ensure_multiproc_dir()
        metrics = {
            counter_key: Counter(
                name,
                help_text,
                labelnames=["project"],
                registry=target,
            )
            for counter_key, name, help_text in _BUILD_ROBUSTNESS_COUNTERS
        }
        metrics["phase_duration"] = Histogram(
            "gordo_fleet_build_phase_duration_seconds",
            "Wall-clock of fleet build phases (per occurrence; phases "
            "like cv_train recur once per bucket chunk)",
            labelnames=["project", "phase"],
            buckets=_PHASE_BUCKETS,
            registry=target,
        )
        metrics["compile_duration"] = Histogram(
            "gordo_fleet_compile_duration_seconds",
            "FIRST-CALL wall-clock of fleet device programs per program "
            "and bucket shape: XLA trace+compile plus the first "
            "execution (they are not separable without an AOT split). "
            "The cache-miss signal is the DELTA vs later calls of the "
            "same signature in gordo_fleet_build_phase_duration_seconds "
            "/ the device_program run spans, not this value alone",
            labelnames=["project", "program", "shape"],
            buckets=_COMPILE_BUCKETS,
            registry=target,
        )
        metrics["member_final_loss"] = Histogram(
            "gordo_fleet_member_final_loss",
            "Final training loss of fleet members at the end of their "
            "final fit",
            labelnames=["project"],
            buckets=_LOSS_BUCKETS,
            registry=target,
        )
        for gauge_key, name, help_text in (
            (
                "machines_total",
                "gordo_fleet_build_machines_total",
                "Machines in the currently running fleet build",
            ),
            (
                "machines_completed",
                "gordo_fleet_build_machines_completed",
                "Machines whose artifacts have landed in the current "
                "fleet build (updated live, not only at build end)",
            ),
            (
                "machines_failed",
                "gordo_fleet_build_machines_failed",
                "Machines failed so far in the current fleet build",
            ),
        ):
            metrics[gauge_key] = Gauge(
                name,
                help_text,
                labelnames=["project"],
                registry=target,
                multiprocess_mode="max",
            )
        # FleetPlan (gordo_tpu.planner) gauges: what the cost model
        # promised for this build, and what the final fit actually cost —
        # the pair an operator (or a recalibration job) diffs to see the
        # model's error. `strategy` is bounded (naive|packed).
        for gauge_key, name, help_text in (
            (
                "plan_predicted_seconds",
                "gordo_fleet_plan_predicted_seconds",
                "FleetPlan predicted build wall-clock (compile + run) for "
                "the planned final-fit buckets",
            ),
            (
                "plan_padding_waste",
                "gordo_fleet_plan_padding_waste_ratio",
                "FleetPlan predicted padded-FLOP waste ratio (padding "
                "FLOPs / total padded FLOPs) across the planned buckets",
            ),
            (
                "plan_compiles",
                "gordo_fleet_plan_compiles",
                "Distinct XLA programs the FleetPlan predicts the planned "
                "buckets will compile",
            ),
            (
                "plan_actual_compiles",
                "gordo_fleet_plan_actual_compiles",
                "First-call (compile) fit programs actually observed "
                "during the final-fit phase of the build",
            ),
            (
                "plan_actual_seconds",
                "gordo_fleet_plan_actual_seconds",
                "Wall-clock of fit device programs actually observed "
                "during the final-fit phase of the build",
            ),
        ):
            metrics[gauge_key] = Gauge(
                name,
                help_text,
                labelnames=["project", "strategy"],
                registry=target,
                multiprocess_mode="max",
            )
        _build_metrics[target] = metrics
    return _build_metrics[target]


def fleet_build_robustness_counters(
    registry: Optional[CollectorRegistry] = None,
) -> dict:
    """The build-robustness Counter subset for ``registry`` (kept for
    callers that predate :func:`fleet_build_metrics`)."""
    metrics = fleet_build_metrics(registry)
    return {key: metrics[key] for key, _, _ in _BUILD_ROBUSTNESS_COUNTERS}


def record_fleet_build_robustness(project: Optional[str], counters: dict):
    """Export a finished build's robustness counters (FleetBuilder calls
    this best-effort at the end of ``build``)."""
    built = fleet_build_robustness_counters()
    for key, counter in built.items():
        value = int(counters.get(key, 0) or 0)
        if value:
            counter.labels(project=project or "").inc(value)


def record_fleet_build_phase(
    project: Optional[str], phase: str, seconds: float
):
    """One build-phase occurrence's wall-clock (live, per span)."""
    fleet_build_metrics()["phase_duration"].labels(
        project=project or "", phase=phase
    ).observe(seconds)


def record_fleet_compile(
    project: Optional[str], program: str, shape: str, seconds: float
):
    """One device program's first-call (compile) wall-clock. ``shape``
    is the bucket's stacked-array shape string — bounded by the fleet's
    distinct (architecture, padded-size) buckets, so label cardinality
    stays at bucket count, not machine count."""
    fleet_build_metrics()["compile_duration"].labels(
        project=project or "", program=program, shape=shape
    ).observe(seconds)


def record_member_final_loss(project: Optional[str], loss: float):
    """One fleet member's final training loss, at final-fit completion."""
    fleet_build_metrics()["member_final_loss"].labels(
        project=project or ""
    ).observe(loss)


# -- serving micro-batcher metrics ------------------------------------------

#: batch sizes are bounded by GORDO_TPU_BATCH_MAX_SIZE (default 32);
#: powers of two mirror the member shape ladder
_BATCH_SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)
#: ratios in [0, 1] (program occupancy / padding waste)
_RATIO_BUCKETS = (0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 0.95, 1.0)


class ProgramCacheCollector:
    """Scrape-time reader of the serving program cache
    (``fleet_store.program_cache_stats``): ``cache="programs"`` counts
    cached (spec, backend) jit entries, ``cache="signatures"`` the XLA
    executables compiled inside them — the number the serve shape
    ladder exists to bound."""

    def collect(self):
        from prometheus_client.core import GaugeMetricFamily

        from ..fleet_store import program_cache_stats

        stats = program_cache_stats()
        family = GaugeMetricFamily(
            "gordo_server_program_cache_size",
            "Compiled serving-program cache size (programs = cached jit "
            "entries per (spec, backend); signatures = XLA executables "
            "compiled inside them, -1 when the jax version hides the "
            "jit cache)",
            labels=["cache"],
        )
        family.add_metric(["programs"], stats["programs"])
        family.add_metric(["signatures"], stats["signatures"])
        # the precision axis (PR 14): programs per serving precision —
        # bounded by the declared precision ladder (f32/bf16/int8)
        for precision, count in sorted(
            (stats.get("by_precision") or {}).items()
        ):
            family.add_metric([f"programs_{precision}"], count)
        yield family


class StoreResidencyCollector:
    """Scrape-time reader of the serving store's resident-revision byte
    estimates (``FleetModelStore.revision_stats``). The ``revision``
    label is BOUNDED by ``N_CACHED_REVISIONS`` (default 2) — revision
    basenames, never member names, so cardinality stays at revision
    count (the PR 8 prometheus-cardinality contract); the ``kind`` axis
    is a three-value constant."""

    def collect(self):
        from prometheus_client.core import GaugeMetricFamily

        from ..fleet_store import STORE

        family = GaugeMetricFamily(
            "gordo_store_revision_bytes",
            "Estimated resident bytes per cached serving revision "
            "(kind=model per-member params, kind=stacked fused f32 "
            "buckets, kind=cast reduced-precision copies)",
            labels=["revision", "kind"],
        )
        for revision, stats in sorted(STORE.revision_stats().items()):
            family.add_metric([revision, "model"], stats["model_bytes"])
            family.add_metric([revision, "stacked"], stats["stacked_bytes"])
            family.add_metric([revision, "cast"], stats["cast_bytes"])
        yield family


#: registries already carrying a ProgramCacheCollector — re-registering
#: would raise on the duplicated metric name
_program_cache_registries: "weakref.WeakSet" = weakref.WeakSet()


def register_program_cache_collector(registry: CollectorRegistry) -> None:
    """Attach the scrape-time program-cache gauge to ``registry``, once.

    Unlike Counter/Histogram, a custom collector is not mmap-backed, so
    it must be registered on every registry that answers scrapes — the
    in-process one AND the fresh multiprocess fan-in registry (where the
    reported values are the answering worker's own cache)."""
    if registry in _program_cache_registries:
        return
    _program_cache_registries.add(registry)
    registry.register(ProgramCacheCollector())
    registry.register(StoreResidencyCollector())


class FleetHealthCollector:
    """Scrape-time BOUNDED aggregates of the per-member health ledger
    (``telemetry/fleet_health.py``): machines-by-state counts and the
    fixed-bucket health-score histogram. Per-machine detail deliberately
    never reaches a label — that is the ledger's job (the PR 8
    prometheus-cardinality contract); the label sets here are constants:
    four states, five score buckets."""

    def collect(self):
        from prometheus_client.core import (
            GaugeHistogramMetricFamily,
            GaugeMetricFamily,
        )

        from ...telemetry.fleet_health import SCORE_BUCKETS, ledger_summaries

        states = GaugeMetricFamily(
            "gordo_fleet_health_machines",
            "Fleet members by health state (quarantined > degraded > "
            "drifting > healthy; per-machine detail lives in "
            "fleet_health.json, not in labels)",
            labels=["state"],
        )
        scores = GaugeHistogramMetricFamily(
            "gordo_fleet_health_score",
            "Distribution of per-member health scores in [0, 1] "
            "(1.0 = healthy; see telemetry.fleet_health.health_score)",
            labels=[],
        )
        totals = {"healthy": 0, "degraded": 0, "drifting": 0, "quarantined": 0}
        bins = [0] * len(SCORE_BUCKETS)
        machines = 0
        score_sum = 0.0
        for summary in ledger_summaries().values():
            if not summary:
                continue
            machines += summary.get("machines", 0)
            for state in totals:
                totals[state] += int(summary.get(state, 0))
            histogram = summary.get("score_histogram") or {}
            counts = histogram.get("counts") or []
            for i, count in enumerate(counts[: len(bins)]):
                bins[i] += int(count)
            score_sum += float(histogram.get("score_sum") or 0.0)
        for state, count in totals.items():
            states.add_metric([state], count)
        cumulative = 0
        buckets = []
        for edge, count in zip(SCORE_BUCKETS, bins):
            cumulative += count
            buckets.append((str(edge), cumulative))
        buckets.append(("+Inf", machines))
        # gsum is the sum of SCORES (mean fleet health = sum / count in
        # one PromQL division), never the machine count
        scores.add_metric([], buckets=buckets, gsum_value=score_sum)
        yield states
        yield scores


class DeviceUtilizationCollector:
    """Scrape-time device telemetry (``telemetry/device.py``): measured
    HBM occupancy per backend (summed over local devices) and the
    process-wide compile-vs-cache-hit counters — the measured
    counterpart of the planner's predicted HBM numbers. All label sets
    are constants (three memory kinds, two sides, two results)."""

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        from ...telemetry import device as device_telemetry

        memory_family = GaugeMetricFamily(
            "gordo_device_memory_bytes",
            "Device memory summed over local devices "
            "(Device.memory_stats; absent when the backend reports none)",
            labels=["kind"],
        )
        memory = device_telemetry.memory_snapshot()
        if memory and memory.get("available"):
            for kind in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                if kind in memory:
                    memory_family.add_metric([kind], memory[kind])
            yield memory_family
        programs = CounterMetricFamily(
            "gordo_compile_cache_events",
            "jit-program executions by compile-cache outcome: "
            "result=compile is a cache miss that paid XLA, result=hit a "
            "steady-state run (side=build for fleet training programs, "
            "side=serve for the fused serving programs)",
            labels=["side", "result"],
        )
        for side, counters in sorted(
            device_telemetry.program_cache_counters().items()
        ):
            programs.add_metric([side, "compile"], counters.get("compiles", 0))
            programs.add_metric([side, "hit"], counters.get("cache_hits", 0))
        yield programs


#: numeric encoding of the alert state machine for the gauge below —
#: `resolved` maps to 0 (it is a closing annotation, not a page)
_SLO_ALERT_STATE_VALUES = {
    "inactive": 0,
    "resolved": 0,
    "pending": 1,
    "firing": 2,
}


class SloCollector:
    """Scrape-time SLO exposition (``telemetry/slo.py``): error-budget
    remaining, multi-window burn rates, and the alert state machine.
    Label cardinality is BOUNDED by the declared ``slos.toml`` — slo
    names and the two burn windows — never by traffic or fleet size
    (the PR 8 prometheus-cardinality contract). Watched directories
    re-evaluate at most once per ``GORDO_TPU_SLO_SCRAPE_REFRESH``."""

    def collect(self):
        from prometheus_client.core import GaugeMetricFamily

        from ...telemetry import slo as slo_engine

        budget = GaugeMetricFamily(
            "gordo_slo_error_budget_remaining_ratio",
            "Fraction of the SLO window's error budget still unspent "
            "(1.0 = clean, 0.0 = the objective is blown)",
            labels=["slo"],
        )
        burn = GaugeMetricFamily(
            "gordo_slo_burn_rate",
            "Error-budget burn rate over the alert windows (1.0 = "
            "spending exactly one budget per SLO window)",
            labels=["slo", "window"],
        )
        state = GaugeMetricFamily(
            "gordo_slo_alert_state",
            "Worst burn-rate alert state per SLO "
            "(0 = inactive/resolved, 1 = pending, 2 = firing)",
            labels=["slo"],
        )
        for doc in slo_engine.scrape_statuses().values():
            for slo in doc.get("slos") or []:
                name = str(slo.get("name"))
                budget.add_metric(
                    [name],
                    float((slo.get("budget") or {}).get("remaining_ratio", 1.0)),
                )
                for window, rate in (slo.get("burn_rates") or {}).items():
                    burn.add_metric([name, str(window)], float(rate))
            worst: Dict[str, int] = {}
            for alert in doc.get("alerts") or []:
                name = str(alert.get("slo"))
                value = _SLO_ALERT_STATE_VALUES.get(
                    str(alert.get("state")), 0
                )
                worst[name] = max(worst.get(name, 0), value)
            for name, value in worst.items():
                state.add_metric([name], value)
        yield budget
        yield burn
        yield state


class StreamPlaneCollector:
    """Scrape-time exposition of the streaming scoring plane
    (``gordo_tpu.stream``): session/subscriber/pending gauges, the
    row-accounting totals, and the flush-duration + ingest→scored
    score-lag fixed-bucket histograms from the process-global stream
    telemetry accumulator.

    Cardinality is BOUNDED by construction (the PR 8/9 contract): the
    only label sets are small constants — session states, row accounting
    scopes, event-drop scopes. Per-machine and per-stream detail NEVER
    reaches a label, however large the fleet grows; it lives on the
    ``/stream/status`` route and in the span trace instead."""

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeHistogramMetricFamily,
            GaugeMetricFamily,
        )

        from ... import stream as stream_plane

        sessions = GaugeMetricFamily(
            "gordo_stream_sessions",
            "Stream sessions by state (tombstoned = closed but retained "
            "for late cursors until the TTL)",
            labels=["state"],
        )
        subscribers = GaugeMetricFamily(
            "gordo_stream_subscribers",
            "Open SSE subscriptions across all stream sessions",
            labels=[],
        )
        pending = GaugeMetricFamily(
            "gordo_stream_pending_rows",
            "Rows buffered in the ingest rings awaiting the watermark, "
            "summed over sessions and machines",
            labels=[],
        )
        quarantined = GaugeMetricFamily(
            "gordo_stream_quarantined_machines",
            "Stream machines currently held by an open circuit breaker "
            "(their rows buffer instead of scoring)",
            labels=[],
        )
        rows = CounterMetricFamily(
            "gordo_stream_rows",
            "Streaming-plane row accounting by outcome (in/scored/"
            "failed/shed); in == scored + failed + pending + shed is "
            "the plane's zero-gap invariant",
            labels=["outcome"],
        )
        events_dropped = CounterMetricFamily(
            "gordo_stream_events_dropped",
            "Emitted events dropped by scope (outbox = slow-consumer "
            "ring eviction, emit = the emit fault site)",
            labels=["scope"],
        )
        flushes = CounterMetricFamily(
            "gordo_stream_flushes",
            "Watermark scoring flushes run by this process",
            labels=[],
        )
        flush_hist = GaugeHistogramMetricFamily(
            "gordo_stream_flush_duration_ms",
            "Wall milliseconds per watermark flush (cut + fused scoring "
            "+ event fan-out), fixed buckets",
            labels=[],
        )
        lag_hist = GaugeHistogramMetricFamily(
            "gordo_stream_score_lag_ms",
            "Ingest→scored lag in milliseconds, row-weighted (each "
            "flush contributes its scored rows at the span's oldest-row "
            "lag) — the freshness SLO's native distribution",
            labels=[],
        )

        plane = stream_plane.get_plane()
        active = tombstoned = subs = pending_rows = quarantine_count = 0
        dropped = {"outbox": 0, "emit": 0}
        if plane is not None:
            stats = plane.stats()
            for session in (stats.get("sessions") or {}).values():
                if session.get("closed"):
                    tombstoned += 1
                else:
                    active += 1
                subs += int(session.get("subscribers") or 0)
                dropped["outbox"] += int(
                    session.get("events_dropped_outbox") or 0
                )
                dropped["emit"] += int(
                    session.get("events_dropped_emit") or 0
                )
                for machine in (session.get("machines") or {}).values():
                    pending_rows += int(machine.get("rows_pending") or 0)
                    if machine.get("quarantined"):
                        quarantine_count += 1
        sessions.add_metric(["active"], active)
        sessions.add_metric(["tombstoned"], tombstoned)
        subscribers.add_metric([], subs)
        pending.add_metric([], pending_rows)
        quarantined.add_metric([], quarantine_count)
        for scope, count in dropped.items():
            events_dropped.add_metric([scope], count)

        telemetry = stream_plane.stream_telemetry().snapshot()
        rows.add_metric(["in"], telemetry["rows_in"])
        rows.add_metric(["scored"], telemetry["rows_scored"])
        rows.add_metric(["failed"], telemetry["rows_failed"])
        rows.add_metric(["shed"], telemetry["rows_shed"])
        flushes.add_metric([], telemetry["flushes"])
        for family, histogram in (
            (flush_hist, telemetry["flush_ms"]),
            (lag_hist, telemetry["lag_ms"]),
        ):
            cumulative = 0
            buckets = []
            counts = histogram.get("counts") or []
            for edge, count in zip(
                histogram.get("buckets_ms") or [], counts
            ):
                cumulative += int(count)
                buckets.append((str(edge), cumulative))
            buckets.append(("+Inf", int(histogram.get("count") or 0)))
            family.add_metric(
                [],
                buckets=buckets,
                gsum_value=float(histogram.get("sum_ms") or 0.0),
            )

        yield sessions
        yield subscribers
        yield pending
        yield quarantined
        yield rows
        yield events_dropped
        yield flushes
        yield flush_hist
        yield lag_hist


#: registries already carrying the fleet-console collectors (same
#: duplicate-registration guard as the program-cache WeakSet)
_fleet_console_registries: "weakref.WeakSet" = weakref.WeakSet()


def register_fleet_console_collectors(registry: CollectorRegistry) -> None:
    """Attach the fleet-health, device-utilization, SLO and stream-plane
    scrape collectors to ``registry``, once — on every registry that
    answers scrapes, like the program-cache collector (scrape-time
    collectors have no mmap backing to ride the multiprocess fan-in)."""
    if registry in _fleet_console_registries:
        return
    _fleet_console_registries.add(registry)
    registry.register(FleetHealthCollector())
    registry.register(DeviceUtilizationCollector())
    registry.register(SloCollector())
    registry.register(StreamPlaneCollector())


class ServeMetrics:
    """The micro-batching engine's metric set: queue depth, batch size /
    coalesce-ratio / padding-waste histograms, and the shed counter.
    Attached to a :class:`gordo_tpu.serve.ServeEngine` by ``build_app``;
    every method is safe to call from dispatcher threads."""

    def __init__(
        self,
        project: Optional[str] = None,
        registry: Optional[CollectorRegistry] = None,
    ):
        _ensure_multiproc_dir()
        self.project = project or ""
        self.registry = registry if registry is not None else REGISTRY
        labels = ["project"]
        self.queue_depth = Gauge(
            "gordo_server_batch_queue_depth",
            "Requests currently waiting in the micro-batch queue",
            labelnames=labels,
            registry=self.registry,
            multiprocess_mode="max",
        )
        self.batch_size = Histogram(
            "gordo_server_batch_size",
            "Requests coalesced into each fused device program",
            labelnames=labels,
            buckets=_BATCH_SIZE_BUCKETS,
            registry=self.registry,
        )
        self.coalesce_ratio = Histogram(
            "gordo_server_batch_coalesce_ratio",
            "Program occupancy: coalesced requests / padded member slots "
            "of the fused program (1.0 = a perfectly full batch)",
            labelnames=labels,
            buckets=_RATIO_BUCKETS,
            registry=self.registry,
        )
        self.padding_waste = Histogram(
            "gordo_server_batch_padding_waste",
            "Fraction of the fused program's padded (member x row) cells "
            "holding no request data",
            labelnames=labels,
            buckets=_RATIO_BUCKETS,
            registry=self.registry,
        )
        self.shed = Counter(
            "gordo_server_batch_shed_total",
            "Requests shed by serving admission control, by reason "
            "(queue_full -> 429, deadline -> 504, cancelled = waiter "
            "gave up before its batch ran, runner_error = the batcher's "
            "backstop resolved a crashed batch)",
            labelnames=labels + ["reason"],
            registry=self.registry,
        )
        # the serving circuit breakers (gordo_tpu.serve.breaker): the
        # `state` label is the breaker vocabulary (open / half_open /
        # closed) — bounded by construction
        self.breaker_transitions = Counter(
            "gordo_server_breaker_transitions_total",
            "Per-member serving circuit-breaker state transitions, by "
            "the state ENTERED (open = tripped into quarantine, "
            "half_open = probing, closed = recovered)",
            labelnames=labels + ["state"],
            registry=self.registry,
        )
        self.breaker_open = Gauge(
            "gordo_server_breaker_open_members",
            "Members currently quarantined by an open serving circuit "
            "breaker (answering 503 + Retry-After instead of riding "
            "batches)",
            labelnames=labels,
            registry=self.registry,
            multiprocess_mode="max",
        )
        register_program_cache_collector(self.registry)
        register_fleet_console_collectors(self.registry)

    def observe_batch(self, size: int, occupancy: float, padding_waste: float):
        self.batch_size.labels(project=self.project).observe(size)
        self.coalesce_ratio.labels(project=self.project).observe(occupancy)
        self.padding_waste.labels(project=self.project).observe(padding_waste)

    def observe_shed(self, reason: str, n: int = 1):
        self.shed.labels(project=self.project, reason=reason).inc(n)

    def observe_breaker(self, state: str):
        self.breaker_transitions.labels(
            project=self.project, state=state
        ).inc()

    def set_breaker_open(self, count: int):
        self.breaker_open.labels(project=self.project).set(count)

    def set_queue_depth(self, depth: int):
        self.queue_depth.labels(project=self.project).set(depth)

    def set_program_cache(self):
        # the gauge is a scrape-time collector; nothing to push
        pass


#: one ServeMetrics per LIVE registry (same WeakKey rationale as
#: ``_build_metrics`` above: a dead registry's id must never alias a new
#: registry into receiving unregistered metric objects)
_serve_metrics: "weakref.WeakKeyDictionary[CollectorRegistry, ServeMetrics]" = (
    weakref.WeakKeyDictionary()
)


def serve_metrics(
    project: Optional[str] = None,
    registry: Optional[CollectorRegistry] = None,
) -> ServeMetrics:
    """The serve metric set for ``registry`` (default: the global
    REGISTRY), created once per live registry."""
    target = registry if registry is not None else REGISTRY
    if target not in _serve_metrics:
        _serve_metrics[target] = ServeMetrics(project=project, registry=target)
    return _serve_metrics[target]


def set_fleet_plan_prediction(
    project: Optional[str],
    strategy: str,
    predicted_seconds: float,
    padding_waste: float,
    compiles: int,
):
    """Export a FleetPlan's headline predictions (at bucket-plan time)."""
    metrics = fleet_build_metrics()
    labels = {"project": project or "", "strategy": strategy}
    metrics["plan_predicted_seconds"].labels(**labels).set(predicted_seconds)
    metrics["plan_padding_waste"].labels(**labels).set(padding_waste)
    metrics["plan_compiles"].labels(**labels).set(compiles)


def set_fleet_plan_actuals(
    project: Optional[str], strategy: str, seconds: float, compiles: int
):
    """Export what the planned (final-fit) programs actually cost, so
    predicted-vs-actual is one PromQL subtraction."""
    metrics = fleet_build_metrics()
    labels = {"project": project or "", "strategy": strategy}
    metrics["plan_actual_seconds"].labels(**labels).set(seconds)
    metrics["plan_actual_compiles"].labels(**labels).set(compiles)


def set_fleet_build_progress(
    project: Optional[str], total: int, completed: int, failed: int
):
    """The live machine-progress gauges (the in-process analog of
    counting Succeeded/Failed pods in ``argo get``)."""
    metrics = fleet_build_metrics()
    labels = {"project": project or ""}
    metrics["machines_total"].labels(**labels).set(total)
    metrics["machines_completed"].labels(**labels).set(completed)
    metrics["machines_failed"].labels(**labels).set(failed)


# -- fleet lifecycle metrics --------------------------------------------------

#: one lifecycle metric set per LIVE registry (same WeakKey rationale as
#: ``_build_metrics``: id() reuse after GC must never resurrect stale
#: collector handles)
_lifecycle_metrics: "weakref.WeakKeyDictionary[CollectorRegistry, dict]" = (
    weakref.WeakKeyDictionary()
)

_LIFECYCLE_EVENT_COUNTERS = (
    (
        "rebuilds",
        "gordo_fleet_lifecycle_rebuilds_total",
        "Members rebuilt by the drift-triggered lifecycle loop",
    ),
    (
        "promotions",
        "gordo_fleet_lifecycle_promotions_total",
        "Canary revisions promoted into serving by the lifecycle loop",
    ),
    (
        "rollbacks",
        "gordo_fleet_lifecycle_rollbacks_total",
        "Canary revisions rolled back and quarantined (gate failures, "
        "failed rebuilds, operator rollbacks)",
    ),
)

#: hot swaps are sub-second by design; the tail buckets catch cold loads
_SWAP_BUCKETS = (0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0)


def fleet_lifecycle_metrics(
    registry: Optional[CollectorRegistry] = None,
) -> dict:
    """The ``gordo_fleet_lifecycle_*`` metric set for ``registry``
    (default: the global REGISTRY), created once per live registry:
    event Counters, the drift/canary status Gauges, and the hot-swap
    duration Histogram."""
    target = registry if registry is not None else REGISTRY
    if target not in _lifecycle_metrics:
        _ensure_multiproc_dir()
        metrics = {
            counter_key: Counter(
                name,
                help_text,
                labelnames=["project"],
                registry=target,
            )
            for counter_key, name, help_text in _LIFECYCLE_EVENT_COUNTERS
        }
        metrics["drifted"] = Gauge(
            "gordo_fleet_lifecycle_drifted_machines",
            "Machines whose latest drift evaluation tripped",
            labelnames=["project"],
            registry=target,
            multiprocess_mode="max",
        )
        metrics["stale"] = Gauge(
            "gordo_fleet_lifecycle_stale_machines",
            "Machines in the current stale set (being rebuilt/canaried)",
            labelnames=["project"],
            registry=target,
            multiprocess_mode="max",
        )
        metrics["canary_fraction"] = Gauge(
            "gordo_fleet_lifecycle_canary_fraction",
            "Traffic fraction currently routed to the canary revision "
            "(0 when no canary is serving)",
            labelnames=["project"],
            registry=target,
            multiprocess_mode="max",
        )
        metrics["swap_seconds"] = Histogram(
            "gordo_fleet_lifecycle_swap_seconds",
            "Wall-clock of promoting a canary into serving (the hot "
            "swap itself, warm included; requests are never paused)",
            labelnames=["project"],
            buckets=_SWAP_BUCKETS,
            registry=target,
        )
        _lifecycle_metrics[target] = metrics
    return _lifecycle_metrics[target]


def record_fleet_lifecycle_event(
    project: Optional[str], event: str, n: int = 1
):
    """Count one lifecycle event (``rebuilds``/``promotions``/
    ``rollbacks``); unknown event names are ignored (forward
    compatibility over crashes). The lookup is restricted to the
    counter keys — the metric dict also holds Gauges/Histograms, which
    must be neither inc'd nor crashed into."""
    if event not in {key for key, _, _ in _LIFECYCLE_EVENT_COUNTERS}:
        return
    if n:
        fleet_lifecycle_metrics()[event].labels(project=project or "").inc(n)


def set_fleet_lifecycle_status(
    project: Optional[str],
    drifted: int,
    stale: int,
    canary_fraction: float,
):
    """The lifecycle loop's live status gauges (per cycle)."""
    metrics = fleet_lifecycle_metrics()
    labels = {"project": project or ""}
    metrics["drifted"].labels(**labels).set(drifted)
    metrics["stale"].labels(**labels).set(stale)
    metrics["canary_fraction"].labels(**labels).set(canary_fraction)


def observe_lifecycle_swap(project: Optional[str], seconds: float):
    """One promotion hot-swap's wall-clock."""
    fleet_lifecycle_metrics()["swap_seconds"].labels(
        project=project or ""
    ).observe(seconds)
