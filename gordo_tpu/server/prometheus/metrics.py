"""
Prometheus request metrics for the model server.

Reference parity: gordo/server/prometheus/metrics.py — request counter and
duration histogram labeled (method, path rule, status, gordo model name,
project, version), with multiprocess-registry support so gunicorn's worker
fleet aggregates into one scrape target.
"""

import logging
import os
import re
from typing import Optional, Tuple

from prometheus_client import (
    REGISTRY,
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
)

import gordo_tpu

logger = logging.getLogger(__name__)

# Extract the model name from a request path under the API prefix:
# /gordo/v0/<project>/<name>/...
_MODEL_PATH_RE = re.compile(r"^/gordo/v0/(?P<project>[^/]+)/(?P<name>[^/]+)(?:/|$)")

# Routes that would only add scrape noise.
DEFAULT_IGNORE_PATHS = ("/healthcheck",)

PROJECT_LEVEL_ROUTES = ("models", "revisions", "expected-models")


def _ensure_multiproc_dir() -> Optional[str]:
    """
    The configured ``PROMETHEUS_MULTIPROC_DIR`` (either env spelling),
    created if missing — prometheus_client crashes at first metric write
    when the mmap dir doesn't exist.
    """
    multiproc_dir = os.getenv("PROMETHEUS_MULTIPROC_DIR") or os.getenv(
        "prometheus_multiproc_dir"
    )
    if multiproc_dir:
        os.makedirs(multiproc_dir, exist_ok=True)
    return multiproc_dir


def multiprocess_registry() -> Optional[CollectorRegistry]:
    """
    A multiprocess collector registry when ``PROMETHEUS_MULTIPROC_DIR`` is
    configured (gunicorn worker fan-in), else None.
    """
    if _ensure_multiproc_dir():
        from prometheus_client import multiprocess

        registry = CollectorRegistry()
        multiprocess.MultiProcessCollector(registry)
        return registry
    return None


class GordoServerPrometheusMetrics:
    """Request count + latency histogram keyed by route/model/status."""

    def __init__(
        self,
        project: Optional[str] = None,
        ignore_paths: Tuple[str, ...] = DEFAULT_IGNORE_PATHS,
        registry: Optional[CollectorRegistry] = None,
    ):
        _ensure_multiproc_dir()
        self.project = project
        self.ignore_paths = tuple(ignore_paths)
        self.registry = registry if registry is not None else REGISTRY

        label_names = ["method", "path", "status_code", "gordo_name", "project"]
        self.request_count = Counter(
            "gordo_server_requests_total",
            "Total number of requests to the gordo model server",
            labelnames=label_names,
            registry=self.registry,
        )
        self.request_duration = Histogram(
            "gordo_server_request_duration_seconds",
            "Request processing wall-time",
            labelnames=label_names,
            registry=self.registry,
        )
        self.info = Gauge(
            "gordo_server_info",
            "Server build information",
            labelnames=["version", "project"],
            registry=self.registry,
            multiprocess_mode="max",
        )
        self.info.labels(
            version=gordo_tpu.__version__, project=project or ""
        ).set(1)

    def _labels(self, request, response) -> Optional[dict]:
        path = request.path
        if path in self.ignore_paths:
            return None
        gordo_name = ""
        project = self.project or ""
        match = _MODEL_PATH_RE.match(path)
        if match:
            project = project or match.group("project")
            name = match.group("name")
            if name not in PROJECT_LEVEL_ROUTES:
                gordo_name = name
                # Collapse the per-model path to its route shape so label
                # cardinality stays bounded by route count, not model count;
                # revision IDs are collapsed for the same reason.
                path = _MODEL_PATH_RE.sub("/gordo/v0/{project}/{name}/", path, count=1)
                path = re.sub(r"revision/\d+$", "revision/{revision}", path)
            else:
                path = _MODEL_PATH_RE.sub("/gordo/v0/{project}/" + name, path, count=1)
        elif path not in ("/healthcheck", "/server-version"):
            # Unmatched paths (scanners, typos) must not mint timeseries.
            path = "{unmatched}"
        return {
            "method": request.method,
            "path": path,
            "status_code": str(response.status_code),
            "gordo_name": gordo_name,
            "project": project,
        }

    def observe(self, request, response, duration_s: float):
        labels = self._labels(request, response)
        if labels is None:
            return
        self.request_count.labels(**labels).inc()
        self.request_duration.labels(**labels).observe(duration_s)


def create_prometheus_metrics(
    project: Optional[str] = None, registry: Optional[CollectorRegistry] = None
) -> GordoServerPrometheusMetrics:
    if registry is None:
        registry = multiprocess_registry() or REGISTRY
    return GordoServerPrometheusMetrics(project=project, registry=registry)


#: (metric suffix, help) per fleet-build robustness counter — the
#: chip-fan-out analogs of the reference DAG's per-pod retry visibility
#: (a retried/failed pod shows in `argo get`; an in-process retry must
#: show in /metrics instead).
_BUILD_ROBUSTNESS_COUNTERS = (
    (
        "fleet_retries",
        "gordo_fleet_build_member_retries_total",
        "Diverged fleet members retrained with a reseeded RNG",
    ),
    (
        "bucket_bisects",
        "gordo_fleet_build_bucket_bisects_total",
        "Device-program bucket bisection (split-retry) events",
    ),
    (
        "data_fetch_retries",
        "gordo_fleet_build_data_fetch_retries_total",
        "Per-machine data fetch retry attempts",
    ),
    (
        "sequential_degraded",
        "gordo_fleet_build_sequential_degraded_total",
        "Machines degraded to the sequential builder after isolated "
        "device failures",
    ),
)

#: one Counter set per CollectorRegistry (a Counter name can only
#: register once per registry; a process typically only ever uses one)
_build_counters: dict = {}


def fleet_build_robustness_counters(
    registry: Optional[CollectorRegistry] = None,
) -> dict:
    """The build-robustness Counter set for ``registry`` (default: the
    global REGISTRY), created once per registry."""
    target = registry if registry is not None else REGISTRY
    key = id(target)
    if key not in _build_counters:
        _ensure_multiproc_dir()
        _build_counters[key] = {
            counter_key: Counter(
                name,
                help_text,
                labelnames=["project"],
                registry=target,
            )
            for counter_key, name, help_text in _BUILD_ROBUSTNESS_COUNTERS
        }
    return _build_counters[key]


def record_fleet_build_robustness(project: Optional[str], counters: dict):
    """Export a finished build's robustness counters (FleetBuilder calls
    this best-effort at the end of ``build``)."""
    built = fleet_build_robustness_counters()
    for key, counter in built.items():
        value = int(counters.get(key, 0) or 0)
        if value:
            counter.labels(project=project or "").inc(value)
