from .metrics import GordoServerPrometheusMetrics, create_prometheus_metrics

__all__ = ["GordoServerPrometheusMetrics", "create_prometheus_metrics"]
