"""
A tiny standalone ``/metrics`` WSGI app, mountable as a second server the
way the reference mounts its metrics Flask app beside the model server
(gordo/server/prometheus/server.py).
"""

from typing import Optional

from prometheus_client import REGISTRY, CollectorRegistry, generate_latest
from werkzeug.wrappers import Request, Response

from .metrics import multiprocess_registry, register_program_cache_collector


def build_metrics_app(registry: Optional[CollectorRegistry] = None):
    """WSGI app answering Prometheus scrapes at ``/metrics`` (and ``/``)."""
    if registry is None:
        registry = multiprocess_registry() or REGISTRY
    # scrape-time collector: not mmap-backed, must ride THIS registry
    register_program_cache_collector(registry)

    def app(environ, start_response):
        request = Request(environ)
        if request.path.rstrip("/") in ("", "/metrics"):
            response = Response(generate_latest(registry), mimetype="text/plain")
        else:
            response = Response("Not Found", status=404)
        return response(environ, start_response)

    return app
