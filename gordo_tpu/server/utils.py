"""
Server-side IO and caching helpers.

Reference parity: gordo/server/utils.py — parquet⇄DataFrame (pyarrow),
MultiIndex-DataFrame⇄nested-dict JSON form, input verification against the
model's tags, zlib-compressed metadata caching (``N_CACHED_METADATA``,
default 250), revision deletion, and name/revision validation regexes.

Engine difference from the reference's model cache: models are served from
the fleet-resident store (``fleet_store.py`` — load-once per revision,
device-resident params, ``N_CACHED_REVISIONS`` bounds revision count)
instead of an LRU(2) of unpickles per request.

Engine difference: no Flask — these helpers are plain functions operating on
an explicit :class:`gordo_tpu.server.app.RequestContext` instead of
``flask.g``.
"""

import io
import logging
import os
import pickle
import re
import shutil
import timeit
import zlib
from datetime import datetime
from functools import lru_cache
from typing import List, Optional

import dateutil.parser
import numpy as np
import pandas as pd

try:  # pyarrow is an optional extra: JSON serving works without it,
    # parquet/Arrow wire formats negotiate themselves away (406/415)
    import pyarrow as pa
    import pyarrow.parquet as pq
except ImportError:  # pragma: no cover - exercised via monkeypatch
    pa = None
    pq = None

from .. import serializer

logger = logging.getLogger(__name__)

gordo_name_re = re.compile(r"^[a-zA-Z\d-]+")
revision_re = re.compile(r"^\d+$")


class ServerError(Exception):
    """An error carrying an HTTP status and a JSON payload."""

    def __init__(self, message: str, status: int = 400, key: str = "message"):
        super().__init__(message)
        self.status = status
        self.payload = {key: message}


def validate_revision(revision: str) -> bool:
    return bool(revision_re.match(revision))


def validate_gordo_name(gordo_name: str):
    """Model names are alpha-numeric + dashes (reference utils.py:425-430)."""
    if gordo_name and not gordo_name_re.match(gordo_name):
        raise ServerError("gordo_name field has wrong format", status=422)


# -- parquet / JSON dataframe wire formats ---------------------------------


def _require_parquet():
    if pa is None:
        raise ServerError(
            "Parquet wire format unavailable (pyarrow not installed); "
            "use JSON",
            status=415,
        )


def dataframe_into_parquet_bytes(df: pd.DataFrame, compression: str = "snappy") -> bytes:
    """Serialize a DataFrame to parquet bytes (the binary wire format)."""
    _require_parquet()
    table = pa.Table.from_pandas(df)
    buf = pa.BufferOutputStream()
    pq.write_table(table, buf, compression=compression)
    return buf.getvalue().to_pybytes()


def dataframe_from_parquet_bytes(buf: bytes) -> pd.DataFrame:
    """Inverse of :func:`dataframe_into_parquet_bytes`."""
    _require_parquet()
    return pq.read_table(io.BytesIO(buf)).to_pandas()


def index_wire_keys(index: pd.Index) -> List[str]:
    """
    THE wire format for response index keys, shared by every route.
    ``astype(str)`` matches the reference (utils.py:129-131): an
    all-midnight DatetimeIndex serializes date-only ('2019-01-01'), and
    clients round-trip it through ``dataframe_from_dict``'s ISO parse.
    """
    if isinstance(index, pd.DatetimeIndex):
        return index.astype(str).tolist()
    return [str(v) for v in index]


def dataframe_to_dict(df: pd.DataFrame) -> dict:
    """
    A (possibly MultiIndex-columned) DataFrame as a JSON-serializable nested
    dict: top-level column name → {sub-column → {index → value}}.

    >>> import numpy as np
    >>> columns = pd.MultiIndex.from_tuples(
    ...     (f"feature{i}", f"sub-feature-{ii}") for i in range(2) for ii in range(2))
    >>> index = pd.date_range('2019-01-01', '2019-02-01', periods=2)
    >>> df = pd.DataFrame(np.arange(8).reshape((2, 4)), columns=columns, index=index)
    >>> serialized = dataframe_to_dict(df)
    >>> serialized["feature0"]["sub-feature-0"]
    {'2019-01-01': 0, '2019-02-01': 4}
    """
    if not df.columns.is_unique:
        # duplicate labels: keep pandas' warn-and-omit to_dict semantics
        data = df.copy()
        if isinstance(data.index, pd.DatetimeIndex):
            data.index = index_wire_keys(data.index)
        if isinstance(df.columns, pd.MultiIndex):
            return {
                col: (
                    data[col].to_dict()
                    if isinstance(data[col], pd.DataFrame)
                    else pd.DataFrame(data[col]).to_dict()
                )
                for col in data.columns.get_level_values(0)
            }
        return data.to_dict()

    # direct dict assembly (no intermediate frames/copies): typed columns
    # yield the exact value types pandas to_dict produced (Timestamps for
    # datetimes, python ints/floats for numerics), object columns box
    # numpy scalars like to_dict's maybe_box_native did, and the key list
    # is built once instead of once per column — this serializer is half
    # the anomaly route's host time at reference payload sizes
    def box_native(v):
        # .item() on ns-precision datetime64/timedelta64 yields raw
        # nanosecond ints — box those like pandas' maybe_box_native does
        if isinstance(v, np.datetime64):
            return pd.Timestamp(v)
        if isinstance(v, np.timedelta64):
            return pd.Timedelta(v)
        return v.item() if isinstance(v, np.generic) else v

    def column_values(series: pd.Series) -> list:
        if series.dtype == object:
            return [box_native(v) for v in series]
        return series.tolist()

    keys = (
        index_wire_keys(df.index)
        if isinstance(df.index, pd.DatetimeIndex)
        else df.index.tolist()
    )
    if isinstance(df.columns, pd.MultiIndex):
        out: dict = {}
        for top in df.columns.get_level_values(0).unique():
            sub = df[top]
            if isinstance(sub, pd.Series):
                sub = sub.to_frame()
            out[top] = {
                c: dict(zip(keys, column_values(sub[c]))) for c in sub.columns
            }
        return out
    return {c: dict(zip(keys, column_values(df[c]))) for c in df.columns}


def dataframe_from_dict(data: dict) -> pd.DataFrame:
    """
    Inverse of :func:`dataframe_to_dict`; index is parsed as ISO datetimes,
    falling back to integers, and sorted.

    >>> serialized = {
    ...     'feature0': {'sub-feature-0': {'2019-01-01': 0, '2019-02-01': 4},
    ...                  'sub-feature-1': {'2019-01-01': 1, '2019-02-01': 5}},
    ...     'feature1': {'sub-feature-0': {'2019-01-01': 2, '2019-02-01': 6},
    ...                  'sub-feature-1': {'2019-01-01': 3, '2019-02-01': 7}}}
    >>> df = dataframe_from_dict(serialized)
    >>> df.shape
    (2, 4)
    """
    if isinstance(data, dict) and any(isinstance(val, dict) for val in data.values()):
        try:
            keys = data.keys()
            df: pd.DataFrame = pd.concat(
                (pd.DataFrame.from_dict(data[key]) for key in keys), axis=1, keys=keys
            )
        except (ValueError, AttributeError):
            df = pd.DataFrame.from_dict(data)
    else:
        df = pd.DataFrame.from_dict(data)

    try:
        # vectorized ISO8601 parse — the per-element dateutil map was the
        # fleet route's top host cost at 100 machines/request
        df.index = pd.to_datetime(df.index, format="ISO8601")
    except (TypeError, ValueError):
        try:
            df.index = df.index.map(dateutil.parser.isoparse)
        except (TypeError, ValueError):
            df.index = df.index.map(int)
    df.sort_index(inplace=True)
    return df


def parse_iso_datetime(datetime_str: str) -> datetime:
    parsed_date = dateutil.parser.isoparse(datetime_str)
    if parsed_date.tzinfo is None:
        raise ValueError(
            f"Provide timezone to timestamp {datetime_str}."
            f" Example: for UTC timezone use {datetime_str + 'Z'} or "
            f"{datetime_str + '+00:00'} "
        )
    return parsed_date


def verify_dataframe(df: pd.DataFrame, expected_columns: List[str]) -> pd.DataFrame:
    """
    Check/normalize client-provided input columns against the model's tags
    (reference utils.py:208-253): unlabeled arrays of the right width get
    the expected names; labeled frames are column-selected (order + extras);
    anything else raises a 400 :class:`ServerError`.
    """
    if isinstance(df.columns, pd.MultiIndex):
        raise ServerError(
            "Server does not support multi-level dataframes at this time: "
            f"{df.columns.tolist()}",
            status=400,
        )
    if not all(col in df.columns for col in expected_columns):
        if len(df.columns) != len(expected_columns):
            raise ServerError(
                f"Unexpected features: was expecting {expected_columns} "
                f"length of {len(expected_columns)}, but got "
                f"{df.columns} length of {len(df.columns)}",
                status=400,
            )
        df.columns = expected_columns
        return df
    return df[expected_columns]


def frame_from_columns(
    resolution,
    columns,
    index,
    expected: List[str],
) -> pd.DataFrame:
    """A verified model-input frame out of decoded Arrow columns, with
    ``verify_dataframe``'s alignment semantics (expected order selected,
    extras dropped, full-width positional rename, otherwise 400) — but
    the selection plan is computed once per (revision, column-set) and
    cached on the fleet's resolution object, so a steady client's
    requests pay a tuple-keyed dict probe, not set algebra."""
    from .wire.arrow_codec import columns_to_frame

    names = tuple(columns)
    expected_t = tuple(expected)
    order = resolution.alignment(names, expected_t) if resolution else None
    if order is None:
        if all(name in columns for name in expected_t):
            order = expected_t
        elif len(names) == len(expected_t):
            # full-width positional rename, like verify_dataframe's
            # unlabeled-array branch
            order = names
        else:
            raise ServerError(
                f"Unexpected features: was expecting {list(expected_t)} "
                f"length of {len(expected_t)}, but got "
                f"{list(names)} length of {len(names)}",
                status=400,
            )
        if resolution is not None:
            resolution.remember_alignment(names, expected_t, order)
    frame = columns_to_frame(columns, index, list(order))
    if tuple(order) != expected_t:
        # positional branch: client names differ but width matches —
        # adopt the model's tag names, like verify_dataframe
        frame.columns = list(expected_t)
    return frame


def _extract_arrow(ctx) -> None:
    """Arrow-IPC request body → ``ctx.X``/``ctx.y`` — the zero-copy
    decode path: columns come off the received buffer as numpy views and
    one ``column_stack`` builds the model-input frame (no JSON parse, no
    per-cell dict walk)."""
    from .properties import get_tags, get_target_tags
    from .wire.arrow_codec import ArrowDecodeError, decode_frames

    try:
        x_columns, y_columns, index = decode_frames(ctx.request.get_data())
    except ArrowDecodeError as exc:
        raise ServerError(str(exc), status=400)
    resolution = getattr(ctx, "resolution", None)
    expected_x = [t.name for t in get_tags(ctx)]
    try:
        ctx.X = frame_from_columns(resolution, x_columns, index, expected_x)
        if y_columns:
            expected_y = [t.name for t in get_target_tags(ctx)]
            ctx.y = frame_from_columns(
                resolution, y_columns, index, expected_y
            )
        else:
            ctx.y = None
    except ServerError:
        raise
    except (ValueError, TypeError) as exc:
        raise ServerError(f"Invalid Arrow body: {exc}", status=400)
    _stash_raw_columns(ctx, x_columns, index)


def _stash_raw_columns(ctx, x_columns, index) -> None:
    """Keep the decoded X column views beside the assembled frame
    (``ctx.ingest``) so the device-resident ingest path can dlpack them
    straight to the device, skipping the ``column_stack`` staging copy.
    Only when the stash would match the frame row-for-row: a
    non-monotonic index means ``columns_to_frame`` re-sorted rows, and a
    positional rename means ``ctx.X.columns`` no longer key into the
    wire columns — both fall back to the frame path (skipping the stash
    is always correct, never wrong)."""
    from ..ingest import RawColumns

    if index is not None and not index.is_monotonic_increasing:
        return
    try:
        columns = [np.asarray(x_columns[name]) for name in ctx.X.columns]
    except KeyError:
        return
    if columns and all(c.ndim == 1 for c in columns):
        ctx.ingest = RawColumns.from_columns(columns)


def extract_X_y(ctx) -> None:
    """
    Pull ``X`` (and optionally ``y``) out of a POST request — a JSON
    body ``{"X": {...}, "y": {...}}``, multipart parquet files, a raw
    ``application/x-parquet`` body, or a columnar Arrow-IPC stream
    (``Content-Type: application/vnd.apache.arrow.stream`` — see
    ``docs/serving.md``) — verify them against the model's tags, and
    stash them on the context (reference utils.py:256-331).
    """
    from .properties import get_tags, get_target_tags
    from .wire import negotiate

    request = ctx.request
    start_time = timeit.default_timer()
    if request.method != "POST":
        raise ServerError(f"Cannot extract X and y from '{request.method}' request.")

    body_format = negotiate.request_format(request)
    if body_format == negotiate.ARROW:
        _extract_arrow(ctx)
        logger.debug(
            "Arrow decode: X %s rows; parse time %.4fs",
            len(ctx.X),
            timeit.default_timer() - start_time,
        )
        return
    if body_format == negotiate.PARQUET:
        # raw-body parquet carries X only (y rides the multipart form or
        # the Arrow stream's role-tagged columns)
        X = dataframe_from_parquet_bytes(request.get_data())
        X = verify_dataframe(X, [t.name for t in get_tags(ctx)])
        ctx.X, ctx.y = X, None
        return

    if request.is_json:
        body = request.get_json(silent=True) or {}
        if "X" not in body:
            raise ServerError('Cannot predict without "X"')
        X = dataframe_from_dict(body["X"])
        y = body.get("y")
        if y is not None:
            y = dataframe_from_dict(y)
    else:
        if "X" not in request.files:
            raise ServerError('Cannot predict without "X"')
        X = dataframe_from_parquet_bytes(request.files["X"].read())
        y = request.files.get("y")
        if y is not None:
            y = dataframe_from_parquet_bytes(y.read())

    X = verify_dataframe(X, [t.name for t in get_tags(ctx)])
    if y is not None:
        y = verify_dataframe(y, [t.name for t in get_target_tags(ctx)])

    ctx.X, ctx.y = X, y
    logger.debug(
        "Size of X: %s, size of y: %s; parse time %.4fs",
        X.size,
        getattr(y, "size", None),
        timeit.default_timer() - start_time,
    )


# -- model / metadata caches -----------------------------------------------


def load_model(directory: str, name: str):
    """
    A served model, from the fleet-resident store: loaded once per
    revision, JAX parameters kept on device, never evicted model-by-model.
    Replaces the reference's LRU(2)-of-pickles (utils.py:334-353), which
    reloads from disk on nearly every request once >2 models are in play.
    """
    from .fleet_store import STORE

    start_time = timeit.default_timer()
    model = STORE.get_model(directory, name)
    logger.debug("Time to load model: %.4fs", timeit.default_timer() - start_time)
    return model


_n_cached_metadata = int(os.getenv("N_CACHED_METADATA", 250))


@lru_cache(maxsize=_n_cached_metadata)
def _load_compressed_metadata(directory: str, name: str) -> bytes:
    """
    Metadata cached as zlib-compressed pickle — the reference measured ~4kb
    compressed vs 37kb live (utils.py:385-401), and with 250 entries cached
    the compression is what makes the cache affordable.
    """
    metadata = serializer.load_metadata(os.path.join(directory, name))
    return zlib.compress(pickle.dumps(metadata))


def load_metadata(directory: str, name: str) -> dict:
    return pickle.loads(zlib.decompress(_load_compressed_metadata(directory, name)))


@lru_cache(maxsize=_n_cached_metadata)
def load_info(directory: str, name: str) -> dict:
    return serializer.load_info(os.path.join(directory, name))


def metadata_file_path(directory: str, name: str) -> Optional[str]:
    """
    Where this model's ``metadata.json`` lives — beside the model or one
    directory up — or None. Existence must be re-checked on every request
    even on cache hits: the DELETE endpoint removes revisions out from under
    the LRU caches (reference utils.py:356-363).
    """
    model_dir = os.path.join(directory, name)
    for candidate_dir in (model_dir, directory):
        candidate = os.path.join(candidate_dir, serializer.METADATA_FILE)
        if os.path.isfile(candidate):
            return candidate
    return None


def check_metadata_file(directory: str, name: str):
    if metadata_file_path(directory, name) is None:
        raise FileNotFoundError("Unable to load metadata.json file")


def delete_revision(directory: str, name: str):
    """
    Delete one model from a revision directory, and the revision directory
    itself once empty (reference utils.py:404-422).
    """
    from .fleet_store import STORE

    full_path = os.path.join(directory, name)
    if not os.path.isfile(os.path.join(full_path, serializer.METADATA_FILE)):
        raise ServerError("Not found", status=404)
    shutil.rmtree(full_path, ignore_errors=True)
    STORE.invalidate(directory)
    if os.path.exists(full_path):
        raise ServerError("Unable to delete this model revision folder", status=500)
    # The builder's crash-safety droppings — the build journal, its
    # flush temp files, and orphaned `.<name>.tmp-*` staging dirs — are
    # not models: a revision holding only those is empty and must still
    # be reclaimed (journal and all).
    from ..serializer.serializer import is_builder_dropping

    leftovers = [
        entry for entry in os.listdir(directory) if not is_builder_dropping(entry)
    ]
    if not leftovers:
        shutil.rmtree(directory, ignore_errors=True)
        if os.path.exists(directory):
            raise ServerError("Unable to delete this revision folder", status=500)


def resolve_model(ctx, gordo_name: str):
    """The scoring routes' model_resolve: load model + metadata + tag
    lists onto the context through the fleet's per-revision
    :class:`~.fleet_store.ModelResolution` cache — a request pays dict
    probes plus one ``metadata.json`` existence re-check (the DELETE
    staleness contract), not a zlib+pickle metadata round-trip. 404 on
    miss, like :func:`require_model`."""
    from .fleet_store import STORE

    validate_gordo_name(gordo_name)
    try:
        check_metadata_file(ctx.collection_dir, gordo_name)
        resolution = STORE.fleet(ctx.collection_dir).resolution(gordo_name)
    except FileNotFoundError:
        raise ServerError(f"No such model found: '{gordo_name}'", status=404)
    ctx.resolution = resolution
    ctx.model = resolution.model
    ctx.metadata = resolution.metadata
    ctx.info = resolution.info


def require_model(ctx, gordo_name: str):
    """Load model + metadata onto the context, 404 on miss."""
    validate_gordo_name(gordo_name)
    try:
        check_metadata_file(ctx.collection_dir, gordo_name)
        ctx.model = load_model(ctx.collection_dir, gordo_name)
    except FileNotFoundError:
        raise ServerError(f"No such model found: '{gordo_name}'", status=404)
    require_metadata(ctx, gordo_name)


def require_metadata(ctx, gordo_name: str):
    """Load metadata (+ info when present) onto the context, 404 on miss."""
    validate_gordo_name(gordo_name)
    ctx.info = {}
    try:
        ctx.info = load_info(ctx.collection_dir, gordo_name)
    except FileNotFoundError:
        pass
    try:
        check_metadata_file(ctx.collection_dir, gordo_name)
        ctx.metadata = load_metadata(ctx.collection_dir, gordo_name)
    except FileNotFoundError:
        raise ServerError(f"No metadata found for '{gordo_name}'", status=404)
