"""
Content negotiation for the prediction/anomaly/fleet wire formats.

Requests pick their body codec with ``Content-Type`` and their response
codec with ``Accept``; JSON stays the default on both sides, so every
existing client keeps working byte-for-byte. Rules (documented in
``docs/serving.md``):

- Response: explicit ``?format=parquet`` wins (legacy contract); then
  the highest-quality acceptable type among Arrow / parquet / JSON,
  with JSON winning ties and every wildcard (``*/*``,
  ``application/*``) counting as JSON. An ``Accept`` header that admits
  none of the three answers 406. A client that accepts Arrow *and*
  JSON degrades gracefully to JSON when pyarrow is unavailable; one
  that accepts ONLY Arrow gets the 406.
- Request: ``Content-Type: application/vnd.apache.arrow.stream`` and
  raw-body ``application/x-parquet`` are decoded columnar; JSON and
  multipart parquet take the legacy decoders. An Arrow body on a
  pyarrow-less server answers 415 (the capability is absent, not the
  request malformed — malformed bodies answer 400).
"""

from typing import Tuple

from .arrow_codec import ARROW_CONTENT_TYPE, arrow_enabled

JSON_CONTENT_TYPE = "application/json"
PARQUET_CONTENT_TYPE = "application/x-parquet"

#: formats the serialize stage understands
JSON, ARROW, PARQUET = "json", "arrow", "parquet"


def _accept_qualities(request) -> Tuple[float, float, float]:
    """(json_q, arrow_q, parquet_q) from the Accept header; wildcards
    count toward JSON (the default representation)."""
    json_q = arrow_q = parquet_q = 0.0
    for value, quality in request.accept_mimetypes:
        mime = value.lower()
        if mime in (JSON_CONTENT_TYPE, "application/*", "*/*"):
            json_q = max(json_q, quality)
        elif mime == ARROW_CONTENT_TYPE:
            arrow_q = max(arrow_q, quality)
        elif mime == PARQUET_CONTENT_TYPE:
            parquet_q = max(parquet_q, quality)
    return json_q, arrow_q, parquet_q


def response_format(request) -> str:
    """The negotiated response codec (``json``/``arrow``/``parquet``).

    Raises :class:`~..utils.ServerError` with status 406 when the
    client's ``Accept`` admits none of the served representations.
    """
    from .. import utils as server_utils

    if request.args.get("format") == "parquet":
        return PARQUET
    accept = request.headers.get("Accept")
    if not accept:
        return JSON
    json_q, arrow_q, parquet_q = _accept_qualities(request)
    if arrow_q > 0 and not arrow_enabled():
        if json_q <= 0 and parquet_q <= 0:
            raise server_utils.ServerError(
                "Arrow responses unavailable (pyarrow not installed); "
                "accept application/json instead",
                status=406,
            )
        arrow_q = 0.0
    if json_q <= 0 and arrow_q <= 0 and parquet_q <= 0:
        raise server_utils.ServerError(
            "Not acceptable: this route serves application/json, "
            f"{ARROW_CONTENT_TYPE} or {PARQUET_CONTENT_TYPE}",
            status=406,
        )
    # highest quality wins; JSON wins ties (default representation),
    # Arrow beats parquet on their tie (it is the cheaper encode)
    if arrow_q > json_q and arrow_q >= parquet_q:
        return ARROW
    if parquet_q > json_q:
        return PARQUET
    return JSON


def request_format(request) -> str:
    """The request-body codec this Content-Type selects: ``arrow`` /
    ``parquet`` (raw body) / ``legacy`` (JSON body or multipart parquet
    files — the pre-columnar decoders own those, including their error
    contract).

    Raises a 415 :class:`~..utils.ServerError` for an Arrow body when
    the Arrow codec is unavailable.
    """
    from .. import utils as server_utils

    mimetype = (request.mimetype or "").lower()
    if mimetype == ARROW_CONTENT_TYPE:
        if not arrow_enabled():
            raise server_utils.ServerError(
                "Arrow request bodies unsupported (pyarrow not "
                "installed); send application/json",
                status=415,
            )
        return ARROW
    if mimetype == PARQUET_CONTENT_TYPE:
        return PARQUET
    return "legacy"
