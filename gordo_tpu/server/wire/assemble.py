"""
Vectorized response assembly: the columnar replacement for the
``make_base_dataframe`` → ``dataframe_to_dict`` pandas round-trip.

Every function here composes a :class:`~.columns.WireTable` whose values
are numerically IDENTICAL — to the float bit — to what the legacy pandas
path produced, in the same column order, so the JSON wire bytes don't
change when the fast path is on (pinned by
``tests/server/test_wire_parity.py``). That means the numpy mirrors
below replicate the legacy dtype flow exactly, quirks included: e.g.
``MinMaxScaler.transform`` scales IN PLACE on the input's float dtype, so
a float32 reconstruction is scaled with float32 rounding before the
float64 subtraction — ``_scaler_transform`` reproduces that rather than
"fixing" it.

Layering: this module may import models' utility types but never the
server views (enforced by the ``gordo-tpu lint`` layering arrow).
"""

import logging
from typing import Any, List, Optional, Sequence

import numpy as np
import pandas as pd

from .columns import WireColumn, WireTable

logger = logging.getLogger(__name__)

#: sklearn's FLOAT_DTYPES: check_array preserves these, converts the rest
_FLOAT_DTYPES = (np.float64, np.float32, np.float16)


def _tag_names(tags: Sequence[Any]) -> List[str]:
    """Mirror of ``models.utils._tag_names``: SensorTag → .name, anything
    else → str."""
    return [getattr(tag, "name", None) or str(tag) for tag in tags]


def _scaler_transform(scaler: Any, values: np.ndarray) -> np.ndarray:
    """``scaler.transform`` bit-for-bit, without the sklearn feature-name
    bookkeeping. MinMaxScaler's transform is ``check_array(copy=True)``
    then in-place ``X *= scale_; X += min_`` — replicated here so the
    dtype (and therefore rounding) of the result matches the legacy
    DataFrame path exactly. Non-MinMax scalers fall back to the real
    ``transform`` on the raw ndarray."""
    from sklearn.preprocessing import MinMaxScaler

    if type(scaler) is MinMaxScaler:
        dtype = values.dtype if values.dtype in _FLOAT_DTYPES else np.float64
        out = np.array(values, dtype=dtype, copy=True)
        out *= scaler.scale_
        out += scaler.min_
        return out
    return np.asarray(scaler.transform(values))


def _row_mean_of_squares(values: np.ndarray) -> np.ndarray:
    """``np.square(frame).mean(axis=1)`` as the legacy path computed it —
    pandas' NaN-skipping row mean (a plain-block frame here, so no
    MultiIndex machinery rides along)."""
    return pd.DataFrame(np.square(values)).mean(axis=1).to_numpy()


#: digest-keyed isoformat cache: serving traffic re-scores the same
#: windows constantly (every fleet machine shares one index; clients
#: replay fixed windows) and the per-row ``isoformat()`` loop was the
#: single largest slice of the columnar assembly (~0.8ms of a ~3ms
#: request at 256 rows). Keys are sha1 digests of the index's raw int64
#: image (+ dtype/offset), so the cache never pins request buffers;
#: entries are capped by count AND by row size, because a
#: sliding-window client mints a new index per request — retaining
#: huge per-row string lists it will never reuse would be a leak the
#: legacy path didn't have. Benign GIL races; cleared wholesale when
#: full.
_INDEX_STRINGS_CACHE: dict = {}
_INDEX_CACHE_MAX_ENTRIES = 128
_INDEX_CACHE_MAX_ROWS = 8192


def _isoformat_columns(
    index: pd.DatetimeIndex, frequency: Optional[Any]
) -> "tuple[list, list]":
    starts = [ts.isoformat() for ts in index]
    if frequency is not None:
        ends = [ts.isoformat() for ts in index + frequency]
    else:
        ends = [None] * len(index)
    return starts, ends


def _index_strings(
    index: pd.Index, frequency: Optional[Any]
) -> "tuple[list, list]":
    """The ``start``/``end`` object columns: cached isoformat strings
    for datetime indexes, None-filled otherwise, matching
    ``make_base_dataframe``."""
    n = len(index)
    if not isinstance(index, pd.DatetimeIndex):
        return [None] * n, [None] * n
    if n > _INDEX_CACHE_MAX_ROWS:
        return _isoformat_columns(index, frequency)
    try:
        import hashlib

        freq_str = frequency.freqstr if frequency is not None else None
        key = (
            hashlib.sha1(index.asi8.tobytes()).digest(),
            str(index.dtype),
            freq_str,
        )
    except Exception:  # noqa: BLE001 - exotic offsets/dtypes: the
        # cache is an optimization, never a correctness dependency
        return _isoformat_columns(index, frequency)
    cached = _INDEX_STRINGS_CACHE.get(key)
    if cached is not None:
        return cached
    columns = _isoformat_columns(index, frequency)
    if len(_INDEX_STRINGS_CACHE) >= _INDEX_CACHE_MAX_ENTRIES:
        _INDEX_STRINGS_CACHE.clear()
    _INDEX_STRINGS_CACHE[key] = columns
    return columns


def _matrix_columns(
    group: str, values: np.ndarray, names: Sequence[str]
) -> List[WireColumn]:
    """One column group out of a 2-D array; sub names fall back to
    stringified positions when the width disagrees with the tag list
    (same rule as ``make_base_dataframe``)."""
    if values.shape[1] == len(names):
        subs = list(names)
    else:
        subs = [str(i) for i in range(values.shape[1])]
    return [
        WireColumn(group, sub, values[:, i]) for i, sub in enumerate(subs)
    ]


def prediction_table(
    tags: Sequence[Any],
    X: pd.DataFrame,
    model_output: Any,
    target_tags: Optional[Sequence[Any]] = None,
    frequency: Optional[Any] = None,
) -> WireTable:
    """
    The base prediction response (``start`` / ``end`` / ``model-input`` /
    ``model-output``) as a columnar table: the vectorized equivalent of
    ``make_base_dataframe(...)`` + ``dataframe_to_dict`` with everything
    aligned to the (possibly shorter) model output.
    """
    output = np.asarray(getattr(model_output, "values", model_output))
    n_out = len(output)
    model_input = np.asarray(getattr(X, "values", X))[-n_out:, :]
    raw_index = getattr(X, "index", None)
    if raw_index is not None:
        index = pd.Index(raw_index[-n_out:])
    else:
        index = pd.RangeIndex(n_out)
    starts, ends = _index_strings(index, frequency)

    in_names = _tag_names(tags)
    out_names = _tag_names(target_tags) if target_tags is not None else in_names
    columns: List[WireColumn] = [
        WireColumn("start", "", starts),
        WireColumn("end", "", ends),
    ]
    columns.extend(_matrix_columns("model-input", model_input, in_names))
    columns.extend(_matrix_columns("model-output", output, out_names))
    return WireTable(index, columns)


def supports_columnar_anomaly(model: Any) -> bool:
    """Whether this model's anomaly frame can be assembled columnar-side:
    exactly the DiffBased detector family, by concrete type — a subclass
    overriding ``anomaly()`` gets the legacy path (its override is the
    contract)."""
    from ...models.anomaly.diff import (
        DiffBasedAnomalyDetector,
        DiffBasedKFCVAnomalyDetector,
    )

    return type(model) in (
        DiffBasedAnomalyDetector,
        DiffBasedKFCVAnomalyDetector,
    ) and type(model).anomaly is DiffBasedAnomalyDetector.anomaly


def anomaly_table(
    model: Any,
    X: pd.DataFrame,
    y: pd.DataFrame,
    model_output: Any,
    frequency: Optional[Any] = None,
    keep_smooth: bool = False,
    thresholds: Optional[np.ndarray] = None,
    aggregate: Optional[float] = None,
) -> WireTable:
    """
    ``DiffBasedAnomalyDetector.anomaly`` recomposed as columnar numpy —
    same math, same dtype flow, same column order, no intermediate
    MultiIndex frame. ``model_output`` is the (possibly micro-batched)
    reconstruction. Smooth columns are only computed when the response
    keeps them (``keep_smooth``) — the legacy path computed and then
    dropped them.

    ``thresholds``/``aggregate`` take the fleet resolution cache's
    pre-extracted arrays (exactly ``np.asarray(feature_thresholds_.values,
    float)`` / ``float(aggregate_threshold_)`` — same values, no
    per-request extraction); when omitted they are read off the model.

    Raises ``AttributeError`` when ``require_thresholds`` is set and no
    thresholds were fitted (the route maps it to 422, as before) and
    ``ValueError`` for input problems (→ 400).
    """
    if not hasattr(X, "values"):
        raise ValueError("Unable to find X.values property")
    output = np.asarray(getattr(model_output, "values", model_output))
    n_out = len(output)
    index = pd.Index(X.index[-n_out:])
    starts, ends = _index_strings(index, frequency)
    model_input = np.asarray(X.values)[-n_out:, :]
    in_names = _tag_names(X.columns)
    out_names = _tag_names(y.columns)
    out_subs = (
        list(out_names)
        if output.shape[1] == len(out_names)
        else [str(i) for i in range(output.shape[1])]
    )

    # -- threshold math, mirroring diff.anomaly() ----------------------
    y_raw = np.asarray(y)[-n_out:, :]
    out_scaled = _scaler_transform(model.scaler, output)
    scaled_y = _scaler_transform(model.scaler, np.asarray(y.values))
    tag_scaled = np.abs(out_scaled - scaled_y[-n_out:, :])
    total_scaled = _row_mean_of_squares(tag_scaled)
    tag_unscaled = np.abs(output - y_raw)
    total_unscaled = _row_mean_of_squares(tag_unscaled)

    columns: List[WireColumn] = [
        WireColumn("start", "", starts),
        WireColumn("end", "", ends),
    ]
    columns.extend(_matrix_columns("model-input", model_input, in_names))
    columns.extend(_matrix_columns("model-output", output, out_names))
    columns.extend(
        WireColumn("tag-anomaly-scaled", sub, tag_scaled[:, i])
        for i, sub in enumerate(out_subs)
    )
    columns.append(WireColumn("total-anomaly-scaled", "", total_scaled))
    columns.extend(
        WireColumn("tag-anomaly-unscaled", sub, tag_unscaled[:, i])
        for i, sub in enumerate(out_names)
    )
    columns.append(WireColumn("total-anomaly-unscaled", "", total_unscaled))

    if keep_smooth and model.window is not None and model.smoothing_method:
        smooth_scaled = _smooth(model, tag_scaled)
        columns.extend(
            WireColumn("smooth-tag-anomaly-scaled", sub, smooth_scaled[:, i])
            for i, sub in enumerate(out_subs)
        )
        columns.append(
            WireColumn(
                "smooth-total-anomaly-scaled",
                "",
                _smooth(model, total_scaled),
            )
        )
        smooth_unscaled = _smooth(model, tag_unscaled)
        columns.extend(
            WireColumn(
                "smooth-tag-anomaly-unscaled", sub, smooth_unscaled[:, i]
            )
            for i, sub in enumerate(out_names)
        )
        columns.append(
            WireColumn(
                "smooth-total-anomaly-unscaled",
                "",
                _smooth(model, total_unscaled),
            )
        )

    if thresholds is None:
        fitted = getattr(model, "feature_thresholds_", None)
        if fitted is not None:
            thresholds = np.asarray(fitted.values, dtype=float)
    if thresholds is not None:
        confidence = tag_unscaled / thresholds
        columns.extend(
            WireColumn("anomaly-confidence", sub, confidence[:, i])
            for i, sub in enumerate(out_subs)
        )
    if aggregate is None:
        fitted_aggregate = getattr(model, "aggregate_threshold_", None)
        if fitted_aggregate is not None:
            aggregate = float(fitted_aggregate)
    if aggregate is not None:
        columns.append(
            WireColumn(
                "total-anomaly-confidence", "", total_scaled / aggregate
            )
        )

    if model.require_thresholds and not any(
        hasattr(model, attr)
        for attr in ("feature_thresholds_", "aggregate_threshold_")
    ):
        raise AttributeError(
            f"`require_thresholds={model.require_thresholds}` however "
            "`.cross_validate` was not called to calculate thresholds "
            "before `.anomaly`"
        )
    return WireTable(index, columns)


def _smooth(model: Any, values: np.ndarray) -> np.ndarray:
    """``DiffBasedAnomalyDetector._smoothing`` over a plain array —
    pandas rolling/ewm on a single-block frame (or Series for 1-D),
    numerically identical to the legacy MultiIndex version."""
    metric = (
        pd.Series(values) if values.ndim == 1 else pd.DataFrame(values)
    )
    if model.smoothing_method == "smm":
        smoothed = metric.rolling(model.window).median()
    elif model.smoothing_method == "sma":
        smoothed = metric.rolling(model.window).mean()
    elif model.smoothing_method == "ewma":
        smoothed = metric.ewm(span=model.window).mean()
    else:
        raise ValueError(
            f"Unknown smoothing_method {model.smoothing_method!r}"
        )
    return smoothed.to_numpy()
