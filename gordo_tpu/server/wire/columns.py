"""
The columnar response model the wire fast path assembles into.

A :class:`WireTable` is the serving pipeline's in-flight response shape:
an ordered list of ``(group, sub, values)`` columns over one shared index
— exactly the structure every wire encoder needs (the nested JSON dict's
``{group: {sub: {key: value}}}``, an Arrow record batch's fields, a
parquet/pandas MultiIndex frame) without committing to any of them. The
point of the type is what it is NOT: a pandas DataFrame. The legacy
response path built a MultiIndex frame column-group by column-group
(``make_base_dataframe`` + joins) and then walked it cell by cell into
wire dicts — measured at ~70% of full-route p50 (BENCH_ROUTE.json,
``response_assemble`` 493ms of 686ms). Here every column is composed
once, as a numpy array, and handed to the encoder as-is.
"""

from typing import Any, Iterator, List, NamedTuple, Optional, Tuple

import numpy as np
import pandas as pd


class WireColumn(NamedTuple):
    """One response column: ``group`` is the top-level wire key
    (``model-output``, ``tag-anomaly-scaled``, ...), ``sub`` the tag-level
    key ('' for scalar groups like ``total-anomaly-scaled``), ``values``
    a 1-D numpy array or a plain list (object columns: ISO strings /
    None)."""

    group: str
    sub: str
    values: Any


class WireTable:
    """An ordered columnar response over one index.

    ``index`` is the (already output-aligned) pandas index; ``keys`` are
    the wire keys the JSON encoders need — the same strings
    ``server.utils.index_wire_keys`` produces, computed once per table
    (lazily: the Arrow encoder never needs them).
    """

    __slots__ = ("index", "columns", "_keys")

    def __init__(self, index: pd.Index, columns: List[WireColumn]):
        self.index = index
        self.columns = columns
        self._keys: Optional[list] = None

    @property
    def keys(self) -> list:
        if self._keys is None:
            from .. import utils as server_utils

            if isinstance(self.index, pd.DatetimeIndex):
                self._keys = server_utils.index_wire_keys(self.index)
            else:
                # non-datetime indexes keep their native values — the
                # JSON layer coerces them to string keys exactly like
                # ``json.dumps`` did for the legacy dict form
                self._keys = list(self.index)
        return self._keys

    @classmethod
    def from_frame(cls, frame: pd.DataFrame) -> "WireTable":
        """A columnar view of an existing (MultiIndex-column) response
        frame — the bridge that lets legacy pandas assemblies (custom
        detectors) ride the new wire encoders."""
        columns: List[WireColumn] = []
        if isinstance(frame.columns, pd.MultiIndex):
            for group, sub in frame.columns:
                columns.append(
                    WireColumn(
                        str(group),
                        str(sub) if sub is not None else "",
                        frame[(group, sub)].to_numpy(),
                    )
                )
        else:
            for name in frame.columns:
                columns.append(
                    WireColumn(str(name), "", frame[name].to_numpy())
                )
        return cls(frame.index, columns)

    def groups(self) -> Iterator[Tuple[str, List[WireColumn]]]:
        """Columns grouped by consecutive top-level key, in order."""
        group: Optional[str] = None
        bucket: List[WireColumn] = []
        for column in self.columns:
            if column.group != group:
                if bucket:
                    yield group, bucket  # type: ignore[misc]
                group, bucket = column.group, []
            bucket.append(column)
        if bucket:
            yield group, bucket  # type: ignore[misc]

    def unique_labels(self) -> bool:
        """Whether every (group, sub) label is unique — the fast wire
        encoders require it (the legacy pandas path keeps pandas'
        warn-and-omit duplicate-label semantics)."""
        labels = [(c.group, c.sub) for c in self.columns]
        return len(set(labels)) == len(labels)

    def to_frame(self) -> pd.DataFrame:
        """The equivalent MultiIndex-column DataFrame — the compatibility
        bridge for the legacy parquet wire format (``?format=parquet``
        responses decode to the exact frame the pandas path produced)."""
        data = {(c.group, c.sub): c.values for c in self.columns}
        frame = pd.DataFrame(
            data,
            index=self.index,
            columns=pd.MultiIndex.from_tuples(list(data)),
        )
        return frame

    def to_wire_dict(self) -> dict:
        """The nested ``{group: {sub: {key: value}}}`` wire dict — the
        fleet route's JSON envelope embeds tables per machine. Numeric
        columns go through ``tolist()`` (python scalars, like pandas
        ``to_dict`` produced)."""
        keys = self.keys
        out: dict = {}
        for group, bucket in self.groups():
            # sub '' nests under the group's own name, matching the
            # legacy pandas serializer (('start', '') collapsed to a
            # Series named 'start' and THAT became the wire sub key)
            out[group] = {
                (c.sub or group): dict(
                    zip(
                        keys,
                        c.values.tolist()
                        if isinstance(c.values, np.ndarray)
                        else c.values,
                    )
                )
                for c in bucket
            }
        return out
