"""
The dict-free JSON wire encoder.

The legacy serialize path materialized the full nested wire dict
(``{group: {sub: {key: value}}}`` — one python dict per column, one
entry per cell) and then walked it AGAIN inside ``json.dumps`` (plus a
third time in the ``ignore_nan`` sanitize walk on stdlib json). This
encoder writes the same bytes straight from the columnar table: the
per-row key prefixes (``"2020-01-01 00:00:00+00:00": ``) are formatted
ONCE per request and every column's cells become literals via one
``tolist()`` + ``repr`` pass — python floats repr exactly as
``json.dumps`` emits them, so the output is byte-for-byte identical to
``json_compat.dumps(payload, default=str, ignore_nan=True)`` of the
equivalent dict (pinned by ``tests/server/test_wire_parity.py``).

``iter_encode_response`` is the streamed variant
(``GORDO_TPU_WIRE_STREAM``): chunks come out one column group at a
time, so a WSGI server that streams can overlap encode with socket
writes instead of materializing multi-MB bodies.
"""

import json
import math
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ...utils import json_compat
from .columns import WireTable

#: separators matching ``json.dumps``' defaults (the legacy serializer
#: used them — byte parity requires the spaces)
_ITEM_SEP = ", "
_KEY_SEP = ": "


def _key_literal(key: Any) -> str:
    """A JSON OBJECT KEY for ``key``, with ``json.dumps``' non-string
    key coercion rules (int → str, float → repr, bool → true/false)."""
    if isinstance(key, str):
        return json.dumps(key)
    if key is True:
        return '"true"'
    if key is False:
        return '"false"'
    if isinstance(key, int):
        return f'"{key:d}"'
    if isinstance(key, float):
        return f'"{float.__repr__(key)}"'
    return json.dumps(str(key))


def _value_literal(value: Any) -> str:
    """One cell as a JSON literal, with the legacy path's ``default=str,
    ignore_nan=True`` semantics."""
    if value is None:
        return "null"
    if isinstance(value, str):
        return json.dumps(value)
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, float):
        return float.__repr__(value) if math.isfinite(value) else "null"
    if isinstance(value, int):
        return str(value)
    return json_compat.dumps(value, default=str, ignore_nan=True)


def _column_literals(values: Any) -> List[str]:
    """Every cell of one column as JSON literals — one ``tolist()`` for
    numeric arrays (C-speed unboxing), per-value fallback for object
    columns (ISO strings / None)."""
    if isinstance(values, np.ndarray):
        kind = values.dtype.kind
        if kind == "f":
            literals = [float.__repr__(v) for v in values.tolist()]
            if not np.isfinite(values).all():
                finite = np.isfinite(values).tolist()
                literals = [
                    lit if ok else "null"
                    for lit, ok in zip(literals, finite)
                ]
            return literals
        if kind in "iu":
            return [str(v) for v in values.tolist()]
        if kind == "b":
            return [
                "true" if v else "false" for v in values.tolist()
            ]
        values = values.tolist()
    return [_value_literal(v) for v in values]


def encode_table(table: WireTable) -> Iterator[str]:
    """The ``{group: {sub: {key: value}}}`` JSON object, one text chunk
    per column group."""
    key_prefixes = [
        _key_literal(key) + _KEY_SEP for key in table.keys
    ]
    first = True
    yield "{"
    for group, bucket in table.groups():
        sub_parts = []
        for column in bucket:
            literals = _column_literals(column.values)
            body = _ITEM_SEP.join(
                prefix + literal
                for prefix, literal in zip(key_prefixes, literals)
            )
            # scalar groups nest under their own name — pandas collapsed
            # ('start', '') to a Series named 'start', and that Series
            # name became the legacy wire's sub key
            sub_parts.append(
                json.dumps(column.sub or column.group)
                + _KEY_SEP
                + "{"
                + body
                + "}"
            )
        chunk = (
            ("" if first else _ITEM_SEP)
            + json.dumps(group)
            + _KEY_SEP
            + "{"
            + _ITEM_SEP.join(sub_parts)
            + "}"
        )
        first = False
        yield chunk
    yield "}"


def iter_encode_response(
    table: WireTable, extra: Optional[Dict[str, Any]] = None
) -> Iterator[bytes]:
    """The full response body ``{"data": <table>, **extra}``, streamed
    as UTF-8 chunks (one per column group). ``extra`` items serialize
    through the same ``json_compat`` path the legacy serializer used."""
    yield b'{"data"' + _KEY_SEP.encode()
    for chunk in encode_table(table):
        yield chunk.encode()
    if extra:
        for key, value in extra.items():
            yield (
                _ITEM_SEP
                + json.dumps(key)
                + _KEY_SEP
                + json_compat.dumps(value, default=str, ignore_nan=True)
            ).encode()
    yield b"}"


def encode_response(
    table: WireTable, extra: Optional[Dict[str, Any]] = None
) -> bytes:
    """The full response body as one bytes payload (the default,
    non-streamed serialize path)."""
    return b"".join(iter_encode_response(table, extra))
