"""
Arrow-IPC wire codec (import-guarded: pyarrow is an optional extra).

Schema conventions (documented in ``docs/serving.md``):

- Every field carries ``gordo:role`` metadata: ``index`` for the row
  index (a timestamp column named ``__index__`` by convention), ``y``
  for target columns on request bodies, ``x`` (or no metadata) for
  input columns.
- Response fields additionally carry ``gordo:group`` / ``gordo:sub``
  metadata — the nested JSON wire form's two column levels — and are
  named ``group`` or ``group/sub`` for human readability (the metadata
  is authoritative; tags may contain ``/``).
- Scalar response envelope fields (``revision``, ``time-seconds``)
  travel as schema-level metadata under ``gordo:meta`` (a JSON object).
- Fleet bodies are a container of per-machine IPC streams
  (:func:`pack_streams` / :func:`unpack_streams`) because machines have
  heterogeneous schemas and one IPC stream carries exactly one schema.

Decoding is zero-copy where Arrow allows it: a null-free numeric column
comes back as a numpy VIEW over the received buffer (``to_numpy``
``zero_copy_only``), so ``data_decode`` is column-pointer bookkeeping
instead of a JSON parse.
"""

import json
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from ...utils.env import env_bool
from .columns import WireTable

try:  # pragma: no cover - exercised via HAVE_ARROW in both states
    import pyarrow as _pa
except ImportError:  # pragma: no cover
    _pa = None

HAVE_ARROW = _pa is not None

#: wire content types (the stream type is the official Arrow IPC one)
ARROW_CONTENT_TYPE = "application/vnd.apache.arrow.stream"

ROLE_KEY = b"gordo:role"
GROUP_KEY = b"gordo:group"
SUB_KEY = b"gordo:sub"
META_KEY = b"gordo:meta"
INDEX_FIELD = "__index__"

#: fleet container magic: per-machine IPC streams, length-prefixed
_FLEET_MAGIC = b"GDTAF1"


def arrow_enabled() -> bool:
    """Whether the Arrow wire format is served: pyarrow importable AND
    not force-disabled (``GORDO_TPU_WIRE_ARROW=0`` drills the JSON-only
    fallback without uninstalling anything)."""
    return HAVE_ARROW and env_bool("GORDO_TPU_WIRE_ARROW", True)


class ArrowDecodeError(ValueError):
    """A malformed Arrow body (the route answers 400)."""


def _require_pa():
    if _pa is None:  # pragma: no cover - guarded by callers
        raise RuntimeError("pyarrow is not installed")
    return _pa


# -- response encoding ------------------------------------------------------


def _index_array(index: pd.Index):
    pa = _require_pa()
    if isinstance(index, pd.DatetimeIndex):
        return pa.array(index)
    return pa.array(list(index))


#: response field lists cached by column structure: a served model's
#: response schema is fixed per (revision, column set, dtypes), and
#: rebuilding 20+ pa.field objects with metadata dicts per request was
#: ~20% of the arrow route's host time. Benign races (dict get/set under
#: the GIL); bounded below.
_FIELDS_CACHE: Dict[tuple, Any] = {}


def _response_fields(key: tuple, arrays, columns) -> list:
    pa = _require_pa()
    fields = _FIELDS_CACHE.get(key)
    if fields is not None:
        return fields
    fields = [
        pa.field(
            INDEX_FIELD, arrays[0].type, metadata={ROLE_KEY: b"index"}
        )
    ]
    for array, column in zip(arrays[1:], columns):
        name = (
            column.group
            if not column.sub
            else f"{column.group}/{column.sub}"
        )
        fields.append(
            pa.field(
                name,
                array.type,
                metadata={
                    GROUP_KEY: column.group.encode(),
                    SUB_KEY: column.sub.encode(),
                },
            )
        )
    if len(_FIELDS_CACHE) >= 256:
        _FIELDS_CACHE.clear()
    _FIELDS_CACHE[key] = fields
    return fields


def encode_table(
    table: WireTable, extra: Optional[Dict[str, Any]] = None
) -> bytes:
    """One response table as a single-batch Arrow IPC stream."""
    pa = _require_pa()
    arrays = [_index_array(table.index)]
    arrays.extend(pa.array(column.values) for column in table.columns)
    key = tuple(
        [str(arrays[0].type)]
        + [
            (column.group, column.sub, str(array.type))
            for array, column in zip(arrays[1:], table.columns)
        ]
    )
    fields = _response_fields(key, arrays, table.columns)
    metadata = {}
    if extra:
        metadata[META_KEY] = json.dumps(extra, default=str).encode()
    schema = pa.schema(fields, metadata=metadata or None)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, schema) as writer:
        writer.write_batch(
            pa.record_batch(arrays, schema=schema)
        )
    return sink.getvalue().to_pybytes()


# -- request decoding -------------------------------------------------------


def _read_ipc(buf: bytes):
    """One IPC stream as a record batch (the overwhelmingly common
    single-batch body decodes without Table/ChunkedArray wrapping — its
    columns are plain Arrays whose ``to_numpy`` is a direct view) or a
    Table for multi-batch streams."""
    pa = _require_pa()
    try:
        with pa.ipc.open_stream(pa.py_buffer(buf)) as reader:
            try:
                first = reader.read_next_batch()
            except StopIteration:
                raise ArrowDecodeError("Empty Arrow IPC body") from None
            try:
                second = reader.read_next_batch()
            except StopIteration:
                return first
            return pa.Table.from_batches(
                [first, second] + list(reader)
            )
    except ArrowDecodeError:
        raise
    except (pa.ArrowInvalid, pa.ArrowIOError, OSError, ValueError) as exc:
        raise ArrowDecodeError(f"Malformed Arrow IPC body: {exc}") from None


def _to_numpy(column) -> np.ndarray:
    """One Arrow column as numpy — zero-copy for null-free primitive
    columns, a NaN-filling copy otherwise."""
    combined = (
        column.combine_chunks()
        if hasattr(column, "combine_chunks")
        else column
    )
    try:
        return combined.to_numpy(zero_copy_only=True)
    except Exception:  # noqa: BLE001 - nulls / non-primitive: copy path
        return combined.to_numpy(zero_copy_only=False)


#: timestamp-index reconstruction cached by sha1 of the raw int64
#: image: clients replay the same windows request after request, and tz
#: localize/convert cost ~0.2ms per decode. Digest keys + row cap keep
#: sliding-window clients (new index every request, 0% hit rate) from
#: turning retention into a leak. Benign GIL races; cleared when full.
_DT_INDEX_CACHE: dict = {}
_DT_INDEX_CACHE_MAX_ENTRIES = 64
_DT_INDEX_CACHE_MAX_ROWS = 8192


def _index_from(arrow_table, position: int) -> pd.Index:
    field = arrow_table.schema.field(position)
    values = _to_numpy(arrow_table.column(position))
    pa = _require_pa()
    if pa.types.is_timestamp(field.type):
        if (
            values.dtype == np.dtype("datetime64[ns]")
            and len(values) <= _DT_INDEX_CACHE_MAX_ROWS
        ):
            import hashlib

            raw = values.astype(np.int64).tobytes()
            key = (hashlib.sha1(raw).digest(), field.type.tz)
            cached = _DT_INDEX_CACHE.get(key)
            if cached is not None:
                return cached
        else:
            key = None
        index = pd.DatetimeIndex(values)
        if field.type.tz is not None:
            index = index.tz_localize("UTC").tz_convert(field.type.tz)
        if key is not None:
            if len(_DT_INDEX_CACHE) >= _DT_INDEX_CACHE_MAX_ENTRIES:
                _DT_INDEX_CACHE.clear()
            _DT_INDEX_CACHE[key] = index
        return index
    return pd.Index(values)


def decode_frames(
    buf: bytes,
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray], Optional[pd.Index]]:
    """An Arrow request body → (x columns, y columns, index). Roles come
    from field metadata (``gordo:role``); unmarked fields are ``x``."""
    arrow_table = _read_ipc(buf)
    x_cols: Dict[str, np.ndarray] = {}
    y_cols: Dict[str, np.ndarray] = {}
    index: Optional[pd.Index] = None
    for position, field in enumerate(arrow_table.schema):
        role = (field.metadata or {}).get(ROLE_KEY, b"x")
        if role == b"index" or (
            field.name == INDEX_FIELD and role == b"x"
        ):
            index = _index_from(arrow_table, position)
            continue
        target = y_cols if role == b"y" else x_cols
        if field.name in target:
            raise ArrowDecodeError(
                f"Duplicate column {field.name!r} in Arrow body"
            )
        target[field.name] = _to_numpy(arrow_table.column(position))
    if not x_cols:
        raise ArrowDecodeError('Cannot predict without "X"')
    return x_cols, y_cols, index


def columns_to_frame(
    columns: Dict[str, np.ndarray],
    index: Optional[pd.Index],
    order: List[str],
) -> pd.DataFrame:
    """Assemble the model-input DataFrame from decoded columns in the
    model's tag order (``order`` — the cached alignment plan's output).
    The index is sorted ascending like the JSON decode path sorts."""
    stacked = np.column_stack([columns[name] for name in order])
    frame = pd.DataFrame(stacked, columns=order, index=index)
    if index is not None and not frame.index.is_monotonic_increasing:
        frame.sort_index(inplace=True)
    return frame


# -- request/response helpers for clients and tests -------------------------


def encode_request(
    X: pd.DataFrame, y: Optional[pd.DataFrame] = None
) -> bytes:
    """An ``X``(+``y``) request body as one Arrow IPC stream — the
    client-side encoder (``gordo_tpu.client`` and the parity tests)."""
    pa = _require_pa()
    arrays = [_index_array(X.index)]
    fields = [
        pa.field(
            INDEX_FIELD, arrays[0].type, metadata={ROLE_KEY: b"index"}
        )
    ]
    for name in X.columns:
        array = pa.array(np.asarray(X[name]))
        fields.append(
            pa.field(str(name), array.type, metadata={ROLE_KEY: b"x"})
        )
        arrays.append(array)
    if y is not None:
        for name in y.columns:
            array = pa.array(np.asarray(y[name]))
            fields.append(
                pa.field(str(name), array.type, metadata={ROLE_KEY: b"y"})
            )
            arrays.append(array)
    schema = pa.schema(fields)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, schema) as writer:
        writer.write_batch(pa.record_batch(arrays, schema=schema))
    return sink.getvalue().to_pybytes()


def decode_response(buf: bytes) -> Tuple[pd.DataFrame, Dict[str, Any]]:
    """A response IPC stream → (MultiIndex-column DataFrame, envelope
    metadata) — the client-side decoder, shaped exactly like
    ``dataframe_from_dict(response["data"])`` for JSON clients."""
    arrow_table = _read_ipc(buf)
    index: Optional[pd.Index] = None
    columns: Dict[Tuple[str, str], np.ndarray] = {}
    for position, field in enumerate(arrow_table.schema):
        metadata = field.metadata or {}
        if metadata.get(ROLE_KEY) == b"index":
            index = _index_from(arrow_table, position)
            continue
        group = metadata.get(GROUP_KEY, field.name.encode()).decode()
        sub = metadata.get(SUB_KEY, b"").decode()
        columns[(group, sub)] = _to_numpy(arrow_table.column(position))
    frame = pd.DataFrame(
        columns,
        index=index,
        columns=pd.MultiIndex.from_tuples(list(columns)),
    )
    extra_raw = (arrow_table.schema.metadata or {}).get(META_KEY)
    extra = json.loads(extra_raw) if extra_raw else {}
    return frame, extra


# -- fleet container --------------------------------------------------------


def pack_streams(
    entries: Dict[str, bytes], extra: Optional[Dict[str, Any]] = None
) -> bytes:
    """Length-prefixed container of named IPC payloads (one per machine)
    plus a JSON ``extra`` trailer (per-machine errors, revision)."""
    parts = [_FLEET_MAGIC, struct.pack("<I", len(entries))]
    for name, payload in entries.items():
        encoded = name.encode()
        parts.append(struct.pack("<I", len(encoded)))
        parts.append(encoded)
        parts.append(struct.pack("<Q", len(payload)))
        parts.append(payload)
    trailer = json.dumps(extra or {}, default=str).encode()
    parts.append(struct.pack("<Q", len(trailer)))
    parts.append(trailer)
    return b"".join(parts)


def unpack_streams(buf: bytes) -> Tuple[Dict[str, bytes], Dict[str, Any]]:
    """Inverse of :func:`pack_streams`; raises
    :class:`ArrowDecodeError` on truncation/garbage."""
    view = memoryview(buf)
    if len(view) < len(_FLEET_MAGIC) + 4 or bytes(
        view[: len(_FLEET_MAGIC)]
    ) != _FLEET_MAGIC:
        raise ArrowDecodeError("Not a gordo Arrow fleet container")
    offset = len(_FLEET_MAGIC)
    try:
        (count,) = struct.unpack_from("<I", view, offset)
        offset += 4
        entries: Dict[str, bytes] = {}
        for _ in range(count):
            (name_len,) = struct.unpack_from("<I", view, offset)
            offset += 4
            name = bytes(view[offset : offset + name_len]).decode()
            offset += name_len
            (payload_len,) = struct.unpack_from("<Q", view, offset)
            offset += 8
            if offset + payload_len > len(view):
                raise ArrowDecodeError("Truncated fleet container entry")
            entries[name] = bytes(view[offset : offset + payload_len])
            offset += payload_len
        (trailer_len,) = struct.unpack_from("<Q", view, offset)
        offset += 8
        trailer = bytes(view[offset : offset + trailer_len])
        extra = json.loads(trailer) if trailer else {}
    except (struct.error, UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise ArrowDecodeError(
            f"Malformed fleet container: {exc}"
        ) from None
    return entries, extra
