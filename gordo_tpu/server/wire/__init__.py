"""
Columnar wire fast path for the serving stack (PR 12).

``gordo_tpu.server.wire`` owns everything between "the model scored"
and "bytes on the socket": content negotiation
(:mod:`~gordo_tpu.server.wire.negotiate`), vectorized response assembly
(:mod:`~gordo_tpu.server.wire.assemble` — numpy columns instead of the
MultiIndex-frame round-trip that was ~70% of full-route p50), the
dict-free JSON encoder (:mod:`~gordo_tpu.server.wire.json_codec`,
byte-identical to the legacy serializer), and the import-guarded
Arrow-IPC codec (:mod:`~gordo_tpu.server.wire.arrow_codec` — zero-copy
request decode, record-batch responses, the fleet container).

Layering contract (enforced by ``gordo-tpu lint``): this package never
imports the server views or app — the views call DOWN into the codec.

Knobs: ``GORDO_TPU_WIRE_COLUMNAR`` (master switch for the vectorized
assembly; off = legacy pandas path, identical bytes),
``GORDO_TPU_WIRE_ARROW`` (serve/accept Arrow bodies when pyarrow is
importable), ``GORDO_TPU_WIRE_STREAM`` (stream JSON response bodies as
WSGI chunks; off by default because streamed serialize time lands
outside the request's exported stage spans).
"""

from ...utils.env import env_bool
from .arrow_codec import (
    ARROW_CONTENT_TYPE,
    HAVE_ARROW,
    ArrowDecodeError,
    arrow_enabled,
    decode_frames,
    decode_response,
    encode_request,
    encode_table,
    pack_streams,
    unpack_streams,
)
from .assemble import (
    anomaly_table,
    prediction_table,
    supports_columnar_anomaly,
)
from .columns import WireColumn, WireTable
from .json_codec import encode_response, iter_encode_response
from .negotiate import (
    ARROW,
    JSON,
    JSON_CONTENT_TYPE,
    PARQUET,
    PARQUET_CONTENT_TYPE,
    request_format,
    response_format,
)


def columnar_enabled() -> bool:
    """Master switch for the vectorized assembly fast path
    (``GORDO_TPU_WIRE_COLUMNAR``, default on). The legacy pandas path
    stays available as the escape hatch — and produces the same bytes."""
    return env_bool("GORDO_TPU_WIRE_COLUMNAR", True)


def stream_enabled() -> bool:
    """Whether JSON responses stream as WSGI chunks
    (``GORDO_TPU_WIRE_STREAM``, default off — see the module docstring
    for the stage-attribution caveat)."""
    return env_bool("GORDO_TPU_WIRE_STREAM", False)


__all__ = [
    "ARROW",
    "ARROW_CONTENT_TYPE",
    "ArrowDecodeError",
    "HAVE_ARROW",
    "JSON",
    "JSON_CONTENT_TYPE",
    "PARQUET",
    "PARQUET_CONTENT_TYPE",
    "WireColumn",
    "WireTable",
    "anomaly_table",
    "arrow_enabled",
    "columnar_enabled",
    "decode_frames",
    "decode_response",
    "encode_request",
    "encode_response",
    "encode_table",
    "iter_encode_response",
    "pack_streams",
    "prediction_table",
    "request_format",
    "response_format",
    "stream_enabled",
    "supports_columnar_anomaly",
    "unpack_streams",
]
