"""
Fleet-resident model store: the TPU-native replacement for the
reference's LRU(2)-of-pickles serving cache (gordo/server/utils.py:334-353).

The reference serves thousands of tiny models by unpickling whichever two
were requested most recently — every other request pays a full disk load
plus (here) a host→device parameter transfer. A TPU fleet's models are
small enough to keep *all* of them resident: this store keeps one
:class:`RevisionFleet` per served revision directory, each holding every
loaded model with its JAX parameters already on device, plus per-spec
**buckets** of stacked parameters (``parallel.fleet.stack_member_params``)
so whole-fleet scoring runs as one device program — through the Pallas
fused kernel (:func:`gordo_tpu.ops.pallas_dense.fleet_feedforward_pallas`)
on TPU, or the XLA vmapped forward elsewhere.

Consistency contract: a model is loaded at most once per revision
directory; the DELETE-revision route invalidates the store, and metadata
existence is still re-checked per request by the caller (the same
staleness rule the reference documents for its LRU caches).
"""

import logging
import os
import threading
from collections import OrderedDict
from functools import lru_cache
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from .. import serializer
from ..models.estimators import JaxBaseEstimator
from ..models.spec import FeedForwardSpec, LSTMSpec
from ..utils.env import env_bool, env_int

logger = logging.getLogger(__name__)


def _find_estimator(model: Any) -> Optional[JaxBaseEstimator]:
    """The JAX estimator inside a served object graph (detector and/or
    sklearn Pipeline wrappers), or None for non-JAX models."""
    obj = model
    base = getattr(obj, "base_estimator", None)
    if base is not None:
        obj = base
    steps = getattr(obj, "steps", None)
    if steps:
        obj = steps[-1][1]
    return obj if isinstance(obj, JaxBaseEstimator) else None


def _host_transform(model: Any, X):
    """Apply any host-side pipeline transformers ahead of the estimator
    (scalers etc.); mirrors the pipeline's own predict path."""
    obj = model
    base = getattr(obj, "base_estimator", None)
    if base is not None:
        obj = base
    steps = getattr(obj, "steps", None)
    if steps:
        for _, transformer in steps[:-1]:
            X = transformer.transform(X)
    return np.asarray(X, np.float32)


class ModelLoadError(Exception):
    """
    A model artifact failed to LOAD (as opposed to failing to score a
    request). Routes must not echo the underlying cause — load errors are
    server-side and their text can carry filesystem paths; the cause is
    chained for the server log only.
    """


class ModelResolution:
    """
    Everything the serving routes derive from one model's artifacts, at
    most once per revision: the loaded model, parsed metadata/info, tag
    lists (both as :class:`SensorTag` and as plain names), the training
    frequency offset, the detector's threshold arrays, and the wire
    column-alignment plans. BENCH_ROUTE.json measured ``model_resolve``
    at 50.9ms p50 (7.5% of the route) — almost all of it the per-request
    zlib+pickle metadata round-trip and tag re-normalization this object
    exists to not repeat: a request now pays dict probes.

    Pinned to the :class:`RevisionFleet` snapshot, so the DELETE/hot-swap
    invalidation contract is inherited wholesale (an invalidated revision
    drops its fleet object, resolutions and all); callers still re-check
    ``metadata.json`` existence per request, as with every other cache.
    """

    __slots__ = (
        "name",
        "model",
        "metadata",
        "info",
        "tags",
        "target_tags",
        "tag_names",
        "target_names",
        "feature_thresholds",
        "aggregate_threshold",
        "_frequency",
        "_plans",
    )

    def __init__(self, name: str, model: Any, metadata: dict, info: dict):
        from types import SimpleNamespace

        from .properties import get_frequency, get_tags, get_target_tags

        self.name = name
        self.model = model
        self.metadata = metadata
        self.info = info
        carrier = SimpleNamespace(metadata=metadata)
        self.tags = get_tags(carrier)
        self.target_tags = get_target_tags(carrier)
        self.tag_names = [t.name for t in self.tags]
        self.target_names = [t.name for t in self.target_tags]
        try:
            self._frequency = ("ok", get_frequency(carrier))
        except Exception as exc:  # noqa: BLE001 - re-raised per access
            self._frequency = ("error", exc)
        thresholds = getattr(model, "feature_thresholds_", None)
        self.feature_thresholds = (
            np.asarray(thresholds.values, dtype=float)
            if thresholds is not None
            else None
        )
        aggregate = getattr(model, "aggregate_threshold_", None)
        self.aggregate_threshold = (
            float(aggregate) if aggregate is not None else None
        )
        self._plans: Dict[Tuple, Tuple[str, ...]] = {}

    @property
    def frequency(self):
        """The training resolution as a pandas offset. Errors are cached
        too and re-raised per access — the route's error contract for a
        bad ``dataset.resolution`` must not depend on cache state."""
        kind, value = self._frequency
        if kind == "error":
            raise value
        return value

    def alignment(
        self, names: Tuple[str, ...], expected: Tuple[str, ...]
    ) -> Optional[Tuple[str, ...]]:
        """The cached column-selection plan for a client column set
        against ``expected`` tag order: the tuple of client column names
        to stack, or None when no plan is cached yet. Bounded: plans are
        keyed by client-supplied column tuples, so the dict is capped
        against adversarial churn."""
        return self._plans.get((names, expected))

    def remember_alignment(
        self,
        names: Tuple[str, ...],
        expected: Tuple[str, ...],
        order: Tuple[str, ...],
    ) -> None:
        if len(self._plans) >= 1024:
            self._plans.clear()
        self._plans[(names, expected)] = order


class RevisionFleet:
    """
    All models of one revision directory, loaded lazily but retained for
    the life of the revision (no per-request eviction thrash). Feedforward
    and LSTM estimators additionally join per-spec stacked buckets for
    fused whole-fleet scoring.
    """

    def __init__(self, collection_dir: str):
        self.collection_dir = collection_dir
        self._lock = threading.Lock()
        # _models and _specs are COPY-ON-WRITE: loads replace the whole
        # dict under the lock, readers just dereference the attribute
        # (an atomic ref read) — the per-request serving path never
        # touches the lock, so a thousand concurrent requests can't
        # convoy behind it (nor behind the micro-batcher's per-batch
        # bucket lookup). Never mutate these dicts in place.
        self._models: Dict[str, Any] = {}
        self._specs: Dict[str, Any] = {}  # name -> spec (JAX models only)
        #: name -> ModelResolution (COW, same discipline as _models)
        self._resolutions: Dict[str, ModelResolution] = {}
        #: spec -> (names, stacked params, epoch stamped at build)
        self._stacked: Dict[Any, Tuple[List[str], Any, int]] = {}
        #: (spec, precision) -> (names, cast/quantized params, epoch):
        #: reduced-precision copies of the f32 buckets, cast ONCE at
        #: fleet load (serve.precision.cast_bucket_params) — the serve
        #: engine's precision ladder reads these per batch, never
        #: re-casts per request. Mutated only under the lock, like
        #: _stacked.
        self._cast_buckets: Dict[Tuple[Any, str], Tuple[List[str], Any, int]] = {}
        #: spec -> (names, FleetIngestPlan | None, epoch): the compiled
        #: preprocessing plan per spec bucket (gordo_tpu.ingest), built
        #: lazily like the buckets. None is a NEGATIVE verdict (some
        #: member's pipeline is not affine-compilable) and is cached too
        #: — probing an uncompilable fleet must not re-walk sklearn
        #: object graphs per request. Mutated only under the lock;
        #: epoch-stamped so hot-swap/DELETE invalidation is inherited.
        self._ingest_plans: Dict[Any, Tuple[List[str], Any, int]] = {}
        #: (spec, precision) -> precision-parity gate report (COW, same
        #: discipline as _models): the serve engine's governor caches
        #: pass/fail verdicts here, so gate state lives and dies with
        #: the revision fleet — a hot-swap or DELETE re-gates naturally.
        self._precision_states: Dict[Tuple[Any, str], Dict[str, Any]] = {}
        self._bucket_epoch = 0  # bumped on every membership change

    # -- single-model serving ------------------------------------------------

    def model(self, name: str) -> Any:
        """The loaded model for ``name`` (load-once, then resident)."""
        cached = self._models.get(name)  # lock-free: _models is COW
        if cached is not None:
            return cached

        model = serializer.load(os.path.join(self.collection_dir, name))
        estimator = _find_estimator(model)
        if estimator is not None and estimator.params_ is not None:
            # Device-resident parameters: every later predict skips the
            # host→device transfer the unpickled numpy params would pay.
            estimator.params_ = jax.device_put(estimator.params_)
        with self._lock:
            # Lost the load race: keep the first copy (single residency).
            existing = self._models.get(name)
            if existing is not None:
                return existing
            models = dict(self._models)
            models[name] = model
            self._models = models
            if estimator is not None and estimator.spec_ is not None:
                specs = dict(self._specs)
                specs[name] = estimator.spec_
                self._specs = specs
                self._stacked.pop(estimator.spec_, None)  # bucket grew; restack
                for key in [
                    k for k in self._cast_buckets if k[0] == estimator.spec_
                ]:
                    self._cast_buckets.pop(key, None)  # recast with the bucket
                self._ingest_plans.pop(estimator.spec_, None)  # replan too
                self._bucket_epoch += 1
        return model

    def resolution(self, name: str) -> ModelResolution:
        """The cached :class:`ModelResolution` for ``name`` — model,
        parsed metadata, tag lists, frequency, thresholds, alignment
        plans — built at most once per revision (lock-free COW read on
        the hot path, like :meth:`model`). Raises ``FileNotFoundError``
        when the artifacts are gone (the routes' 404 contract)."""
        cached = self._resolutions.get(name)  # lock-free: COW
        if cached is not None:
            return cached
        model = self.model(name)
        model_dir = os.path.join(self.collection_dir, name)
        metadata = serializer.load_metadata(model_dir)
        try:
            info = serializer.load_info(model_dir)
        except FileNotFoundError:
            info = {}
        resolution = ModelResolution(name, model, metadata, info)
        with self._lock:
            existing = self._resolutions.get(name)
            if existing is not None:
                return existing
            resolutions = dict(self._resolutions)
            resolutions[name] = resolution
            self._resolutions = resolutions
        return resolution

    def warm(self, names: Optional[List[str]] = None) -> List[str]:
        """Load every model in the revision dir (or ``names``); returns the
        names that loaded successfully."""
        if names is None:
            # list_model_dirs skips the builder's crash-safety droppings:
            # atomic-dump staging dirs (possibly half-written by a killed
            # build) and the build journal are never models.
            names = serializer.list_model_dirs(self.collection_dir)
        loaded = []
        for name in names:
            try:
                self.model(name)
                loaded.append(name)
            except Exception as exc:  # noqa: BLE001 - one bad artifact must
                # not abort warming the other 99 (same per-machine
                # isolation as fleet_scores)
                logger.warning(
                    "warm: could not load %s/%s: %r", self.collection_dir, name, exc
                )
        return loaded

    # -- fused fleet scoring -------------------------------------------------

    def spec_bucket(self, spec, precision: str = "f32") -> Tuple[List[str], Any]:
        """
        The (names, stacked device params) bucket for one spec (feedforward
        or LSTM), built from every loaded model of that spec. Restacked
        only when the bucket's membership changed since the last call. The
        stacking work (host round-trip of every member's params) runs
        OUTSIDE the store lock so concurrent single-model serving never
        stalls behind it.

        ``precision`` other than ``f32`` answers the bucket's cast
        (bf16) or weight-quantized (int8) copy, derived from the f32
        master bucket once per (spec, precision) and cached for the
        revision's lifetime (:meth:`_cast_bucket`).
        """
        from ..parallel.fleet import stack_member_params

        if precision and precision != "f32":
            return self._cast_bucket(spec, precision)
        with self._lock:
            cached = self._stacked.get(spec)
            epoch = self._bucket_epoch
            if cached is not None and cached[2] == epoch:
                # Hot path — one dict probe + an int compare. The
                # micro-batcher hits this once per fused batch while the
                # request threads churn; re-deriving membership here
                # (sort + dict build) measurably starves the dispatcher
                # of the GIL under load.
                return cached[0], cached[1]
            specs, models = self._specs, self._models  # COW snapshots
        names = sorted(n for n, s in specs.items() if s == spec)
        if cached is not None and cached[0] == names:
            with self._lock:
                if self._bucket_epoch == epoch:
                    self._stacked[spec] = (cached[0], cached[1], epoch)
            return cached[0], cached[1]
        if not names:
            raise KeyError(f"no loaded models with spec {spec}")

        class _P:  # stack_member_params wants .params carriers
            __slots__ = ("params",)

            def __init__(self, params):
                self.params = params

        host = [
            # gt-lint: disable=jax-device-sync -- one-time member-param
            # stacking at revision load, outside any program span by design
            _P(jax.device_get(_find_estimator(models[n]).params_)) for n in names
        ]
        stacked = jax.device_put(stack_member_params(host))
        with self._lock:
            # Concurrent stackers of the same membership write identical
            # content; a membership change since our snapshot just means
            # the next call restacks (membership is re-derived then).
            if self._bucket_epoch == epoch:
                self._stacked[spec] = (names, stacked, epoch)
        return names, stacked

    #: retained name from before LSTM buckets existed (r3 API)
    feedforward_bucket = spec_bucket

    def _cast_bucket(self, spec, precision: str) -> Tuple[List[str], Any]:
        """The reduced-precision copy of one spec bucket: cast/quantized
        from the f32 master ONCE per (spec, precision) per membership
        epoch. The cast work (a whole-tree device op) runs outside the
        lock, mirroring :meth:`spec_bucket`'s stacking discipline."""
        from ..serve.precision import cast_bucket_params

        with self._lock:
            cached = self._cast_buckets.get((spec, precision))
            epoch = self._bucket_epoch
            if cached is not None and cached[2] == epoch:
                return cached[0], cached[1]
        names, stacked = self.spec_bucket(spec)
        cast = cast_bucket_params(stacked, precision)
        with self._lock:
            # a membership change since our snapshot means the next call
            # recasts against the fresh f32 bucket (same rule as
            # spec_bucket's concurrent-stacker contract)
            if self._bucket_epoch == epoch:
                self._cast_buckets[(spec, precision)] = (names, cast, epoch)
        return names, cast

    def ingest_plan(self, spec):
        """The compiled preprocessing plan for one spec bucket
        (:class:`gordo_tpu.ingest.FleetIngestPlan`, bucket-name order),
        or None when any member's pipeline is not affine-compilable —
        the NEGATIVE verdict is cached per membership epoch too, so an
        uncompilable fleet costs one dict probe per request, not a
        sklearn object-graph walk. Plan extraction runs outside the
        lock, like every other bucket build."""
        from ..ingest import build_fleet_plan

        with self._lock:
            cached = self._ingest_plans.get(spec)
            epoch = self._bucket_epoch
            if cached is not None and cached[2] == epoch:
                return cached[1]
            specs, models = self._specs, self._models  # COW snapshots
        names = sorted(n for n, s in specs.items() if s == spec)
        if not names:
            return None
        plan = build_fleet_plan(
            [(n, models[n]) for n in names], spec.n_features
        )
        with self._lock:
            if self._bucket_epoch == epoch:
                self._ingest_plans[spec] = (names, plan, epoch)
        return plan

    # -- precision-parity gate state -----------------------------------------

    def precision_state(self, spec, precision: str) -> Optional[Dict[str, Any]]:
        """The cached precision-parity gate report for (spec,
        ``precision``), or None when ungated — INCLUDING when the
        bucket's membership changed since the verdict was taken (states
        are epoch-stamped like the cast buckets: a PASS gated on the
        old membership must not let a later-loaded member serve reduced
        unverified, and a racy FAIL must not stick forever). Lock-free
        COW read — this sits on the per-request serving path (the
        engine's governor probes it per batched request)."""
        entry = self._precision_states.get((spec, precision))
        if entry is None:
            return None
        report, epoch = entry
        return report if epoch == self._bucket_epoch else None

    def set_precision_state(
        self,
        spec,
        precision: str,
        report: Dict[str, Any],
        epoch: Optional[int] = None,
    ):
        """Record a gate verdict (COW replace under the lock, like every
        other serving map), stamped with the membership epoch the
        verdict was EVALUATED at (``epoch``; default: current) — a
        verdict taken against an older membership must read as absent,
        not as a fresh PASS/FAIL. The state is revision-fleet-scoped by
        construction: a hot-swapped or invalidated revision drops its
        fleet object, verdicts and all, and the replacement re-gates."""
        with self._lock:
            states = dict(self._precision_states)
            states[(spec, precision)] = (
                report,
                self._bucket_epoch if epoch is None else epoch,
            )
            self._precision_states = states

    def precision_reports(self) -> List[Dict[str, Any]]:
        """Every LIVE cached gate report (current-epoch verdicts only —
        for the engine stats / fleet-status surface)."""
        epoch = self._bucket_epoch
        return [
            report
            for report, stamped in self._precision_states.values()
            if stamped == epoch
        ]

    def loaded_specs(self) -> Dict[str, Any]:
        """The name -> spec map of the loaded JAX models. The returned
        dict is a COW snapshot — treat it as read-only (no per-call copy:
        this sits on the per-request serving path)."""
        return self._specs

    def resident_bytes(self) -> Dict[str, int]:
        """Estimated bytes this fleet keeps resident: per-member params,
        the fused f32 bucket stacks, and the reduced-precision cast
        copies. An *estimate* (``size * itemsize`` over array leaves;
        non-array leaves and host-side pipeline objects are not
        counted) — the fleet-status / Prometheus capacity signal, not an
        allocator audit. Lock-free: reads the COW maps; ``_stacked`` /
        ``_cast_buckets`` values are replaced whole, so a concurrent
        restack at worst skews one bucket."""

        def _tree_bytes(tree: Any) -> int:
            total = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                try:
                    total += int(leaf.size) * int(leaf.dtype.itemsize)
                except (AttributeError, TypeError):
                    continue  # non-array leaf (scalars, None, strings)
            return total

        model_bytes = 0
        models = self._models  # COW snapshot
        for model in models.values():
            estimator = _find_estimator(model)
            if estimator is not None and getattr(estimator, "params_", None) is not None:
                model_bytes += _tree_bytes(estimator.params_)
        stacked_bytes = sum(
            _tree_bytes(params) for (_, params, _) in list(self._stacked.values())
        )
        cast_bytes = sum(
            _tree_bytes(params)
            for (_, params, _) in list(self._cast_buckets.values())
        )
        ingest_bytes = sum(
            plan.nbytes
            for (_, plan, _) in list(self._ingest_plans.values())
            if plan is not None
        )
        return {
            "models": len(models),
            "model_bytes": model_bytes,
            "stacked_bytes": stacked_bytes,
            "cast_bytes": cast_bytes,
            "ingest_bytes": ingest_bytes,
            "total_bytes": model_bytes
            + stacked_bytes
            + cast_bytes
            + ingest_bytes,
        }

    def fleet_scores(
        self, inputs: Dict[str, Any]
    ) -> Tuple[Dict[str, Tuple[np.ndarray, np.ndarray]], Dict[str, Exception]]:
        """
        Score many models in one device program per spec bucket:
        ``inputs[name] -> X`` (raw model-space frames/arrays; host pipeline
        transformers are applied here) returns ``(scores, errors)`` where
        ``scores[name] -> (reconstruction, per-row mse)`` and ``errors``
        records per-machine failures (a broken model never takes the batch
        down). Feedforward AND windowed LSTM models take fused per-spec
        bucket paths; any others fall back to their own predict.
        """
        errors: Dict[str, Exception] = {}
        loadable = []
        for name in inputs:
            try:
                self.model(name)  # ensure loaded + bucketed
                loadable.append(name)
            except Exception as exc:  # noqa: BLE001 - per-machine isolation
                logger.warning("fleet_scores: could not load %s: %r", name, exc)
                if isinstance(exc, FileNotFoundError):
                    errors[name] = exc  # routes map it to a plain 404
                else:
                    load_error = ModelLoadError(name)
                    load_error.__cause__ = exc
                    errors[name] = load_error

        specs = self.loaded_specs()
        by_spec: Dict[Any, List[str]] = {}
        by_lstm_spec: Dict[Any, List[str]] = {}
        fallback: List[str] = []
        for name in loadable:
            spec = specs.get(name)
            if isinstance(spec, FeedForwardSpec):
                by_spec.setdefault(spec, []).append(name)
            elif isinstance(spec, LSTMSpec):
                by_lstm_spec.setdefault(spec, []).append(name)
            else:
                fallback.append(name)

        out: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}

        def mse_vs_raw(prediction: np.ndarray, raw: np.ndarray) -> np.ndarray:
            # Reconstructions live in raw target space (host transformers
            # only feed the estimator input), so error is vs the raw rows,
            # tail-aligned for windowed models' shorter outputs.
            aligned = raw[len(raw) - len(prediction):]
            width = min(prediction.shape[-1], aligned.shape[-1])
            return ((prediction[:, :width] - aligned[:, :width]) ** 2).mean(axis=-1)

        for spec, names in by_spec.items():
            names, member_params, transformed = self._bucket_request(
                spec, names, inputs, errors
            )
            if not names:
                continue
            b_max = max(arr.shape[0] for arr in transformed.values())
            X = np.zeros((len(names), b_max, spec.n_features), np.float32)
            for i, n in enumerate(names):
                X[i, : transformed[n].shape[0]] = transformed[n]
            recon = np.asarray(fleet_forward(spec, member_params, X))
            for i, n in enumerate(names):
                b = transformed[n].shape[0]
                r = recon[i, :b]
                out[n] = (r, mse_vs_raw(r, np.asarray(inputs[n], np.float32)))
        for spec, names in by_lstm_spec.items():
            self._score_lstm_bucket(
                spec, names, inputs, out, errors, mse_vs_raw
            )
        for n in fallback:
            try:
                model = self._models[n]
                prediction = np.asarray(model.predict(inputs[n]))
                out[n] = (
                    prediction,
                    mse_vs_raw(prediction, np.asarray(inputs[n], np.float32)),
                )
            except Exception as exc:  # noqa: BLE001 - per-machine isolation
                logger.warning("fleet_scores: predict failed for %s: %r", n, exc)
                errors[n] = exc
        return out, errors

    def _bucket_request(self, spec, names, inputs, errors):
        """Shared bucket-request staging: sort into bucket order, apply
        host transformers with per-machine error isolation, and gather the
        requested members' stacked params (whole-bucket requests — the
        replay/dashboard pattern — serve straight off the resident stack)."""
        from ..ingest import compiled_enabled

        names = sorted(names)
        bucket_names, stacked = self.spec_bucket(spec)
        rows = {n: i for i, n in enumerate(bucket_names)}
        plan = self.ingest_plan(spec) if compiled_enabled() else None
        transformed = {}
        for n in names:
            try:
                if plan is not None and plan.identity:
                    # the compiled-plan verdict for a bare-estimator
                    # bucket: the pipeline walk IS a float32 cast
                    transformed[n] = np.asarray(inputs[n], np.float32)
                elif plan is not None:
                    # vectorized composed-affine staging off the plan's
                    # host copy — one fused multiply-add instead of a
                    # per-transformer sklearn pass
                    i = rows[n]
                    transformed[n] = np.asarray(
                        np.asarray(inputs[n], np.float32)
                        * plan.host_scale[i]
                        + plan.host_offset[i],
                        np.float32,
                    )
                else:
                    transformed[n] = _host_transform(self._models[n], inputs[n])
            except Exception as exc:  # noqa: BLE001 - per-machine isolation
                logger.warning("fleet_scores: transform failed for %s: %r", n, exc)
                errors[n] = exc
        names = [n for n in names if n in transformed]
        if not names:
            return [], None, {}
        if names == bucket_names:
            member_params = stacked
        else:
            member_params = jax.tree_util.tree_map(
                lambda a: a[np.asarray([rows[n] for n in names])], stacked
            )
        return names, member_params, transformed

    _LSTM_SERVING_BATCH = 256  # window batch of the on-device gather scan

    def _score_lstm_bucket(self, spec, names, inputs, out, errors, mse_vs_raw):
        """
        Fused LSTM scoring: every member's raw series stays ``[b, F]`` and
        windows are gathered on device per scan batch
        (parallel.fleet.fleet_windowed_predict_program) — one device
        program for the whole bucket, same as the feedforward path.
        Window counts honor each estimator's lookahead (the model-offset
        contract), which is per-member data, not part of the compiled
        shape.
        """
        from ..parallel.fleet import fleet_windowed_predict_program

        names, member_params, transformed = self._bucket_request(
            spec, names, inputs, errors
        )
        if not names:
            return
        lookback = spec.lookback_window
        counts = {}
        for n in names:
            estimator = _find_estimator(self._models[n])
            lookahead = getattr(estimator, "lookahead", 0)
            count = transformed[n].shape[0] - lookback - lookahead + 1
            if count <= 0:
                errors[n] = ValueError(
                    f"series of {transformed[n].shape[0]} rows too short for "
                    f"lookback {lookback} (lookahead {lookahead})"
                )
            else:
                counts[n] = count
        kept = [n for n in names if n in counts]
        if not kept:
            return
        if kept != names:
            keep_rows = np.asarray([names.index(n) for n in kept])
            member_params = jax.tree_util.tree_map(
                lambda a: a[keep_rows], member_params
            )
        b_max = max(transformed[n].shape[0] for n in kept)
        # series shorter than one window would make even the zero-padded
        # gather read out of bounds
        b_max = max(b_max, lookback)
        batch = self._LSTM_SERVING_BATCH
        nv_max = -(-max(counts.values()) // batch) * batch
        series = np.zeros((len(kept), b_max, spec.n_features), np.float32)
        order = np.zeros((len(kept), nv_max), np.int32)
        for i, n in enumerate(kept):
            series[i, : transformed[n].shape[0]] = transformed[n]
            order[i, : counts[n]] = np.arange(counts[n])
        predictions = np.asarray(
            fleet_windowed_predict_program(spec, batch)(
                member_params, series, order
            )
        )
        for i, n in enumerate(kept):
            prediction = predictions[i, : counts[n]]
            out[n] = (
                prediction,
                mse_vs_raw(prediction, np.asarray(inputs[n], np.float32)),
            )


def use_pallas() -> bool:
    """Fused Pallas serving kernel: on by default on TPU backends, off
    elsewhere and under ``GORDO_TPU_DISABLE_PALLAS``."""
    # env_bool: a literal `GORDO_TPU_DISABLE_PALLAS=0` now reads as
    # enabled-Pallas instead of silently disabling it (truthy-string bug)
    if env_bool("GORDO_TPU_DISABLE_PALLAS", False):
        return False
    return jax.default_backend() == "tpu"


def serving_backend(precision: str = "f32") -> str:
    """The fused-program backend for one serving precision: the Pallas
    kernel serves the f32 path on TPU; reduced-precision programs run
    the XLA vmapped forward everywhere (bf16 hits the MXU natively
    through XLA; a reduced-precision Pallas kernel is a follow-up —
    dtype tiling differs, see the Pallas guide's tiling table)."""
    if precision and precision != "f32":
        return "xla"
    return "pallas" if use_pallas() else "xla"


def fleet_forward(spec: FeedForwardSpec, stacked_params, X: np.ndarray):
    """
    The fused fleet forward ``X[M, B, F] -> [M, B, F_out]``: Pallas kernel
    on TPU (whole layer stack per grid step, activations in VMEM —
    ops/pallas_dense.py), XLA vmap elsewhere. Both paths share ONE cached
    program table keyed by (spec, backend, precision) so serving requests
    hit a compiled program and cache growth is observable in one place
    (``program_cache_stats`` / the ``gordo_server_program_cache_size``
    Prometheus gauge).
    """
    backend = serving_backend()
    return _fleet_forward_program(spec, backend, False, "f32")(stacked_params, X)


def fleet_forward_gather(
    spec: FeedForwardSpec,
    stacked_params,
    indices: np.ndarray,
    X: np.ndarray,
    precision: str = "f32",
    ingest=None,
):
    """
    The fused gather+forward the micro-batcher runs:
    ``(bucket[N, ...], indices[M], X[M, B, F]) -> [M, B, F_out]``, where
    ``indices`` picks each batch member's row out of the revision's FULL
    resident bucket INSIDE the jitted program. One device dispatch per
    batch — gathering on the host instead (a ``tree_map`` of fancy
    indexing) costs one tiny device program per parameter leaf, which at
    micro-batch rates dominates the fused forward itself. The jit
    signature includes the bucket's member count, which is fixed per
    revision, so the executable count per spec stays bounded by the serve
    shape ladder (now ``× |precisions in use|``).

    ``precision`` selects the reduced-precision program variant; the
    caller passes the MATCHING bucket (``spec_bucket(spec, precision)``)
    — bf16 weights for the bf16 program, the quantized pytree for int8.
    Output is float32 at every precision (the dtype contract).

    ``ingest`` — the device-resident preprocessing plan as a
    ``(scale[N, F], offset[N, F])`` pair (``RevisionFleet.ingest_plan``)
    — selects the INGEST program variant: ``X`` arrives as raw float32
    wire rows and the compiled prologue gathers each member's plan row
    with the same ``indices``, applies ``X*scale+offset`` in float32,
    then casts to the precision's payload dtype before the fused
    forward. None (identity plans included — see
    ``gordo_tpu.ingest.plan``) runs the classic pre-transformed-payload
    program, bit-identical to what it computed before plans existed.
    """
    precision = precision or "f32"
    backend = serving_backend(precision)
    if ingest is not None:
        scale, offset = ingest
        return _fleet_forward_program(spec, backend, True, precision, True)(
            stacked_params, indices, X, scale, offset
        )
    return _fleet_forward_program(spec, backend, True, precision)(
        stacked_params, indices, X
    )


#: keys ever handed to ``_fleet_forward_program`` — lru_cache has no key
#: iteration API, and ``program_cache_stats`` needs the live entries to
#: sum their per-shape executable counts
_program_cache_keys: set = set()


def _fleet_forward_program(
    spec: FeedForwardSpec,
    backend: str,
    gather: bool,
    precision: str = "f32",
    ingest: bool = False,
):
    _program_cache_keys.add((spec, backend, gather, precision, ingest))
    return _build_fleet_forward_program(spec, backend, gather, precision, ingest)


@lru_cache(maxsize=None)
def _build_fleet_forward_program(
    spec: FeedForwardSpec,
    backend: str,
    gather: bool = False,
    precision: str = "f32",
    ingest: bool = False,
):
    """The jitted fused-forward entry for one (spec, backend[, gather,
    precision]). The lru entry holds the jit wrapper; XLA compiles one
    executable per input shape INSIDE it (counted by
    ``program_cache_stats``)."""
    if backend == "pallas":
        from ..ops.pallas_dense import fleet_feedforward_pallas

        fused = lambda params, X: fleet_feedforward_pallas(spec, params, X)  # noqa: E731
    elif precision == "int8":
        from ..serve.precision import forward_feedforward_quantized

        fused = jax.vmap(
            lambda p, x: forward_feedforward_quantized(spec, p, x)
        )
    else:
        from ..models.nn import forward_fn_for

        forward = forward_fn_for(spec)
        if precision == "bf16":
            # the serving spec computes in bf16 whatever the training
            # compute_dtype was; the forward's own contract keeps the
            # OUTPUT float32
            from dataclasses import replace

            run_spec = replace(spec, compute_dtype="bfloat16")
        else:
            run_spec = spec
        fused = jax.vmap(lambda p, x: forward(run_spec, p, x)[0])
    if gather:
        if ingest:
            from ..serve.precision import payload_dtype

            dtype = payload_dtype(precision)

            def run_ingest(params, indices, X, scale, offset):
                member = jax.tree_util.tree_map(lambda a: a[indices], params)
                # the fused preprocessing prologue: raw float32 wire rows
                # through each member's composed affine plan, then into
                # the precision's payload dtype — the same tensor the
                # pre-transformed payload program would have received
                s = scale[indices][:, None, :]
                o = offset[indices][:, None, :]
                Xp = X.astype(jax.numpy.float32) * s + o
                return fused(member, Xp.astype(dtype))

            return jax.jit(run_ingest)

        def run(params, indices, X):
            member = jax.tree_util.tree_map(lambda a: a[indices], params)
            return fused(member, X)

        return jax.jit(run)
    return jax.jit(fused)


def program_cache_stats() -> Dict[str, int]:
    """Serving program-cache sizes: ``programs`` is the number of cached
    (spec, backend, precision) jit entries, ``signatures`` the number of
    XLA executables compiled inside them (distinct argument shapes) —
    the number that must stay bounded by the serve shape ladder. A
    ``signatures`` of -1 means this jax version hides the jit cache."""
    signatures = 0
    by_precision: Dict[str, int] = {}
    for (spec, backend, gather, precision, ingest) in list(_program_cache_keys):
        by_precision[precision] = by_precision.get(precision, 0) + 1
        program = _build_fleet_forward_program(
            spec, backend, gather, precision, ingest
        )
        try:
            if signatures >= 0:
                signatures += program._cache_size()
        except AttributeError:  # jit cache introspection is version-bound
            signatures = -1
    return {
        "programs": _build_fleet_forward_program.cache_info().currsize,
        "signatures": signatures,
        "by_precision": by_precision,
    }


class FleetModelStore:
    """LRU of :class:`RevisionFleet`s keyed by (real) revision directory.

    ``N_CACHED_REVISIONS`` (env, default 2) bounds how many *revisions*
    stay resident — the model axis within a revision is never evicted,
    which is the point: the reference's pressure point was per-model
    eviction, not revision count.

    Lifecycle extensions (``gordo_tpu.lifecycle``): :meth:`route`
    resolves a requested collection dir through the hot-swap redirect a
    promotion installed (:meth:`swap`) and the canary traffic slice
    (:meth:`set_canary`) — requests route ONCE, at revision-resolution
    time, so one request never mixes base and canary artifacts (model
    from one, params from the other). A swap never touches an existing
    :class:`RevisionFleet`: in-flight work pinned to the old fleet
    object keeps scoring its device-resident snapshot (the same
    contract the DELETE-revision race relies on), while requests routed
    after the swap resolve the new — pre-warmed — fleet.
    """

    def __init__(self, max_revisions: Optional[int] = None):
        if max_revisions is None:
            # Validated, never trusted: this constructor runs at module
            # import (the process-wide STORE below), so a malformed env
            # var must degrade to the default, not kill every worker at
            # boot.
            max_revisions = env_int("N_CACHED_REVISIONS", 2)
            if max_revisions < 1:
                logger.warning(
                    "N_CACHED_REVISIONS=%d is not a positive revision "
                    "count; using 2",
                    max_revisions,
                )
                max_revisions = 2
        self.max_revisions = max_revisions
        self._lock = threading.Lock()
        self._revisions: "OrderedDict[str, RevisionFleet]" = OrderedDict()
        #: lock-free fast path for the overwhelmingly common case of every
        #: request hitting the same revision: one atomic tuple read
        #: instead of realpath() syscalls + the store lock + an
        #: OrderedDict reorder PER REQUEST (all three are GIL-handoff
        #: points that convoy under concurrent serving load)
        self._mru: Optional[Tuple[str, RevisionFleet]] = None
        #: hot-swap redirects: requested dir -> served dir. Mutated only
        #: under the lock; read lock-free (dict.get is atomic under the
        #: GIL) on the per-request routing path.
        self._redirects: Dict[str, str] = {}
        #: canary slice: (source dir, canary dir, every-nth period) —
        #: one atomic tuple read per routed request; None in steady
        #: state. The tick is intentionally unlocked: under concurrent
        #: load the slice is approximate (lost increments skew it a
        #: request or two), which is fine for traffic splitting and
        #: keeps the hot path lock-free.
        self._canary: Optional[Tuple[str, str, int]] = None
        self._canary_tick = 0

    # -- lifecycle routing --------------------------------------------------

    @staticmethod
    def _route_key(collection_dir: str) -> str:
        """Routing keys are normpath'd strings: the env var may carry a
        trailing slash while the supervisor/restore path installs
        normalized sources — a cosmetic difference must not silently
        disable a recorded promotion or a canary slice. (normpath, not
        realpath: no syscalls on the per-request path.)"""
        return os.path.normpath(collection_dir)

    def route(self, collection_dir: str) -> str:
        """The directory a request for ``collection_dir`` should serve
        from, after the hot-swap redirect and the canary slice. Resolved
        once per request (at revision resolution) so every artifact the
        request touches — model, metadata, params — comes from ONE
        revision."""
        key = self._route_key(collection_dir)
        canary = self._canary
        if canary is not None and canary[0] == key:
            self._canary_tick += 1
            if self._canary_tick % canary[2] == 0:
                return canary[1]
        return self._redirects.get(key, collection_dir)

    def swap(
        self, collection_dir: str, new_dir: str, warm: bool = True
    ) -> RevisionFleet:
        """Zero-downtime hot swap: requests for ``collection_dir`` serve
        ``new_dir`` from now on. The new fleet is loaded (and by default
        warmed) BEFORE the redirect lands, so no request ever waits on
        cold artifact loads; requests already in flight keep the fleet
        object they resolved — nothing is dropped or torn. Swapping a
        dir onto itself removes the redirect (rollback to disk truth)."""
        fleet = self._ensure_fleet(new_dir, warm=warm)
        key = self._route_key(collection_dir)
        with self._lock:
            if os.path.realpath(new_dir) == os.path.realpath(collection_dir):
                self._redirects.pop(key, None)
            else:
                self._redirects[key] = new_dir
            canary = self._canary
            if canary is not None and canary[0] == key:
                self._canary = None
            # the swapped-in dir is about to be the hottest key
            self._mru = (new_dir, fleet)
        return fleet

    def set_canary(
        self,
        collection_dir: str,
        canary_dir: str,
        fraction: float,
        warm: bool = True,
    ) -> RevisionFleet:
        """Route ``~fraction`` of the traffic for ``collection_dir`` to
        ``canary_dir`` (every Nth routed request, N = round(1/fraction)
        — deterministic, no per-request RNG). The canary fleet is
        pre-warmed before any traffic lands on it."""
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"canary fraction must be in (0, 1]: {fraction}")
        fleet = self._ensure_fleet(canary_dir, warm=warm)
        period = max(1, int(round(1.0 / fraction)))
        with self._lock:
            self._canary = (self._route_key(collection_dir), canary_dir, period)
        return fleet

    def clear_canary(self, collection_dir: Optional[str] = None) -> None:
        """Stop canary routing (for ``collection_dir``, or whatever is
        canarying); in-flight canary-routed requests finish against the
        still-resident canary fleet."""
        with self._lock:
            canary = self._canary
            if canary is not None and (
                collection_dir is None
                or canary[0] == self._route_key(collection_dir)
            ):
                self._canary = None

    def canary_status(self) -> Optional[Dict[str, Any]]:
        canary = self._canary
        if canary is None:
            return None
        return {
            "source": canary[0],
            "canary": canary[1],
            "fraction": 1.0 / canary[2],
        }

    def revision_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-resident-revision byte estimates, keyed by the revision
        dir's basename (``RevisionFleet.resident_bytes``; the key set is
        bounded by ``N_CACHED_REVISIONS``). The fleet-status ``serving``
        section and the ``gordo_store_revision_bytes`` gauge read this."""
        with self._lock:
            revisions = list(self._revisions.items())
        return {
            os.path.basename(key) or key: fleet.resident_bytes()
            for key, fleet in revisions
        }

    def _rerank_mru_locked(self) -> None:
        """Re-rank the lock-free fast path's fleet before any eviction
        decision (caller holds the lock): requests served through
        ``_mru`` never refresh their LRU slot, so the hottest revision
        can look least-recently-used — evicting it would force every
        fast-path request onto a cold reload."""
        mru = self._mru
        if mru is None:
            return
        for mru_key, mru_fleet in self._revisions.items():
            if mru_fleet is mru[1]:
                self._revisions.move_to_end(mru_key)
                break

    def _ensure_fleet(self, collection_dir: str, warm: bool) -> RevisionFleet:
        """The ONE get-or-create path for resident fleets — request
        path (:meth:`fleet`) and lifecycle path (swap/set_canary) share
        it, so eviction and MRU re-rank behavior cannot drift apart.
        Model loads (``warm``) run OUTSIDE the store lock, like every
        other load path. The re-rank walk is O(max_revisions)."""
        key = os.path.realpath(collection_dir)
        with self._lock:
            fleet = self._revisions.get(key)
            if fleet is None:
                self._rerank_mru_locked()
                fleet = RevisionFleet(key)
                self._revisions[key] = fleet
                while len(self._revisions) > self.max_revisions:
                    evicted_key, _ = self._revisions.popitem(last=False)
                    logger.info("Evicting served revision %s", evicted_key)
            else:
                self._revisions.move_to_end(key)
        if warm:
            fleet.warm()
        return fleet

    def fleet(self, collection_dir: str) -> RevisionFleet:
        mru = self._mru
        if mru is not None and mru[0] == collection_dir:
            return mru[1]
        fleet = self._ensure_fleet(collection_dir, warm=False)
        with self._lock:
            self._mru = (collection_dir, fleet)
        return fleet

    def get_model(self, collection_dir: str, name: str) -> Any:
        return self.fleet(collection_dir).model(name)

    def invalidate(self, collection_dir: str):
        key = os.path.realpath(collection_dir)
        with self._lock:
            self._mru = None  # conservatively, whatever alias it holds
            self._revisions.pop(key, None)
            # Routing that TARGETS the invalidated dir is stale too: a
            # deleted canary must stop taking traffic, and a redirect
            # onto a deleted revision must fall back to disk truth.
            # Routing FROM it survives — a redirect is serving state,
            # not a cache of the source dir's content.
            canary = self._canary
            if canary is not None and os.path.realpath(canary[1]) == key:
                self._canary = None
            for source, target in list(self._redirects.items()):
                if os.path.realpath(target) == key:
                    del self._redirects[source]

    def clear(self):
        with self._lock:
            self._mru = None
            self._revisions.clear()
            self._redirects.clear()
            self._canary = None


#: Process-wide store (gunicorn gthread workers share it per process, like
#: the reference's module-level lru_cache).
STORE = FleetModelStore()
