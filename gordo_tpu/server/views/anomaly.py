"""
Anomaly route: ``POST /gordo/v0/<project>/<name>/anomaly/prediction``.

Reference parity: gordo/server/blueprints/anomaly.py — requires ``y``,
calls ``model.anomaly(X, y, frequency)``, 422 when the served model is not
an anomaly detector, drops the ``smooth-*`` columns unless ``?all_columns``,
answers JSON or parquet.
"""

import logging
import timeit
from typing import Any, Dict

from .. import utils as server_utils
from ..properties import get_frequency

logger = logging.getLogger(__name__)

DELETED_FROM_RESPONSE_COLUMNS = (
    "smooth-tag-anomaly-scaled",
    "smooth-total-anomaly-scaled",
    "smooth-tag-anomaly-unscaled",
    "smooth-total-anomaly-unscaled",
)


def _unprocessable_response(ctx):
    """The route's historical AttributeError → 422 mapping (non-detector
    model OR ``require_thresholds`` unmet)."""
    return ctx.json_response(
        {
            "message": "Model is not an AnomalyDetector, it is of type: "
            f"{type(ctx.model)}"
        },
        status=422,
    )


def post_anomaly_prediction(ctx, gordo_project: str, gordo_name: str):
    from ...serve import BatchShedError, get_engine
    from .. import model_io, wire
    from .base import encode_wire_response

    start_time = timeit.default_timer()
    with ctx.stage("model_resolve"):
        server_utils.resolve_model(ctx, gordo_name)
    # negotiate before decoding/scoring: unacceptable Accept → 406 early
    response_format = wire.response_format(ctx.request)
    with ctx.stage("data_decode"):
        server_utils.extract_X_y(ctx)

    if ctx.y is None:
        return ctx.json_response(
            {"message": "Cannot perform anomaly without 'y' to compare against."},
            status=400,
        )

    keep_smooth = ctx.request.args.get("all_columns") is not None
    # The columnar fast path: for the stock DiffBased detector family the
    # reconstruction is the only model work — threshold/confidence math
    # composes as numpy columns in response_assemble (same numbers, no
    # MultiIndex frame). Custom detectors keep the legacy anomaly() path.
    fast = wire.columnar_enabled() and wire.supports_columnar_anomaly(
        ctx.model
    )
    anomaly_df = None
    model_output = None
    # device_ingest: stage the request onto the device for the compiled
    # (engine-less) path — sequential with inference, like the base
    # route, so the stage split attributes wire→device staging apart
    # from the device program itself.
    staged = None
    if get_engine() is None and (
        fast or model_io.accepts_model_output(ctx.model)
    ):
        with ctx.stage("device_ingest"):
            staged = model_io.stage_compiled_input(ctx, gordo_name, ctx.X)
    try:
        with ctx.stage("inference"):
            # Micro-batching: when the detector accepts a precomputed
            # model_output, the reconstruction can coalesce with
            # concurrent requests into one fused program; the detector's
            # threshold/confidence math still runs per request.
            kwargs = {"frequency": get_frequency(ctx)}
            if model_io.accepts_model_output(ctx.model):
                model_output = model_io.batched_model_output(
                    ctx, gordo_name, ctx.X
                )
            if model_output is None and staged is not None:
                try:
                    model_output = model_io.compiled_output(staged)
                except Exception:  # noqa: BLE001 - compiled path is an
                    # optimization; device refusal → host fallback
                    logger.exception(
                        "compiled ingest scoring failed; host fallback"
                    )
            if fast:
                if model_output is None:
                    # the same reconstruction anomaly() would compute
                    model_output = (
                        ctx.model.predict(ctx.X)
                        if hasattr(ctx.model.base_estimator, "predict")
                        else ctx.model.transform(ctx.X)
                    )
            else:
                if model_output is not None:
                    kwargs["model_output"] = model_output
                anomaly_df = ctx.model.anomaly(ctx.X, ctx.y, **kwargs)
    except BatchShedError as exc:
        return model_io.shed_response(ctx, exc)
    except AttributeError:
        return _unprocessable_response(ctx)
    except ValueError as err:
        # Client-data problem (e.g. fewer rows than a windowed model's
        # lookback) — same ValueError→400 contract as the base route.
        logger.error("Failed to compute anomalies: %s", err)
        return ctx.json_response({"error": f"ValueError: {err}"}, status=400)

    # same response_assemble stage as the base route: threshold math /
    # column composition (fast path) or column filtering + frame walk
    # (legacy) is host-pipeline time the per-stage attribution must cover
    table = None
    try:
        with ctx.stage("response_assemble"):
            if fast:
                resolution = ctx.resolution
                table = wire.anomaly_table(
                    ctx.model,
                    ctx.X,
                    ctx.y,
                    model_output,
                    frequency=kwargs["frequency"],
                    keep_smooth=keep_smooth,
                    # the fleet resolution cache's pre-extracted
                    # threshold arrays (same values, no per-request
                    # Series→array extraction)
                    thresholds=(
                        resolution.feature_thresholds if resolution else None
                    ),
                    aggregate=(
                        resolution.aggregate_threshold if resolution else None
                    ),
                )
                if not table.unique_labels():
                    table = None
                    if model_io.accepts_model_output(ctx.model):
                        kwargs["model_output"] = model_output
                    anomaly_df = ctx.model.anomaly(ctx.X, ctx.y, **kwargs)
            if table is None and not keep_smooth:
                columns_for_delete = [
                    column
                    for column in anomaly_df
                    if column[0] in DELETED_FROM_RESPONSE_COLUMNS
                ]
                anomaly_df = anomaly_df.drop(columns=columns_for_delete)
    except AttributeError:
        # require_thresholds unmet surfaces here on the fast path — the
        # same 422 the legacy inference-stage anomaly() answered
        return _unprocessable_response(ctx)
    except ValueError as err:
        logger.error("Failed to compute anomalies: %s", err)
        return ctx.json_response({"error": f"ValueError: {err}"}, status=400)

    extra: Dict[Any, Any] = {}
    if response_format != wire.PARQUET:
        extra["time-seconds"] = f"{timeit.default_timer() - start_time:.4f}"
    return encode_wire_response(
        ctx, response_format, table=table, frame=anomaly_df, extra=extra
    )
