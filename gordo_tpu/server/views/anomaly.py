"""
Anomaly route: ``POST /gordo/v0/<project>/<name>/anomaly/prediction``.

Reference parity: gordo/server/blueprints/anomaly.py — requires ``y``,
calls ``model.anomaly(X, y, frequency)``, 422 when the served model is not
an anomaly detector, drops the ``smooth-*`` columns unless ``?all_columns``,
answers JSON or parquet.
"""

import logging
import timeit
from typing import Any, Dict

from .. import utils as server_utils
from ..properties import get_frequency

logger = logging.getLogger(__name__)

DELETED_FROM_RESPONSE_COLUMNS = (
    "smooth-tag-anomaly-scaled",
    "smooth-total-anomaly-scaled",
    "smooth-tag-anomaly-unscaled",
    "smooth-total-anomaly-unscaled",
)


def post_anomaly_prediction(ctx, gordo_project: str, gordo_name: str):
    from ...serve import BatchShedError
    from .. import model_io

    start_time = timeit.default_timer()
    with ctx.stage("model_resolve"):
        server_utils.require_model(ctx, gordo_name)
    with ctx.stage("data_decode"):
        server_utils.extract_X_y(ctx)

    if ctx.y is None:
        return ctx.json_response(
            {"message": "Cannot perform anomaly without 'y' to compare against."},
            status=400,
        )

    try:
        with ctx.stage("inference"):
            # Micro-batching: when the detector accepts a precomputed
            # model_output, the reconstruction can coalesce with
            # concurrent requests into one fused program; the detector's
            # threshold/confidence math still runs per request.
            kwargs = {"frequency": get_frequency(ctx)}
            if model_io.accepts_model_output(ctx.model):
                model_output = model_io.batched_model_output(
                    ctx, gordo_name, ctx.X
                )
                if model_output is not None:
                    kwargs["model_output"] = model_output
            anomaly_df = ctx.model.anomaly(ctx.X, ctx.y, **kwargs)
    except BatchShedError as exc:
        return model_io.shed_response(ctx, exc)
    except AttributeError:
        return ctx.json_response(
            {
                "message": "Model is not an AnomalyDetector, it is of type: "
                f"{type(ctx.model)}"
            },
            status=422,
        )
    except ValueError as err:
        # Client-data problem (e.g. fewer rows than a windowed model's
        # lookback) — same ValueError→400 contract as the base route.
        logger.error("Failed to compute anomalies: %s", err)
        return ctx.json_response({"error": f"ValueError: {err}"}, status=400)

    # same response_assemble stage as the base route: column filtering +
    # frame→wire-dict conversion is host-pipeline time the per-stage
    # attribution must cover
    with ctx.stage("response_assemble"):
        if ctx.request.args.get("all_columns") is None:
            columns_for_delete = [
                column
                for column in anomaly_df
                if column[0] in DELETED_FROM_RESPONSE_COLUMNS
            ]
            anomaly_df = anomaly_df.drop(columns=columns_for_delete)

        if ctx.request.args.get("format") == "parquet":
            payload = server_utils.dataframe_into_parquet_bytes(anomaly_df)
        else:
            payload = None
            context: Dict[Any, Any] = dict()
            context["data"] = server_utils.dataframe_to_dict(anomaly_df)
    if payload is not None:
        return ctx.file_response(payload)
    context["time-seconds"] = f"{timeit.default_timer() - start_time:.4f}"
    return ctx.json_response(context)
