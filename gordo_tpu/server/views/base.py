"""
Base model routes under ``/gordo/v0/<project>/...``.

Reference parity: gordo/server/blueprints/base.py — POST prediction, GET
metadata/healthcheck, GET download-model (pickle stream), GET models, GET
revisions, GET expected-models, DELETE revision/<revision>.
"""

import logging
import os
import timeit
import traceback
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

import gordo_tpu
from ... import serializer
from ...models import utils as model_utils
from ...telemetry import load_status as load_build_status
from .. import model_io
from .. import utils as server_utils
from ..properties import get_tags, get_target_tags

logger = logging.getLogger(__name__)


def encode_wire_response(
    ctx,
    response_format: str,
    table=None,
    frame=None,
    extra: Optional[Dict[str, Any]] = None,
    status: int = 200,
):
    """
    The shared serialize stage of the scoring routes: one columnar
    :class:`~..wire.WireTable` (the fast path) or one legacy MultiIndex
    frame, encoded per the negotiated response format. ``extra`` carries
    the scalar envelope fields in wire order (``time-seconds``);
    ``revision`` is appended here for the fast encoders exactly where
    ``json_response`` would have stamped it, so JSON bytes stay
    byte-identical to the legacy serializer's.
    """
    from .. import wire

    extra_items: Dict[str, Any] = dict(extra or {})
    if ctx.revision is not None:
        extra_items["revision"] = ctx.revision

    # serialize is a DEFERRED stage: nothing meaningful runs between
    # the encode and the request's end (response construction is ~30µs),
    # so the interval closes at _finalize's end-of-request clock read —
    # otherwise the GIL preemption a long encode earns under thread load
    # parks the thread right after a conventional span's exit, leaking
    # ~20ms p50 of scheduler wait into unattributed walltime. The
    # sampling profiler still attributes encode frames to the stage via
    # current_stage (left set until _finalize's record closes it).
    serialize_start = timeit.default_timer()
    ctx.current_stage = "serialize"

    if response_format == wire.PARQUET:
        payload = server_utils.dataframe_into_parquet_bytes(
            frame if frame is not None else table.to_frame()
        )
        response = ctx.file_response(payload)
        ctx.deferred_stage = ("serialize", serialize_start)
        return response

    if response_format == wire.ARROW:
        if table is None:
            # bridge legacy pandas assemblies (columnar switched off,
            # custom detectors) into the Arrow encoder — only genuinely
            # unrepresentable responses (duplicate labels) refuse
            bridged = wire.WireTable.from_frame(frame)
            if not bridged.unique_labels():
                raise server_utils.ServerError(
                    "Response columns are not representable as Arrow "
                    "(duplicate labels); request JSON instead",
                    status=400,
                )
            table = bridged
        body = wire.encode_table(table, extra_items)
        response = ctx.raw_response(body, wire.ARROW_CONTENT_TYPE, status)
        ctx.deferred_stage = ("serialize", serialize_start)
        return response

    # JSON (the default wire format)
    if table is None:
        context: Dict[Any, Any] = {
            "data": server_utils.dataframe_to_dict(frame)
        }
        context.update(extra or {})  # json_response appends revision
        return ctx.json_response(context, status=status)
    if wire.stream_enabled():
        # streamed serialize: chunks encode during the WSGI write loop —
        # off the request's instrumented path (docs/serving.md caveat)
        return ctx.raw_response(
            wire.iter_encode_response(table, extra_items),
            wire.JSON_CONTENT_TYPE,
            status,
        )
    body = wire.encode_response(table, extra_items)
    response = ctx.raw_response(body, wire.JSON_CONTENT_TYPE, status)
    ctx.deferred_stage = ("serialize", serialize_start)
    return response


def post_prediction(ctx, gordo_project: str, gordo_name: str):
    """
    Run the model on client-provided ``X`` and answer the
    start/end/model-input/model-output response frame as JSON (the
    default — byte-identical to the pre-columnar serializer), Arrow IPC
    (``Accept: application/vnd.apache.arrow.stream``), or parquet
    (``?format=parquet`` or content negotiation).

    With micro-batching on (``GORDO_TPU_BATCHING``), concurrent requests
    for same-architecture models coalesce into one fused fleet program
    (``gordo_tpu.serve``); admission control maps to 429/504 and
    everything unbatchable falls back to the model's own predict.
    """
    from ...serve import BatchShedError, get_engine
    from .. import wire

    with ctx.stage("model_resolve"):
        server_utils.resolve_model(ctx, gordo_name)
    # negotiate BEFORE decoding/scoring: an unacceptable Accept header
    # answers 406 without paying for the model run
    response_format = wire.response_format(ctx.request)
    with ctx.stage("data_decode"):
        server_utils.extract_X_y(ctx)

    context: Dict[Any, Any] = dict()
    X = ctx.X
    process_request_start_time_s = timeit.default_timer()

    # device_ingest is its own stage, SEQUENTIAL with (never nested in)
    # inference: the wire→device staging the compiled path does is the
    # cost data_decode used to hide, and the stage attribution must show
    # the two separately (docs/observability.md "Stage reference"). With
    # the micro-batcher on, the engine stages instead and reports its
    # own device_ingest interval.
    staged = None
    if get_engine() is None:
        with ctx.stage("device_ingest"):
            staged = model_io.stage_compiled_input(ctx, gordo_name, X)

    try:
        with ctx.stage("inference"):
            output = None
            if staged is not None:
                try:
                    output = model_io.compiled_output(staged)
                except Exception:  # noqa: BLE001 - the compiled path is
                    # an optimization: a device refusal keeps the host
                    # predict path, never fails the request
                    logger.exception(
                        "compiled ingest scoring failed; host fallback"
                    )
            if output is None:
                output = model_io.batched_model_output(ctx, gordo_name, X)
            if output is None:
                output = model_io.get_model_output(model=ctx.model, X=X)
    except BatchShedError as exc:
        return model_io.shed_response(ctx, exc)
    except ValueError as err:
        logger.error(
            "Failed to predict or transform; error: %s - \nTraceback: %s",
            err,
            traceback.format_exc(),
        )
        context["error"] = f"ValueError: {str(err)}"
        return ctx.json_response(context, status=400)
    except Exception as exc:
        logger.error(
            "Failed to predict or transform; error: %s - \nTraceback: %s",
            exc,
            traceback.format_exc(),
        )
        context["error"] = "Something unexpected happened; check your input data"
        return ctx.json_response(context, status=400)

    logger.debug(
        "Calculating model output took %.4fs",
        timeit.default_timer() - process_request_start_time_s,
    )
    # response_assemble is its own stage (distinct from `serialize`, the
    # wire encode): response composition is a big slice of full-route
    # walltime, and the per-stage attribution the trace/bench surfaces
    # report must cover it to explain the route. The columnar fast path
    # composes numpy columns; the legacy pandas frame remains the escape
    # hatch (GORDO_TPU_WIRE_COLUMNAR=0) and the duplicate-label fallback.
    table = None
    frame = None
    with ctx.stage("response_assemble"):
        if wire.columnar_enabled():
            table = wire.prediction_table(
                get_tags(ctx),
                X if isinstance(X, pd.DataFrame) else pd.DataFrame(X),
                output,
                target_tags=get_target_tags(ctx),
            )
            if not table.unique_labels():
                table = None
        if table is None:
            frame = model_utils.make_base_dataframe(
                tags=get_tags(ctx),
                model_input=X.values if isinstance(X, pd.DataFrame) else X,
                model_output=output,
                target_tag_list=get_target_tags(ctx),
                index=X.index,
            )
    return encode_wire_response(
        ctx, response_format, table=table, frame=frame
    )


def post_fleet_prediction(ctx, gordo_project: str):
    """
    TPU-native extension route (no reference analog): score MANY models in
    one request. Body ``{"X": {<model-name>: <dataframe-dict>}}``; models
    sharing an architecture are stacked and scored as one fused device
    program (Pallas kernel on TPU, XLA vmap elsewhere) instead of N
    pickle-load + predict round trips.

    Response per model, lean mode (default): ``model-output`` rows and the
    ``total-anomaly-unscaled`` per-row mse. With ``?full`` (or body
    ``{"full": true}``), anomaly-detector machines instead answer the FULL
    anomaly frame — the same column groups the single-model
    ``anomaly/prediction`` route emits (tag-anomaly scaled/unscaled,
    totals, confidence; ``smooth-*`` kept only with ``?all_columns``) —
    assembled from the fused reconstruction, so the Influx replay can
    carry the reference client's complete series set
    (reference argo-workflow.yml.template:1296-1410). ``y`` defaults to
    ``X`` per machine (autoencoder replay); a body ``"y"`` dict overrides
    per machine.
    """
    from ..fleet_store import STORE, ModelLoadError
    from .. import wire

    request = ctx.request
    response_format = wire.response_format(request)
    if response_format == wire.PARQUET:
        raise server_utils.ServerError(
            "The fleet route serves JSON or Arrow, not parquet",
            status=406,
        )
    fleet_for_meta = STORE.fleet(ctx.collection_dir)

    frames: Dict[str, pd.DataFrame] = {}
    y_frames: Dict[str, pd.DataFrame] = {}
    metadatas: Dict[str, Any] = {}
    errors: Dict[str, Dict[str, Any]] = {}

    def resolve_machine(name: str):
        """Per-machine resolution through the fleet cache, mapped to the
        route's per-machine error entries (never the whole batch's)."""
        server_utils.validate_gordo_name(name)
        server_utils.check_metadata_file(ctx.collection_dir, name)
        return fleet_for_meta.resolution(name)

    body_format = wire.request_format(request)
    with ctx.stage("data_decode"):
        if body_format == wire.ARROW:
            full, keep_smooth = _decode_fleet_arrow(
                ctx, resolve_machine, frames, y_frames, metadatas, errors
            )
        else:
            full, keep_smooth = _decode_fleet_json(
                ctx, resolve_machine, frames, y_frames, metadatas, errors
            )

    data: Dict[str, Any] = {}
    if frames:
        with ctx.stage("inference"):
            scores, score_errors = STORE.fleet(ctx.collection_dir).fleet_scores(
                frames
            )
        _record_fleet_health(ctx, frames, scores, score_errors)
        for name, exc in score_errors.items():
            # Filesystem/internal errors never echo raw text (it can carry
            # server paths; details live in the server log); client-data
            # ValueErrors are user-facing messages and do echo, matching
            # the single-model routes.
            if isinstance(exc, FileNotFoundError):
                errors[name] = {
                    "error": f"No such model found: '{name}'",
                    "status": 404,
                }
            elif isinstance(exc, ModelLoadError):
                errors[name] = {
                    "error": "Model could not be loaded",
                    "status": 500,
                }
            elif isinstance(exc, ValueError):
                # client-data problem (e.g. too few rows for a windowed
                # model) — same ValueError→400 echo contract as the
                # single-model prediction and anomaly routes
                errors[name] = {
                    "error": f"Scoring failed (ValueError: {exc})",
                    "status": 400,
                }
            elif isinstance(exc, TypeError):
                # likely client data, but the text may describe server
                # internals — generic message, like the single-model routes
                errors[name] = {
                    "error": "Something unexpected happened; "
                    "check your input data",
                    "status": 400,
                }
            else:
                errors[name] = {
                    "error": f"Scoring failed ({type(exc).__name__})",
                    "status": 500,
                }
        # Formatting a DatetimeIndex to wire strings costs ~1ms per
        # machine and the fleet's machines typically share ONE index —
        # format each distinct index once per request (the wire format
        # itself lives in server_utils.index_wire_keys, shared with the
        # single-model routes).
        formatted: List[Tuple[Any, List[str]]] = []

        def index_keys(index) -> List[str]:
            for seen, keys in formatted:
                if index.equals(seen):
                    return keys
            keys = server_utils.index_wire_keys(index)
            formatted.append((index, keys))
            return keys

        fleet = STORE.fleet(ctx.collection_dir) if full else None
        as_arrow = response_format == wire.ARROW
        # per-machine wire assembly is the fleet route's host-pipeline
        # tail — staged like the single-model routes' response_assemble
        with ctx.stage("response_assemble"):
            for name, (reconstruction, mse) in scores.items():
                index = frames[name].index
                recon = np.asarray(reconstruction)
                if len(recon) > len(index):
                    # more output rows than input rows can only be a broken
                    # model/transformer; zip would silently misalign
                    errors[name] = {
                        "error": "Scoring failed (output longer than input)",
                        "status": 500,
                    }
                    continue
                if full:
                    try:
                        table, frame, error = _full_anomaly_entry(
                            fleet,
                            name,
                            frames[name],
                            y_frames.get(name, frames[name]),
                            metadatas[name],
                            recon,
                            keep_smooth,
                        )
                    except Exception:  # noqa: BLE001 - per-machine isolation:
                        # custom detectors run arbitrary code; one broken
                        # machine must never 500 the batch (route contract)
                        logger.exception(
                            "full anomaly assembly failed for %s", name
                        )
                        table, frame, error = None, None, {
                            "error": "Anomaly assembly failed",
                            "status": 500,
                        }
                    if error is not None:
                        errors[name] = error
                        continue
                    if frame is not None:
                        # the legacy pandas assembly ran (custom
                        # detector or columnar switched off) — both
                        # encoders can still carry it, except
                        # duplicate-label frames: JSON keeps pandas'
                        # legacy duplicate semantics, Arrow can't
                        # express them (per-machine error, never a
                        # whole-batch 500)
                        bridged = wire.WireTable.from_frame(frame)
                        if bridged.unique_labels():
                            table = bridged
                        elif as_arrow:
                            errors[name] = {
                                "error": "Response columns are not "
                                "representable as Arrow "
                                "(duplicate labels)",
                                "status": 400,
                            }
                            continue
                        else:
                            data[name] = server_utils.dataframe_to_dict(
                                frame
                            )
                            continue
                    if table is not None:
                        data[name] = (
                            table if as_arrow else table.to_wire_dict()
                        )
                        continue
                    # not an anomaly detector: lean entry below
                aligned_index = index[len(index) - len(recon):]
                if as_arrow:
                    data[name] = _lean_table(aligned_index, recon, mse)
                    continue
                keys = index_keys(aligned_index)
                # direct dict assembly — same wire shape as
                # dataframe_to_dict(DataFrame(reconstruction)) with
                # stringified columns, without re-building frames per machine
                data[name] = {
                    "model-output": {
                        str(col): dict(zip(keys, recon[:, col].tolist()))
                        for col in range(recon.shape[1])
                    },
                    "total-anomaly-unscaled": dict(
                        zip(keys, np.asarray(mse).tolist())
                    ),
                }

    status = 200 if data else 400
    if response_format == wire.ARROW:
        # deferred serialize, like encode_wire_response: the interval
        # closes at _finalize so the post-encode GIL park stays attributed
        serialize_start = timeit.default_timer()
        ctx.current_stage = "serialize"
        entries = {
            name: wire.encode_table(table) for name, table in data.items()
        }
        body = wire.pack_streams(
            entries,
            extra={"errors": errors, "revision": ctx.revision},
        )
        response = ctx.raw_response(body, wire.ARROW_CONTENT_TYPE, status)
        ctx.deferred_stage = ("serialize", serialize_start)
        return response
    context: Dict[str, Any] = {"data": data}
    if errors:
        context["errors"] = errors
    return ctx.json_response(context, status=status)


def _lean_table(index, recon: np.ndarray, mse) -> "Any":
    """The lean fleet entry (``model-output`` + per-row mse) as a
    columnar table — the Arrow twin of the JSON path's direct dict."""
    from .. import wire

    columns = [
        wire.WireColumn("model-output", str(col), recon[:, col])
        for col in range(recon.shape[1])
    ]
    columns.append(
        wire.WireColumn("total-anomaly-unscaled", "", np.asarray(mse))
    )
    return wire.WireTable(pd.Index(index), columns)


def _decode_fleet_json(
    ctx, resolve_machine, frames, y_frames, metadatas, errors
) -> Tuple[bool, bool]:
    """The legacy JSON fleet body: ``{"X": {name: frame-dict}, "y":
    {...}, "full": bool}`` — per-machine verification against the
    resolution cache's tag lists, malformed machines isolated into
    ``errors``."""
    request = ctx.request
    body = request.get_json(silent=True) if request.is_json else None
    if not body or not isinstance(body.get("X"), dict) or not body["X"]:
        raise server_utils.ServerError(
            'Fleet prediction needs a JSON body {"X": {<model-name>: frame}}'
        )
    full = request.args.get("full") is not None or bool(body.get("full"))
    keep_smooth = request.args.get("all_columns") is not None
    y_payloads = body.get("y") if isinstance(body.get("y"), dict) else {}

    for name, payload in body["X"].items():
        try:
            resolution = resolve_machine(name)
            frame = server_utils.dataframe_from_dict(payload)
            frames[name] = server_utils.verify_dataframe(
                frame, resolution.tag_names
            )
            metadatas[name] = resolution.metadata
            if name in y_payloads:
                # verify/reorder y exactly like the single-model route
                # (extract_X_y): an unverified y dict with shuffled or
                # wrong columns would silently misalign the detector's
                # scaler.transform(y) instead of answering 400
                y_frames[name] = server_utils.verify_dataframe(
                    server_utils.dataframe_from_dict(y_payloads[name]),
                    resolution.target_names,
                )
        except FileNotFoundError:
            errors[name] = {"error": f"No such model found: '{name}'", "status": 404}
        except server_utils.ServerError as exc:
            errors[name] = {"error": str(exc), "status": exc.status}
        except (ValueError, TypeError, KeyError) as exc:
            # malformed frame payloads (unparseable index etc.) are that
            # machine's problem, never the whole batch's
            errors[name] = {"error": f"Invalid frame payload: {exc}", "status": 400}
        except Exception:  # noqa: BLE001 - a broken artifact is this
            # machine's problem (the resolution loads the model)
            logger.exception("fleet resolution failed for %s", name)
            errors[name] = {"error": "Model could not be loaded", "status": 500}
    return full, keep_smooth


def _decode_fleet_arrow(
    ctx, resolve_machine, frames, y_frames, metadatas, errors
) -> Tuple[bool, bool]:
    """The columnar fleet body: a container of per-machine Arrow IPC
    streams (``wire.pack_streams``), each carrying role-tagged ``x``
    (and optionally ``y``) columns; ``full`` rides the container's JSON
    trailer or the query string."""
    from .. import wire

    request = ctx.request
    try:
        entries, extra = wire.unpack_streams(request.get_data())
    except wire.ArrowDecodeError as exc:
        raise server_utils.ServerError(str(exc), status=400)
    if not entries:
        raise server_utils.ServerError(
            "Fleet prediction needs at least one machine entry"
        )
    full = request.args.get("full") is not None or bool(extra.get("full"))
    keep_smooth = (
        request.args.get("all_columns") is not None
        or bool(extra.get("all_columns"))
    )
    for name, payload in entries.items():
        try:
            resolution = resolve_machine(name)
            x_columns, y_columns, index = wire.decode_frames(payload)
            frames[name] = server_utils.frame_from_columns(
                resolution, x_columns, index, resolution.tag_names
            )
            metadatas[name] = resolution.metadata
            if y_columns:
                y_frames[name] = server_utils.frame_from_columns(
                    resolution, y_columns, index, resolution.target_names
                )
        except FileNotFoundError:
            errors[name] = {"error": f"No such model found: '{name}'", "status": 404}
        except server_utils.ServerError as exc:
            errors[name] = {"error": str(exc), "status": exc.status}
        except (ValueError, TypeError, KeyError) as exc:
            errors[name] = {"error": f"Invalid frame payload: {exc}", "status": 400}
        except Exception:  # noqa: BLE001 - per-machine isolation
            logger.exception("fleet resolution failed for %s", name)
            errors[name] = {"error": "Model could not be loaded", "status": 500}
    return full, keep_smooth


def _record_fleet_health(ctx, frames, scores, score_errors) -> None:
    """Per-machine serving health out of one fleet-scoring window:
    request+row counts and the rolling residual mean for machines that
    scored, an error mark for machines that failed server-side. One
    throttled snapshot write for the whole batch (the ledger is keyed
    to the anchor collection dir, like the single-model path)."""
    try:
        from ...telemetry import ledger_for

        anchor = os.environ.get(ctx.config["MODEL_COLLECTION_DIR_ENV_VAR"])
        if not anchor:
            return
        ledger = ledger_for(anchor)
        if not ledger.enabled:
            return
        # every name here came through check_metadata_file (an artifact
        # dir on disk) — score/error keys are bounded by the volume's
        # machines, never by client-invented request text
        for name, (reconstruction, mse) in scores.items():
            residuals = np.asarray(mse, dtype=float).ravel()
            residuals = residuals[np.isfinite(residuals)]
            frame = frames.get(name)
            ledger.record_scores(
                name,
                len(frame) if frame is not None else len(residuals),
                float(residuals.mean()) if len(residuals) else None,
                write=False,
            )
            ledger.record_request(name)
        for name, exc in score_errors.items():
            # client-side failures (ValueError/TypeError → 4xx, missing
            # model → 404) are not the machine's health problem
            ledger.record_request(
                name,
                error=not isinstance(
                    exc, (ValueError, TypeError, FileNotFoundError)
                ),
            )
        ledger.write()
    except Exception:  # noqa: BLE001 - health telemetry is advisory
        logger.debug("fleet health not recorded", exc_info=True)


def _full_anomaly_entry(
    fleet, name, X, y, metadata, reconstruction, keep_smooth
):
    """
    One machine's FULL anomaly response assembled from the fused-bucket
    reconstruction: ``(table, frame, error)`` — a columnar
    :class:`~..wire.WireTable` on the vectorized fast path, the legacy
    pandas frame for custom detectors (or columnar switched off), both
    None for non-detector models (→ caller falls back to the lean
    shape), ``error`` a per-machine error dict. The detector's
    threshold/confidence math runs host-side exactly as in the
    single-model route; only the predict was fused.
    """
    from types import SimpleNamespace

    from ...models.anomaly.base import AnomalyDetectorBase
    from .. import wire
    from ..properties import get_frequency
    from .anomaly import DELETED_FROM_RESPONSE_COLUMNS

    model = fleet.model(name)
    if not isinstance(model, AnomalyDetectorBase):
        return None, None, None
    try:
        frequency = get_frequency(SimpleNamespace(metadata=metadata))
    except (KeyError, TypeError, ValueError):
        frequency = None
    try:
        if wire.columnar_enabled() and wire.supports_columnar_anomaly(
            model
        ):
            table = wire.anomaly_table(
                model,
                X,
                y,
                reconstruction,
                frequency=frequency,
                keep_smooth=keep_smooth,
            )
            if table.unique_labels():
                return table, None, None
        kwargs = {"frequency": frequency}
        if model_io.accepts_model_output(model):
            kwargs["model_output"] = reconstruction
        anomaly_df = model.anomaly(X, y, **kwargs)
    except AttributeError:
        return None, None, {
            "error": "Model has no thresholds (require_thresholds unmet)",
            "status": 422,
        }
    except ValueError as exc:
        return None, None, {"error": f"ValueError: {exc}", "status": 400}
    if not keep_smooth:
        # same drop set as the single-model anomaly route, by construction
        anomaly_df = anomaly_df.drop(
            columns=[
                column
                for column in anomaly_df
                if column[0] in DELETED_FROM_RESPONSE_COLUMNS
            ]
        )
    return None, anomaly_df, None


def delete_model_revision(ctx, gordo_project: str, gordo_name: str, revision: str):
    """Delete a (non-current) model revision from disk."""
    server_utils.validate_gordo_name(gordo_name)
    if not server_utils.validate_revision(revision):
        return ctx.json_response(
            {"error": "Revision should only contains numbers."}, status=422
        )
    if revision == ctx.current_revision:
        return ctx.json_response(
            {"error": "Unable to delete current revision."}, status=409
        )
    revision_dir = os.path.join(ctx.collection_dir, "..", revision)
    server_utils.delete_revision(revision_dir, gordo_name)
    return ctx.json_response({"ok": True}, status=200)


def get_build_status(ctx, gordo_project: str):
    """
    The live fleet-build progress document (``build_status.json``) the
    builder heartbeats beside this revision's artifacts — phase, machine
    counts and per-phase durations, served verbatim so operators (and
    the ``gordo-tpu build-status`` CLI pointed at the server) can watch
    a build without volume access. 404 when no build has written one.
    """
    doc = load_build_status(ctx.collection_dir)
    if doc is None:
        return ctx.json_response(
            {"error": "No build status for this revision."}, status=404
        )
    return ctx.json_response(doc)


def get_fleet_health(ctx, gordo_project: str):
    """
    The joined fleet-status document for the served collection: build
    progress, plan accuracy (predicted vs measured HBM/padding), the
    per-member health ledger, lifecycle/quarantine state, device memory
    and compile-cache hit rates — exactly what the ``gordo-tpu
    fleet-status`` CLI renders, as one JSON payload. Sections the
    directory has no data for are null rather than errors: a plain
    build dir still answers, so does a serve-only dir.

    The health section is bounded at fleet scale (summary + top
    offenders; per-machine records elide past
    ``GORDO_TPU_FLEET_STATUS_MAX_MACHINES``): ``?machines=`` selects
    records back in — ``all``, ``none``, a health state
    (``unhealthy``, ``quarantined``, ...) or a comma-separated name
    list — and ``?limit=``/``?offset=`` page through the selection.
    """
    from ...telemetry import fleet_status_document, utilization_snapshot
    from ..fleet_store import program_cache_stats

    # the ANCHOR dir (the env var), not the routed revision: the ledger
    # and lifecycle state are keyed to the operator's stable handle
    anchor = os.environ.get(ctx.config["MODEL_COLLECTION_DIR_ENV_VAR"])
    directory = anchor or ctx.collection_dir
    args = ctx.request.args
    machines = args.get("machines")
    try:
        limit = int(args["limit"]) if "limit" in args else None
    except (TypeError, ValueError):
        limit = None
    try:
        offset = int(args.get("offset") or 0)
    except (TypeError, ValueError):
        offset = 0
    try:
        programs = program_cache_stats()
    except Exception:  # noqa: BLE001 - cache stats are advisory
        programs = None
    # the serve-engine section: batch counters plus the precision ladder
    # (per-precision coalesce counts, degrade counter, and the served
    # revision's cached precision-parity gate reports)
    serving = None
    try:
        from ... import serve
        from ..fleet_store import STORE

        engine = serve.get_engine()
        if engine is not None:
            serving = engine.stats()
            serving["gates"] = STORE.fleet(
                STORE.route(directory)
            ).precision_reports()
            # per-revision resident-byte estimates (the capacity signal
            # gordo_store_revision_bytes also exports)
            serving["store"] = STORE.revision_stats()
    except Exception:  # noqa: BLE001 - engine stats are advisory
        pass
    # the streaming plane joins the console like device/programs — an
    # injected live-process section (telemetry never imports the plane)
    stream = None
    try:
        from ...stream import stream_plane_section

        stream = stream_plane_section()
    except Exception:  # noqa: BLE001 - plane stats are advisory
        pass
    doc = fleet_status_document(
        directory,
        device=utilization_snapshot(),
        programs=programs,
        serving=serving,
        stream=stream,
        machines=machines,
        limit=limit,
        offset=offset,
    )
    return ctx.json_response(doc)


def get_slo_status(ctx, gordo_project: str):
    """
    The fleet SLO judgment for the served collection: per-objective
    error-budget remaining, multi-window burn rates, and every alert's
    pending/firing/resolved state — exactly what ``gordo-tpu slo
    status --as-json`` prints, evaluated over the serving telemetry
    dir's cross-worker rollups (``GORDO_TPU_TELEMETRY_DIR`` when
    configured, else the anchor collection dir — a dir with no sinks
    evaluates to empty traffic, inside SLO). 404 only when neither
    resolves to a directory; config errors surface as 422 (a bad
    slos.toml is the operator's to fix, not a server fault).
    """
    from ...telemetry import slo as slo_engine

    # the ANCHOR dir (env var, falling back to the resolved collection
    # dir like get_fleet_health) unless a telemetry dir is configured
    anchor = os.environ.get(ctx.config["MODEL_COLLECTION_DIR_ENV_VAR"])
    directory = slo_engine.slo_directory(anchor or ctx.collection_dir)
    if not directory or not os.path.isdir(directory):
        return ctx.json_response(
            {
                "error": "No telemetry directory to evaluate "
                "(set GORDO_TPU_TELEMETRY_DIR)."
            },
            status=404,
        )
    try:
        config = slo_engine.load_slo_config(directory)
    except (OSError, ValueError) as exc:
        return ctx.json_response(
            {"error": f"Bad SLO config: {exc}"}, status=422
        )
    try:
        # throttled: a dashboard polling this route re-serves the cached
        # status until the scrape-refresh window lapses — a GET must not
        # re-aggregate (disk writes) or step the alert state machine at
        # whatever rate an external poller chooses
        doc = slo_engine.evaluate_cached(directory, config=config)
    except OSError as exc:
        # a read-only artifact volume (a real serving deployment shape)
        # cannot host rollups — answer a clean 503, not a traceback
        return ctx.json_response(
            {"error": f"SLO evaluation failed: {exc}"}, status=503
        )
    return ctx.json_response(doc)


def get_metadata(ctx, gordo_project: str, gordo_name: str):
    """Model metadata; doubles as the per-model healthcheck route."""
    server_utils.require_metadata(ctx, gordo_name)
    model_collection_env_var = ctx.config["MODEL_COLLECTION_DIR_ENV_VAR"]
    metadata = dict(ctx.info) if ctx.info else {}
    metadata.update(
        {
            "gordo-server-version": gordo_tpu.__version__,
            "metadata": ctx.metadata,
            "env": {model_collection_env_var: os.environ.get(model_collection_env_var)},
        }
    )
    return ctx.json_response(metadata)


def get_download_model(ctx, gordo_project: str, gordo_name: str):
    """The serialized current model (``serializer.dumps`` wire format)."""
    server_utils.require_model(ctx, gordo_name)
    return ctx.file_response(serializer.dumps(ctx.model), download_name="model.pickle")


def get_model_list(ctx, gordo_project: str):
    """Names of models currently available from the served revision.
    Only artifact directories count (serializer.list_model_dirs): the
    fleet builder's journal file and atomic-dump staging dirs (possibly
    half-written by a killed build) are never models."""
    return ctx.json_response(
        {"models": serializer.list_model_dirs(ctx.collection_dir)}
    )


def get_revision_list(ctx, gordo_project: str):
    """All revisions present on disk, plus which one is latest."""
    try:
        available_revisions = os.listdir(os.path.join(ctx.collection_dir, ".."))
    except FileNotFoundError:
        logger.error(
            "Attempted to list directories above %s but failed with: %s",
            ctx.collection_dir,
            traceback.format_exc(),
        )
        available_revisions = [ctx.current_revision]
    return ctx.json_response(
        {"latest": ctx.current_revision, "available-revisions": available_revisions}
    )


def get_expected_models(ctx, gordo_project: str):
    """The project's configured (expected-to-be-built) model names."""
    return ctx.json_response({"expected-models": ctx.config["EXPECTED_MODELS"]})
