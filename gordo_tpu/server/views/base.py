"""
Base model routes under ``/gordo/v0/<project>/...``.

Reference parity: gordo/server/blueprints/base.py — POST prediction, GET
metadata/healthcheck, GET download-model (pickle stream), GET models, GET
revisions, GET expected-models, DELETE revision/<revision>.
"""

import logging
import os
import timeit
import traceback
from typing import Any, Dict, List, Tuple

import numpy as np
import pandas as pd

import gordo_tpu
from ... import serializer
from ...models import utils as model_utils
from .. import model_io
from .. import utils as server_utils
from ..properties import get_tags, get_target_tags

logger = logging.getLogger(__name__)


def post_prediction(ctx, gordo_project: str, gordo_name: str):
    """
    Run the model on client-provided ``X`` and answer the
    start/end/model-input/model-output response frame as JSON (or parquet
    with ``?format=parquet``).
    """
    server_utils.require_model(ctx, gordo_name)
    server_utils.extract_X_y(ctx)

    context: Dict[Any, Any] = dict()
    X = ctx.X
    process_request_start_time_s = timeit.default_timer()

    try:
        output = model_io.get_model_output(model=ctx.model, X=X)
    except ValueError as err:
        logger.error(
            "Failed to predict or transform; error: %s - \nTraceback: %s",
            err,
            traceback.format_exc(),
        )
        context["error"] = f"ValueError: {str(err)}"
        return ctx.json_response(context, status=400)
    except Exception as exc:
        logger.error(
            "Failed to predict or transform; error: %s - \nTraceback: %s",
            exc,
            traceback.format_exc(),
        )
        context["error"] = "Something unexpected happened; check your input data"
        return ctx.json_response(context, status=400)

    logger.debug(
        "Calculating model output took %.4fs",
        timeit.default_timer() - process_request_start_time_s,
    )
    data = model_utils.make_base_dataframe(
        tags=get_tags(ctx),
        model_input=X.values if isinstance(X, pd.DataFrame) else X,
        model_output=output,
        target_tag_list=get_target_tags(ctx),
        index=X.index,
    )
    if ctx.request.args.get("format") == "parquet":
        return ctx.file_response(server_utils.dataframe_into_parquet_bytes(data))
    context["data"] = server_utils.dataframe_to_dict(data)
    return ctx.json_response(context)


def post_fleet_prediction(ctx, gordo_project: str):
    """
    TPU-native extension route (no reference analog): score MANY models in
    one request. Body ``{"X": {<model-name>: <dataframe-dict>}}``; models
    sharing an architecture are stacked and scored as one fused device
    program (Pallas kernel on TPU, XLA vmap elsewhere) instead of N
    pickle-load + predict round trips. Response per model: ``model-output``
    rows and the ``total-anomaly-unscaled`` per-row mse.
    """
    from types import SimpleNamespace

    from ..fleet_store import STORE, ModelLoadError

    request = ctx.request
    body = request.get_json(silent=True) if request.is_json else None
    if not body or not isinstance(body.get("X"), dict) or not body["X"]:
        raise server_utils.ServerError(
            'Fleet prediction needs a JSON body {"X": {<model-name>: frame}}'
        )

    frames: Dict[str, pd.DataFrame] = {}
    errors: Dict[str, Dict[str, Any]] = {}
    for name, payload in body["X"].items():
        try:
            server_utils.validate_gordo_name(name)
            server_utils.check_metadata_file(ctx.collection_dir, name)
            metadata = server_utils.load_metadata(ctx.collection_dir, name)
            frame = server_utils.dataframe_from_dict(payload)
            tags = get_tags(SimpleNamespace(metadata=metadata))
            frames[name] = server_utils.verify_dataframe(
                frame, [t.name for t in tags]
            )
        except FileNotFoundError:
            errors[name] = {"error": f"No such model found: '{name}'", "status": 404}
        except server_utils.ServerError as exc:
            errors[name] = {"error": str(exc), "status": exc.status}
        except (ValueError, TypeError, KeyError) as exc:
            # malformed frame payloads (unparseable index etc.) are that
            # machine's problem, never the whole batch's
            errors[name] = {"error": f"Invalid frame payload: {exc}", "status": 400}

    data: Dict[str, Any] = {}
    if frames:
        scores, score_errors = STORE.fleet(ctx.collection_dir).fleet_scores(frames)
        for name, exc in score_errors.items():
            # Filesystem/internal errors never echo raw text (it can carry
            # server paths; details live in the server log); client-data
            # ValueErrors are user-facing messages and do echo, matching
            # the single-model routes.
            if isinstance(exc, FileNotFoundError):
                errors[name] = {
                    "error": f"No such model found: '{name}'",
                    "status": 404,
                }
            elif isinstance(exc, ModelLoadError):
                errors[name] = {
                    "error": "Model could not be loaded",
                    "status": 500,
                }
            elif isinstance(exc, ValueError):
                # client-data problem (e.g. too few rows for a windowed
                # model) — same ValueError→400 echo contract as the
                # single-model prediction and anomaly routes
                errors[name] = {
                    "error": f"Scoring failed (ValueError: {exc})",
                    "status": 400,
                }
            elif isinstance(exc, TypeError):
                # likely client data, but the text may describe server
                # internals — generic message, like the single-model routes
                errors[name] = {
                    "error": "Something unexpected happened; "
                    "check your input data",
                    "status": 400,
                }
            else:
                errors[name] = {
                    "error": f"Scoring failed ({type(exc).__name__})",
                    "status": 500,
                }
        # Formatting a DatetimeIndex to wire strings costs ~1ms per
        # machine and the fleet's machines typically share ONE index —
        # format each distinct index once per request (the wire format
        # itself lives in server_utils.index_wire_keys, shared with the
        # single-model routes).
        formatted: List[Tuple[Any, List[str]]] = []

        def index_keys(index) -> List[str]:
            for seen, keys in formatted:
                if index.equals(seen):
                    return keys
            keys = server_utils.index_wire_keys(index)
            formatted.append((index, keys))
            return keys

        for name, (reconstruction, mse) in scores.items():
            index = frames[name].index
            recon = np.asarray(reconstruction)
            if len(recon) > len(index):
                # more output rows than input rows can only be a broken
                # model/transformer; zip would silently misalign
                errors[name] = {
                    "error": "Scoring failed (output longer than input)",
                    "status": 500,
                }
                continue
            keys = index_keys(index[len(index) - len(recon):])
            # direct dict assembly — same wire shape as
            # dataframe_to_dict(DataFrame(reconstruction)) with stringified
            # columns, without re-building frames per machine
            data[name] = {
                "model-output": {
                    str(col): dict(zip(keys, recon[:, col].tolist()))
                    for col in range(recon.shape[1])
                },
                "total-anomaly-unscaled": dict(
                    zip(keys, np.asarray(mse).tolist())
                ),
            }

    context: Dict[str, Any] = {"data": data}
    if errors:
        context["errors"] = errors
    return ctx.json_response(context, status=200 if data else 400)


def delete_model_revision(ctx, gordo_project: str, gordo_name: str, revision: str):
    """Delete a (non-current) model revision from disk."""
    server_utils.validate_gordo_name(gordo_name)
    if not server_utils.validate_revision(revision):
        return ctx.json_response(
            {"error": "Revision should only contains numbers."}, status=422
        )
    if revision == ctx.current_revision:
        return ctx.json_response(
            {"error": "Unable to delete current revision."}, status=409
        )
    revision_dir = os.path.join(ctx.collection_dir, "..", revision)
    server_utils.delete_revision(revision_dir, gordo_name)
    return ctx.json_response({"ok": True}, status=200)


def get_metadata(ctx, gordo_project: str, gordo_name: str):
    """Model metadata; doubles as the per-model healthcheck route."""
    server_utils.require_metadata(ctx, gordo_name)
    model_collection_env_var = ctx.config["MODEL_COLLECTION_DIR_ENV_VAR"]
    metadata = dict(ctx.info) if ctx.info else {}
    metadata.update(
        {
            "gordo-server-version": gordo_tpu.__version__,
            "metadata": ctx.metadata,
            "env": {model_collection_env_var: os.environ.get(model_collection_env_var)},
        }
    )
    return ctx.json_response(metadata)


def get_download_model(ctx, gordo_project: str, gordo_name: str):
    """The serialized current model (``serializer.dumps`` wire format)."""
    server_utils.require_model(ctx, gordo_name)
    return ctx.file_response(serializer.dumps(ctx.model), download_name="model.pickle")


def get_model_list(ctx, gordo_project: str):
    """Names of models currently available from the served revision."""
    try:
        available_models = os.listdir(ctx.collection_dir)
    except FileNotFoundError:
        available_models = []
    return ctx.json_response({"models": available_models})


def get_revision_list(ctx, gordo_project: str):
    """All revisions present on disk, plus which one is latest."""
    try:
        available_revisions = os.listdir(os.path.join(ctx.collection_dir, ".."))
    except FileNotFoundError:
        logger.error(
            "Attempted to list directories above %s but failed with: %s",
            ctx.collection_dir,
            traceback.format_exc(),
        )
        available_revisions = [ctx.current_revision]
    return ctx.json_response(
        {"latest": ctx.current_revision, "available-revisions": available_revisions}
    )


def get_expected_models(ctx, gordo_project: str):
    """The project's configured (expected-to-be-built) model names."""
    return ctx.json_response({"expected-models": ctx.config["EXPECTED_MODELS"]})
