from . import anomaly, base

__all__ = ["base", "anomaly"]
