"""
Streaming-plane routes under ``/gordo/v0/<project>/stream/...``.

No reference analog: the reference serves request/response only. These
routes are the thin HTTP skin over :mod:`gordo_tpu.stream` — a stream is
a server-side session (``stream_id``), fed by repeated ingest POSTs on a
keep-alive connection and consumed as one long-lived SSE response:

- ``POST  .../stream/<stream_id>/ingest`` — an Arrow-IPC container
  (``wire.pack_streams``, the fleet route's request body) or the JSON
  twin ``{"X": {<machine>: frame-dict}}``; rows land in the session's
  rings, the watermark flush scores, and the JSON ack reports
  accepted/shed/scored/quarantined per machine plus the consumer
  ``cursor``. Backpressure is visible, never fatal: ``backpressure:
  true`` + ``retry_after_s`` when rows were shed oldest-first.
- ``GET   .../stream/<stream_id>/events`` — ``text/event-stream``.
  Resume with ``?cursor=<seq>`` or the standard ``Last-Event-ID``
  header; ``?max_events=`` and ``?idle_timeout_s=`` bound the response
  (tests, polling consumers). The first frames are ``open`` and any
  active ``quarantined`` notices — a reconnect learns about an ongoing
  quarantine immediately, not from a silent gap.
- ``GET   .../stream/status`` — every live session's counters.
- ``DELETE .../stream/<stream_id>`` — close with a terminal ``end``
  frame.

Ladder: 503 streaming disabled / server draining · 429 session cap
(``Retry-After``) · 410 closed stream ingest · 400 malformed body — all
JSON, mirroring the request/response error ladder in
``docs/serving.md``.
"""

import logging
import os
import re
from typing import Any, Dict

from .. import utils as server_utils

logger = logging.getLogger(__name__)

_STREAM_ID = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,127}$")


def _validate_stream_id(stream_id: str) -> None:
    if not _STREAM_ID.match(stream_id):
        raise server_utils.ServerError(
            "Invalid stream id: letters, digits, '.', '_', '-' "
            "(max 128 chars).",
            status=400,
        )


def _anchor_dir(ctx) -> str:
    """The ANCHOR collection dir (the env var's value, not the routed
    revision): sessions outlive hot-swaps, so the session pins the
    operator's stable handle and the scorer re-routes per flush."""
    return os.environ[ctx.config["MODEL_COLLECTION_DIR_ENV_VAR"]]


def _require_plane(ctx):
    from ... import stream as stream_plane

    plane = stream_plane.ensure_plane()
    if plane is None:
        raise server_utils.ServerError(
            "Streaming is disabled (GORDO_TPU_STREAM_ENABLED=0)",
            status=503,
        )
    plane.ledger_anchor = _anchor_dir(ctx)
    return plane


def _open_session(ctx, plane, gordo_project: str, stream_id: str):
    """``(session, None)`` on admission, ``(None, 429 response)`` when
    the plane is saturated or draining."""
    from ...stream import PlaneSaturated

    try:
        return (
            plane.session(gordo_project, stream_id, _anchor_dir(ctx)),
            None,
        )
    except PlaneSaturated as exc:
        response = ctx.json_response(
            {
                "error": str(exc),
                "retry_after_s": exc.retry_after_s,
            },
            status=429,
        )
        response.headers["Retry-After"] = str(
            max(1, int(round(exc.retry_after_s)))
        )
        return None, response


def _decode_stream_body(ctx, frames, errors) -> None:
    """Per-machine decode straight off the fleet route's body formats —
    same per-machine isolation: a malformed entry errors alone in the
    ack. Streaming is autoencoder replay; ``y`` entries are ignored."""
    from .. import wire
    from ..fleet_store import STORE

    request = ctx.request
    fleet = STORE.fleet(ctx.collection_dir)

    def resolve(name: str):
        server_utils.validate_gordo_name(name)
        server_utils.check_metadata_file(ctx.collection_dir, name)
        return fleet.resolution(name)

    if wire.request_format(request) == wire.ARROW:
        try:
            entries, _extra = wire.unpack_streams(request.get_data())
        except wire.ArrowDecodeError as exc:
            raise server_utils.ServerError(str(exc), status=400)
        if not entries:
            raise server_utils.ServerError(
                "Stream ingest needs at least one machine entry"
            )
        for name, payload in entries.items():
            try:
                resolution = resolve(name)
                x_columns, _y, index = wire.decode_frames(payload)
                frames[name] = server_utils.frame_from_columns(
                    resolution, x_columns, index, resolution.tag_names
                )
            except FileNotFoundError:
                errors[name] = {
                    "error": f"No such model found: '{name}'",
                    "status": 404,
                }
            except server_utils.ServerError as exc:
                errors[name] = {"error": str(exc), "status": exc.status}
            except (ValueError, TypeError, KeyError) as exc:
                errors[name] = {
                    "error": f"Invalid frame payload: {exc}",
                    "status": 400,
                }
            except Exception:  # noqa: BLE001 - per-machine isolation
                logger.exception("stream resolution failed for %s", name)
                errors[name] = {
                    "error": "Model could not be loaded",
                    "status": 500,
                }
        return

    body = request.get_json(silent=True) if request.is_json else None
    if not body or not isinstance(body.get("X"), dict) or not body["X"]:
        raise server_utils.ServerError(
            'Stream ingest needs an Arrow container or a JSON body '
            '{"X": {<model-name>: frame}}'
        )
    for name, payload in body["X"].items():
        try:
            resolution = resolve(name)
            frame = server_utils.dataframe_from_dict(payload)
            frames[name] = server_utils.verify_dataframe(
                frame, resolution.tag_names
            )
        except FileNotFoundError:
            errors[name] = {
                "error": f"No such model found: '{name}'",
                "status": 404,
            }
        except server_utils.ServerError as exc:
            errors[name] = {"error": str(exc), "status": exc.status}
        except (ValueError, TypeError, KeyError) as exc:
            errors[name] = {
                "error": f"Invalid frame payload: {exc}",
                "status": 400,
            }
        except Exception:  # noqa: BLE001 - per-machine isolation
            logger.exception("stream resolution failed for %s", name)
            errors[name] = {
                "error": "Model could not be loaded",
                "status": 500,
            }


def post_stream_ingest(ctx, gordo_project: str, stream_id: str):
    """Land one record batch on a stream session and run the watermark
    flush; answers the JSON ingest ack."""
    _validate_stream_id(stream_id)
    plane = _require_plane(ctx)
    session, rejected = _open_session(ctx, plane, gordo_project, stream_id)
    if rejected is not None:
        return rejected
    if session.closed:
        return ctx.json_response(
            {"error": f"Stream '{stream_id}' is closed"}, status=410
        )

    frames: Dict[str, Any] = {}
    errors: Dict[str, Dict[str, Any]] = {}
    with ctx.stage("data_decode"):
        _decode_stream_body(ctx, frames, errors)
    with ctx.stage("inference"):
        ack = plane.ingest(session, frames, errors)
    status = 200 if (ack["accepted"] or not ack["errors"]) else 400
    return ctx.json_response(ack, status=status)


def get_stream_events(ctx, gordo_project: str, stream_id: str):
    """The long-lived SSE feed for one stream (resume via ``?cursor=``
    or ``Last-Event-ID``)."""
    _validate_stream_id(stream_id)
    plane = _require_plane(ctx)
    session, rejected = _open_session(ctx, plane, gordo_project, stream_id)
    if rejected is not None:
        return rejected
    request = ctx.request

    def _int_arg(name: str, header: str = "") -> int:
        raw = request.args.get(name) or (
            request.headers.get(header) if header else None
        )
        try:
            return max(0, int(raw)) if raw else 0
        except (TypeError, ValueError):
            raise server_utils.ServerError(
                f"'{name}' must be an integer", status=400
            )

    cursor = _int_arg("cursor", "Last-Event-ID")
    max_events = _int_arg("max_events") or None
    idle_raw = request.args.get("idle_timeout_s")
    try:
        idle_timeout_s = float(idle_raw) if idle_raw else None
    except ValueError:
        raise server_utils.ServerError(
            "'idle_timeout_s' must be a number", status=400
        )

    from ...stream import SSE_CONTENT_TYPE

    body = plane.subscribe(
        session,
        cursor=cursor,
        max_events=max_events,
        idle_timeout_s=idle_timeout_s,
    )
    response = ctx.raw_response(body, SSE_CONTENT_TYPE)
    # SSE hygiene: never cached, never buffered by nginx-style proxies
    response.headers["Cache-Control"] = "no-cache"
    response.headers["X-Accel-Buffering"] = "no"
    return response


def get_stream_status(ctx, gordo_project: str):
    """Every live session's counters (the plane's observability face)."""
    from ... import stream as stream_plane

    plane = stream_plane.get_plane()
    if plane is None:
        return ctx.json_response(
            {"enabled": stream_plane.stream_enabled(), "sessions": {}}
        )
    return ctx.json_response(plane.stats())


def delete_stream(ctx, gordo_project: str, stream_id: str):
    """Close a stream with a terminal ``end`` frame."""
    _validate_stream_id(stream_id)
    from ... import stream as stream_plane

    plane = stream_plane.get_plane()
    closed = bool(
        plane and plane.close_session(gordo_project, stream_id)
    )
    return ctx.json_response(
        {"stream": stream_id, "closed": closed},
        status=200 if closed else 404,
    )
