"""
Model invocation for the serving path (reference: gordo/server/model_io.py).
"""

import logging

import numpy as np

logger = logging.getLogger(__name__)


def get_model_output(model, X) -> np.ndarray:
    """
    Raw model output for ``X``: try ``predict``, fall back to ``transform``
    (the model may be a bare transformer pipeline).
    """
    try:
        return model.predict(X)
    except AttributeError:
        try:
            return model.transform(X)
        except Exception as exc:
            logger.error("Failed to predict or transform; error: %s", exc)
            raise
