"""
Model invocation for the serving path (reference: gordo/server/model_io.py),
plus the glue between the request handlers and the cross-request
micro-batching engine (``gordo_tpu.serve``).
"""

import inspect
import logging
from typing import Any, Optional

import numpy as np

logger = logging.getLogger(__name__)


def get_model_output(model, X) -> np.ndarray:
    """
    Raw model output for ``X``: try ``predict``, fall back to ``transform``
    (the model may be a bare transformer pipeline).
    """
    try:
        return model.predict(X)
    except AttributeError:
        try:
            return model.transform(X)
        except Exception as exc:
            logger.error("Failed to predict or transform; error: %s", exc)
            raise


def accepts_model_output(model: Any) -> bool:
    """Whether ``model.anomaly`` takes a precomputed ``model_output`` —
    signature inspection, not a TypeError probe: a custom detector whose
    ``anomaly()`` raises TypeError internally must surface it, not
    silently re-run unfused."""
    anomaly = getattr(model, "anomaly", None)
    if anomaly is None:
        return False
    try:
        return "model_output" in inspect.signature(anomaly).parameters
    except (TypeError, ValueError):
        return False


def batched_model_output(ctx, gordo_name: str, X) -> Optional[np.ndarray]:
    """
    The micro-batched reconstruction for one single-model request, or
    None when batching is off or this request is not batchable (caller
    falls back to the model's own predict — including a demoted-rung
    OOM fallback). The engine's admission and containment errors
    (:class:`gordo_tpu.serve.QueueFullError` → 429,
    :class:`gordo_tpu.serve.MemberQuarantined` → 503,
    :class:`gordo_tpu.serve.ServeDeviceError` → 500,
    :class:`gordo_tpu.serve.DeadlineExceeded` → 504) propagate to the
    route, which maps them via :func:`shed_response`.

    The request's decoded wire columns (``ctx.ingest``, stashed by the
    Arrow decode when they align with the model's tag order) ride along
    so the engine can batch RAW — preprocessing compiled into the fused
    program instead of run per request on this thread.
    """
    from ..serve import get_engine

    engine = get_engine()
    if engine is None:
        return None
    return engine.batched_predict(
        ctx.collection_dir,
        gordo_name,
        ctx.model,
        X,
        timing=ctx.timing,
        raw=getattr(ctx, "ingest", None),
    )


class CompiledInput:
    """A request staged for the compiled UNBATCHED path: the device-
    resident input batch plus everything :func:`compiled_output` needs
    to run the fused gather program for one member."""

    __slots__ = ("spec", "stacked", "index", "plan", "X_dev", "rows")

    def __init__(self, spec, stacked, index: int, plan, X_dev, rows: int):
        self.spec = spec
        self.stacked = stacked
        self.index = index
        self.plan = plan
        self.X_dev = X_dev
        self.rows = rows


def stage_compiled_input(ctx, gordo_name: str, X) -> Optional[CompiledInput]:
    """
    Stage one request's input onto the device for the compiled
    (engine-less) scoring path, or None → the caller keeps the host
    ``model.predict`` path. Meant to run inside the view's
    ``device_ingest`` stage: everything here is wire→device staging —
    the raw columns (``ctx.ingest`` when the Arrow decode stashed them,
    else the already-decoded matrix) cross via
    :func:`gordo_tpu.ingest.to_device`, row-padded on a geometric
    sample-ladder rung so the executable count stays bounded at ≤25%
    padded compute.

    Eligibility mirrors the micro-batcher: a feedforward spec with a
    resident compiled preprocessing plan (``RevisionFleet.ingest_plan``
    — identity plans included, where the compiled path is bit-identical
    to the host path). Anything else — non-affine pipelines, LSTM
    specs, width mismatches — answers None and costs one cached probe.
    """
    from ..ingest import RawColumns, compiled_enabled, dlpack_enabled, to_device
    from ..models.spec import FeedForwardSpec
    from ..planner import ladder
    from .fleet_store import STORE, _find_estimator

    if not compiled_enabled():
        return None
    estimator = _find_estimator(ctx.model)
    if estimator is None or not isinstance(
        getattr(estimator, "spec_", None), FeedForwardSpec
    ):
        return None
    spec = estimator.spec_
    fleet = STORE.fleet(ctx.collection_dir)
    try:
        plan = fleet.ingest_plan(spec)
    except Exception:  # noqa: BLE001 - planning never gates serving
        plan = None
    if plan is None:
        return None
    try:
        bucket_names, stacked = fleet.spec_bucket(spec)
    except KeyError:
        return None
    try:
        index = bucket_names.index(gordo_name)
    except ValueError:
        return None
    raw = getattr(ctx, "ingest", None)
    rows = int(len(X))
    if raw is None or raw.rows != rows:
        raw = RawColumns.from_matrix(np.asarray(X, np.float32))
    if raw.rows == 0 or raw.width != spec.n_features:
        return None
    # quantize rows on the packed-sample geometric ladder (ratio 1.25,
    # whole multiples of 32), NOT the serve row ladder: the batcher's
    # coarse rungs exist for arrival coalescing and waste up to 4x
    # compute on a single request (256 rows -> the 512 rung doubles the
    # fused program's work), while the legacy host path this replaces
    # compiles per EXACT row count per member — geometric rungs bound
    # the executable count (~22 rungs to 8k rows, shared by the whole
    # bucket) and cap padded compute at 25%
    padded_rows = ladder.round_up_ladder(
        rows, ladder.sample_pad_ratio(), multiple=32
    )
    X_dev = to_device(raw, padded_rows=padded_rows, dlpack=dlpack_enabled())
    return CompiledInput(spec, stacked, index, plan, X_dev, rows)


def compiled_output(staged: CompiledInput) -> np.ndarray:
    """Run the fused gather program for one staged request (the view's
    ``inference`` stage): identity plans run the classic program on the
    staged float32 rows — bit-identical to the host predict — and
    non-identity plans run the ingest variant whose prologue applies
    the compiled preprocessing. Returns the member's reconstruction
    rows (padding sliced off)."""
    from .fleet_store import fleet_forward_gather

    plan = staged.plan
    recon = np.asarray(
        fleet_forward_gather(
            staged.spec,
            staged.stacked,
            np.asarray([staged.index], np.int32),
            staged.X_dev[None],
            ingest=None if plan.identity else (plan.scale, plan.offset),
        )
    )
    return recon[0, : staged.rows]


def shed_response(ctx, exc):
    """The flow-control / fault-containment response for a serving-plane
    rejection (the full table lives in docs/serving.md "Error
    contract"):

    - 429 + ``Retry-After`` — the batch queue is full (overload degrades
      into backpressure instead of OOMing the host);
    - 503 + ``Retry-After`` — THIS member's circuit breaker is open (its
      device programs kept failing); the ``Retry-After`` is the
      breaker's remaining half-open backoff, mirroring the 429 contract;
    - 500 — the device program failed for this request/member after the
      engine's bisection isolated it (innocent coalesced riders already
      answered 200);
    - 504 — the request missed its batching deadline.
    """
    from ..serve import MemberQuarantined, QueueFullError, ServeDeviceError

    if isinstance(exc, QueueFullError):
        response = ctx.json_response(
            {"error": "Server overloaded: batch queue full, retry later."},
            status=429,
        )
        response.headers["Retry-After"] = str(
            max(1, int(round(exc.retry_after_s)))
        )
        return response
    if isinstance(exc, MemberQuarantined):
        response = ctx.json_response(
            {
                "error": "Model is quarantined after repeated device "
                "failures; retry later."
            },
            status=503,
        )
        response.headers["Retry-After"] = str(
            max(1, int(round(exc.retry_after_s)))
        )
        return response
    if isinstance(exc, ServeDeviceError):
        # server-side: the text never echoes device internals (the cause
        # is chained into the server log by the engine)
        return ctx.json_response(
            {"error": "Device scoring failed for this model."}, status=500
        )
    return ctx.json_response(
        {"error": "Request timed out waiting for its batch."}, status=504
    )
