"""
Model invocation for the serving path (reference: gordo/server/model_io.py),
plus the glue between the request handlers and the cross-request
micro-batching engine (``gordo_tpu.serve``).
"""

import inspect
import logging
from typing import Any, Optional

import numpy as np

logger = logging.getLogger(__name__)


def get_model_output(model, X) -> np.ndarray:
    """
    Raw model output for ``X``: try ``predict``, fall back to ``transform``
    (the model may be a bare transformer pipeline).
    """
    try:
        return model.predict(X)
    except AttributeError:
        try:
            return model.transform(X)
        except Exception as exc:
            logger.error("Failed to predict or transform; error: %s", exc)
            raise


def accepts_model_output(model: Any) -> bool:
    """Whether ``model.anomaly`` takes a precomputed ``model_output`` —
    signature inspection, not a TypeError probe: a custom detector whose
    ``anomaly()`` raises TypeError internally must surface it, not
    silently re-run unfused."""
    anomaly = getattr(model, "anomaly", None)
    if anomaly is None:
        return False
    try:
        return "model_output" in inspect.signature(anomaly).parameters
    except (TypeError, ValueError):
        return False


def batched_model_output(ctx, gordo_name: str, X) -> Optional[np.ndarray]:
    """
    The micro-batched reconstruction for one single-model request, or
    None when batching is off or this request is not batchable (caller
    falls back to the model's own predict — including a demoted-rung
    OOM fallback). The engine's admission and containment errors
    (:class:`gordo_tpu.serve.QueueFullError` → 429,
    :class:`gordo_tpu.serve.MemberQuarantined` → 503,
    :class:`gordo_tpu.serve.ServeDeviceError` → 500,
    :class:`gordo_tpu.serve.DeadlineExceeded` → 504) propagate to the
    route, which maps them via :func:`shed_response`.
    """
    from ..serve import get_engine

    engine = get_engine()
    if engine is None:
        return None
    return engine.batched_predict(
        ctx.collection_dir, gordo_name, ctx.model, X, timing=ctx.timing
    )


def shed_response(ctx, exc):
    """The flow-control / fault-containment response for a serving-plane
    rejection (the full table lives in docs/serving.md "Error
    contract"):

    - 429 + ``Retry-After`` — the batch queue is full (overload degrades
      into backpressure instead of OOMing the host);
    - 503 + ``Retry-After`` — THIS member's circuit breaker is open (its
      device programs kept failing); the ``Retry-After`` is the
      breaker's remaining half-open backoff, mirroring the 429 contract;
    - 500 — the device program failed for this request/member after the
      engine's bisection isolated it (innocent coalesced riders already
      answered 200);
    - 504 — the request missed its batching deadline.
    """
    from ..serve import MemberQuarantined, QueueFullError, ServeDeviceError

    if isinstance(exc, QueueFullError):
        response = ctx.json_response(
            {"error": "Server overloaded: batch queue full, retry later."},
            status=429,
        )
        response.headers["Retry-After"] = str(
            max(1, int(round(exc.retry_after_s)))
        )
        return response
    if isinstance(exc, MemberQuarantined):
        response = ctx.json_response(
            {
                "error": "Model is quarantined after repeated device "
                "failures; retry later."
            },
            status=503,
        )
        response.headers["Retry-After"] = str(
            max(1, int(round(exc.retry_after_s)))
        )
        return response
    if isinstance(exc, ServeDeviceError):
        # server-side: the text never echoes device internals (the cause
        # is chained into the server log by the engine)
        return ctx.json_response(
            {"error": "Device scoring failed for this model."}, status=500
        )
    return ctx.json_response(
        {"error": "Request timed out waiting for its batch."}, status=504
    )
