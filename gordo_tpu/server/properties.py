"""
Pull tag lists and train resolution out of served machine metadata.

Reference parity: gordo/server/properties.py — ``get_tags`` /
``get_target_tags`` resolve the dataset's configured tag lists (with asset
defaulting) and ``get_frequency`` the training resolution, all from the
metadata document saved beside the model.
"""

from typing import List, Optional

import pandas as pd

from ..dataset.sensor_tag import SensorTag, normalize_sensor_tags


def get_frequency(ctx):
    """The training resolution as a pandas offset (reference :45-49).
    Served requests resolved through the fleet's resolution cache answer
    from it (including a cached parse error, re-raised unchanged)."""
    resolution = getattr(ctx, "resolution", None)
    if resolution is not None:
        return resolution.frequency
    return pd.tseries.frequencies.to_offset(ctx.metadata["dataset"]["resolution"])


def _dataset_asset(dataset: dict) -> Optional[str]:
    """Default asset for bare-string tags (reference :62-69)."""
    default_tag = dataset.get("default_tag")
    if isinstance(default_tag, dict) and default_tag.get("asset"):
        return default_tag["asset"]
    return dataset.get("asset") or None


def get_tags(ctx) -> List[SensorTag]:
    """The model's input tags (cached on the fleet resolution when the
    request resolved through it)."""
    resolution = getattr(ctx, "resolution", None)
    if resolution is not None:
        return resolution.tags
    dataset = ctx.metadata["dataset"]
    return normalize_sensor_tags(dataset["tag_list"], asset=_dataset_asset(dataset))


def get_target_tags(ctx) -> List[SensorTag]:
    """The model's target tags; defaults to the input tags."""
    resolution = getattr(ctx, "resolution", None)
    if resolution is not None:
        return resolution.target_tags
    dataset = ctx.metadata["dataset"]
    target_tag_list = dataset.get("target_tag_list")
    if target_tag_list:
        return normalize_sensor_tags(target_tag_list, asset=_dataset_asset(dataset))
    return get_tags(ctx)
