"""
SARIF 2.1.0 output for ``gordo-tpu lint`` (``--sarif <path>``).

SARIF (Static Analysis Results Interchange Format) is the one artifact
every code-scanning consumer already understands — GitHub code scanning
renders it as inline PR annotations natively, so the CI lint job uploads
this document instead of hand-rolling ``::error`` workflow commands from
the ``--as-json`` shape.

Mapping choices:

- each rule becomes a ``tool.driver.rules`` entry (id, short
  description, a ``helpUri`` into the committed rule catalog);
- new findings are ``level: error`` results; baselined findings are
  emitted too but carried as ``suppressions`` (kind ``external``, the
  baseline justification as the suppression justification) so scanners
  show them resolved rather than re-paging on every PR;
- the engine's stable fingerprint (rule|path|message|occurrence — line
  independent) lands in ``partialFingerprints`` as
  ``gordoLint/v1``, which is exactly the stability contract SARIF asks
  of that field;
- parse errors become ``tool.driver.notifications``-shaped execution
  notifications under ``invocations`` so a broken file fails loudly in
  the same artifact.
"""

from typing import Dict, List, Optional, Sequence

from .baseline import BaselineEntry
from .core import Finding, LintResult

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)
#: the committed rule catalog every rule's helpUri points into
CATALOG_URI = "docs/static-analysis.md"


def _rule_metadata(rules: Sequence[object]) -> List[Dict]:
    entries = []
    for rule in rules:
        name = getattr(rule, "name", None)
        if not name:
            continue
        entries.append(
            {
                "id": name,
                "name": name,
                "shortDescription": {
                    "text": getattr(rule, "description", name)
                },
                "helpUri": f"{CATALOG_URI}#the-rule-catalog",
                "defaultConfiguration": {"level": "error"},
            }
        )
    return entries


def _result(
    finding: Finding,
    baselined: bool,
    justification: Optional[str] = None,
) -> Dict:
    result = {
        "ruleId": finding.rule,
        "level": "error",
        "message": {"text": finding.message},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": finding.path,
                        "uriBaseId": "%SRCROOT%",
                    },
                    "region": {
                        "startLine": max(1, finding.line),
                        "startColumn": max(1, finding.col + 1),
                    },
                }
            }
        ],
        "partialFingerprints": {"gordoLint/v1": finding.fingerprint},
    }
    if baselined:
        suppression = {"kind": "external", "status": "accepted"}
        if justification:
            suppression["justification"] = justification
        result["suppressions"] = [suppression]
    return result


def sarif_document(
    result: LintResult,
    new: List[Finding],
    baselined: List[Finding],
    entries: Optional[List[BaselineEntry]] = None,
    rules: Sequence[object] = (),
    version: str = "",
) -> Dict:
    """The SARIF 2.1.0 run document for one lint invocation."""
    justifications = {
        (entry.rule, entry.path, entry.fingerprint): entry.justification
        for entry in (entries or [])
    }
    results = [_result(finding, baselined=False) for finding in new]
    results += [
        _result(
            finding,
            baselined=True,
            justification=justifications.get(
                (finding.rule, finding.path, finding.fingerprint)
            ),
        )
        for finding in baselined
    ]
    tool_notifications = [
        {
            "level": "error",
            "message": {"text": f"unparseable file: {error}"},
        }
        for error in result.parse_errors
    ]
    driver = {
        "name": "gordo-tpu-lint",
        "informationUri": CATALOG_URI,
        "rules": _rule_metadata(rules),
    }
    if version:
        driver["version"] = version
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {"driver": driver},
                "results": results,
                "invocations": [
                    {
                        "executionSuccessful": not result.parse_errors,
                        "toolExecutionNotifications": tool_notifications,
                    }
                ],
                "columnKind": "utf16CodeUnits",
            }
        ],
    }
