"""
The runtime lock-order harness: instrumented locks + a deadlock analyzer.

Static lock-guard inference (``rules/concurrency.py``) proves writes sit
under the right lock; it cannot prove two locks are always taken in the
same ORDER — the classic ABBA deadlock needs runtime evidence. This
module supplies it, opt-in and zero-cost when off:

- ``GORDO_TPU_LOCK_TRACE=<path>.jsonl`` (or ``=1`` for
  ``./lock_trace.jsonl``) makes :func:`install_lock_trace` wrap
  ``threading.Lock``/``threading.RLock`` so every lock created AFTER
  installation is a :class:`TracedLock`. Lock identity is the
  **creation site** (``file:line``), not the instance — a per-request
  lock allocated a million times is still one node, which is what makes
  the graph meaningful.
- each thread keeps its held-lock stack; acquiring lock *B* while
  holding *A* records the ordering edge ``A -> B`` (with wait time and
  a held-while-blocking sample) into an in-process edge table, flushed
  as JSON lines at interpreter exit (and on :func:`dump_edges`). The
  tests' conftest auto-installs under the env knob, so
  ``GORDO_TPU_LOCK_TRACE=1 pytest -m "serve or slo or lifecycle"``
  leaves a sink the CI gate can analyze.
- :func:`analyze` loads one or more edge sinks, builds the lock-order
  graph, and reports every cycle (a potential deadlock: some thread
  orders A before B, another B before A) plus the
  max-held-while-blocking hotspots — the edges where a thread sat
  longest waiting for a lock while holding another one (the convoy
  telemetry the serving stack's lock budget cares about).
  ``gordo-tpu lockgraph`` is the CLI; CI fails on any cycle.

The wrapper honors the full lock protocol (``acquire``/``release``/
context manager/``locked``) and delegates everything else, so
``threading.Condition(traced_lock)`` works — the Condition binds the
wrapper's ``acquire``/``release``, which is exactly how the
micro-batcher's ``Condition(self._lock)`` alias stays one graph node.
"""

import atexit
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

LOCK_TRACE_ENV = "GORDO_TPU_LOCK_TRACE"

#: default sink when the knob is a bare truthy flag rather than a path
DEFAULT_SINK = "lock_trace.jsonl"

#: edges whose acquirer never waited are still ordering evidence; the
#: hotspot report ranks by wait, the cycle check ignores it
_EDGE_FIELDS = ("src", "dst", "count", "max_wait_ms", "total_wait_ms")


def lock_trace_sink() -> Optional[str]:
    """The configured edge-sink path, or None when tracing is off.

    ``GORDO_TPU_LOCK_TRACE`` is the knob: a path-looking value (has a
    separator or a ``.jsonl`` tail) is the sink path; any other truthy
    spelling means :data:`DEFAULT_SINK` in the current directory. The
    sink is pid-suffixed, fork-safely: each process that actually
    records writes its own file and the analyzer globs them back
    together (the same worker-sink convention as ``serve_trace``)."""
    from ..utils.env import env_str

    raw = env_str(LOCK_TRACE_ENV, None)
    if not raw:
        return None
    value = raw.strip()
    if value.lower() in ("0", "false", "off", "no"):
        return None
    if os.sep in value or value.endswith(".jsonl"):
        return value
    return DEFAULT_SINK


class _TraceState:
    """Process-wide trace state: per-thread held stacks + the edge table."""

    def __init__(self, base_path: str):
        #: UNsuffixed: the pid lands in the filename at DUMP time, so a
        #: worker forked after install still writes its own file — the
        #: frozen-pid-path bug class the fork-safety rule bans (a child
        #: inherits the parent's pre-fork edges and re-dumps them; the
        #: analyzer's merge double-counts those, which only inflates
        #: hotspot totals, never invents or hides a cycle)
        self.base_path = base_path
        self.local = threading.local()
        self.table_lock = _REAL_LOCK()
        #: (src site, dst site) -> [count, max_wait_s, total_wait_s]
        self.edges: Dict[Tuple[str, str], List[float]] = {}

    def held(self) -> List[str]:
        stack = getattr(self.local, "stack", None)
        if stack is None:
            stack = self.local.stack = []
        return stack

    def note_edges(self, dst_site: str, wait_s: float, held: List[str]) -> None:
        with self.table_lock:
            for src_site in held:
                if src_site == dst_site:
                    continue  # re-entrant same-site acquisition orders nothing
                entry = self.edges.get((src_site, dst_site))
                if entry is None:
                    entry = self.edges[(src_site, dst_site)] = [0, 0.0, 0.0]
                entry[0] += 1
                entry[1] = max(entry[1], wait_s)
                entry[2] += wait_s

    def snapshot(self) -> List[Dict[str, Any]]:
        with self.table_lock:
            items = sorted(self.edges.items())
        return [
            {
                "src": src,
                "dst": dst,
                "count": int(count),
                "max_wait_ms": round(max_wait * 1000.0, 3),
                "total_wait_ms": round(total_wait * 1000.0, 3),
            }
            for (src, dst), (count, max_wait, total_wait) in items
        ]


_state: Optional[_TraceState] = None
#: the REAL factories, captured before any patching (TracedLock's own
#: internals must never recurse through the wrapper)
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_install_guard = threading.Lock()


class TracedLock:
    """A ``threading.Lock``/``RLock`` wrapper that records ordering
    edges. Site identity comes from the allocation site so instances
    coalesce; re-entrant RLock re-acquisitions neither push the stack
    twice nor record self-edges."""

    __slots__ = ("_inner", "_site", "_reentrant")

    def __init__(self, inner, site: str, reentrant: bool):
        self._inner = inner
        self._site = site
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        state = _state
        if state is None:
            return self._inner.acquire(blocking, timeout)
        held = state.held()
        start = time.monotonic()
        acquired = self._inner.acquire(blocking, timeout)
        if acquired:
            if not (self._reentrant and self._site in held):
                state.note_edges(
                    self._site, time.monotonic() - start, held
                )
                held.append(self._site)
            elif self._reentrant:
                held.append(self._site)  # balanced with release's pop
        return acquired

    def release(self):
        state = _state
        if state is not None:
            held = state.held()
            if self._site in held:
                # remove the most recent acquisition of this site (locks
                # release LIFO in with-blocks; out-of-order release still
                # drops the right site)
                for index in range(len(held) - 1, -1, -1):
                    if held[index] == self._site:
                        del held[index]
                        break
        return self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def __repr__(self):
        return f"<TracedLock {self._site} {self._inner!r}>"

    def __getattr__(self, name):
        # Condition probes _is_owned/_release_save/_acquire_restore on
        # RLocks; delegate so wait() keeps working (its internal
        # release/reacquire bypasses tracing, which is fine — a parked
        # waiter acquires nothing else meanwhile)
        return getattr(self._inner, name)


def _allocation_site() -> str:
    """``relpath:line`` of the frame that called the lock factory."""
    import sys

    frame = sys._getframe(2)
    filename = frame.f_code.co_filename.replace("\\", "/")
    parts = filename.rsplit("/", 3)
    short = "/".join(parts[-2:]) if len(parts) > 1 else filename
    return f"{short}:{frame.f_lineno}"


class _Factory:
    """A non-function callable: third-party code stores
    ``threading.Lock`` as a CLASS attribute and calls
    ``self.lock_class()`` (werkzeug's routing Map does) — a plain
    function patched into ``threading`` would descriptor-bind there and
    receive a spurious ``self``. Instances don't bind."""

    __slots__ = ("_make", "_reentrant")

    def __init__(self, make, reentrant: bool):
        self._make = make
        self._reentrant = reentrant

    def __call__(self):
        return TracedLock(self._make(), _allocation_site(), self._reentrant)


_traced_lock_factory = _Factory(_REAL_LOCK, reentrant=False)
_traced_rlock_factory = _Factory(_REAL_RLOCK, reentrant=True)


def install_lock_trace(sink_path: Optional[str] = None) -> bool:
    """Patch ``threading.Lock``/``RLock`` so locks created from now on
    are traced; idempotent; returns whether tracing is (now) on. With
    no ``sink_path``, the env knob decides — off means no-op."""
    global _state
    path = sink_path or lock_trace_sink()
    if path is None:
        return _state is not None
    with _install_guard:
        if _state is not None:
            return True
        _state = _TraceState(path)
        threading.Lock = _traced_lock_factory
        threading.RLock = _traced_rlock_factory
        atexit.register(dump_edges)
    return True


def uninstall_lock_trace() -> None:
    """Restore the real factories and drop the trace state (tests)."""
    global _state
    with _install_guard:
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        _state = None


def trace_active() -> bool:
    return _state is not None


def dump_edges(path: Optional[str] = None) -> Optional[str]:
    """Write the aggregated edge table as JSON lines (one edge per
    line; ``meta`` line first). Returns the path written, or None when
    tracing is off. Registered atexit by :func:`install_lock_trace`, so
    a traced test run leaves its sink behind without any teardown
    plumbing."""
    state = _state
    if state is None:
        return None
    if path is None:
        stem, ext = os.path.splitext(state.base_path)
        path = f"{stem}-{os.getpid()}{ext or '.jsonl'}"
    target = path
    edges = state.snapshot()
    directory = os.path.dirname(target)
    if directory:
        os.makedirs(directory, exist_ok=True)
    tmp = f"{target}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as handle:
        handle.write(
            json.dumps(
                {"meta": {"pid": os.getpid(), "edges": len(edges)}}
            )
            + "\n"
        )
        for edge in edges:
            handle.write(json.dumps(edge, sort_keys=True) + "\n")
    os.replace(tmp, target)
    return target


# -- analysis -----------------------------------------------------------------


def load_edges(paths: Iterable[str]) -> List[Dict[str, Any]]:
    """Read edge records from one or more sink files (meta lines and
    unreadable lines are skipped; edges from different pids merge)."""
    merged: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for path in paths:
        try:
            with open(path, encoding="utf-8") as handle:
                lines = handle.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError:
                continue
            if not isinstance(record, dict) or "src" not in record:
                continue
            key = (str(record["src"]), str(record["dst"]))
            entry = merged.get(key)
            if entry is None:
                merged[key] = {
                    "src": key[0],
                    "dst": key[1],
                    "count": int(record.get("count", 1)),
                    "max_wait_ms": float(record.get("max_wait_ms", 0.0)),
                    "total_wait_ms": float(record.get("total_wait_ms", 0.0)),
                }
            else:
                entry["count"] += int(record.get("count", 1))
                entry["max_wait_ms"] = max(
                    entry["max_wait_ms"], float(record.get("max_wait_ms", 0.0))
                )
                entry["total_wait_ms"] += float(record.get("total_wait_ms", 0.0))
    return sorted(merged.values(), key=lambda e: (e["src"], e["dst"]))


def find_cycles(edges: List[Dict[str, Any]]) -> List[List[str]]:
    """Every elementary cycle in the lock-order graph (DFS over SCCs;
    lock graphs are tiny — tens of nodes — so simple enumeration is
    fine). A cycle means two threads order the same locks differently:
    a potential deadlock."""
    graph: Dict[str, List[str]] = {}
    for edge in edges:
        graph.setdefault(edge["src"], []).append(edge["dst"])
        graph.setdefault(edge["dst"], [])
    cycles: List[List[str]] = []
    seen_signatures = set()

    def dfs(start: str, node: str, path: List[str], visiting: set) -> None:
        for nxt in graph.get(node, ()):
            if nxt == start:
                # self-loops (len(path) == 1) are re-entrancy artifacts,
                # not ordering cycles
                if len(path) > 1:
                    # canonical rotation, NOT the node set: A->B->C->A
                    # and A->C->B->A share nodes but are two distinct
                    # ordering violations, both worth reporting
                    pivot = path.index(min(path))
                    signature = tuple(path[pivot:] + path[:pivot])
                    if signature not in seen_signatures:
                        seen_signatures.add(signature)
                        cycles.append(path + [start])
                continue
            if nxt in visiting or nxt < start:
                # only walk nodes ordered after start: each cycle is
                # enumerated exactly once, from its smallest node
                continue
            visiting.add(nxt)
            dfs(start, nxt, path + [nxt], visiting)
            visiting.discard(nxt)

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles


def hotspots(edges: List[Dict[str, Any]], top: int = 10) -> List[Dict[str, Any]]:
    """The held-while-blocking hotspots: edges ranked by the longest
    single wait for ``dst`` while holding ``src`` — where lock convoys
    (and the deadlock *cost*, should a cycle ever close) live."""
    ranked = sorted(edges, key=lambda e: e["max_wait_ms"], reverse=True)
    return ranked[:top]


def analyze(paths: Iterable[str], top: int = 10) -> Dict[str, Any]:
    """The full lock-order report over one or more edge sinks: the
    merged graph, every ordering cycle, and the blocking hotspots.
    ``ok`` is False exactly when a cycle exists — the CI gate."""
    edges = load_edges(paths)
    cycles = find_cycles(edges)
    return {
        "ok": not cycles,
        "edges": len(edges),
        "locks": len({e["src"] for e in edges} | {e["dst"] for e in edges}),
        "cycles": [" -> ".join(cycle) for cycle in cycles],
        "hotspots": hotspots(edges, top=top),
    }


def render_report(report: Dict[str, Any]) -> str:
    lines = [
        f"lock-order graph: {report['locks']} locks, "
        f"{report['edges']} ordering edges"
    ]
    if report["cycles"]:
        lines.append(f"CYCLES ({len(report['cycles'])}) — potential deadlocks:")
        for cycle in report["cycles"]:
            lines.append(f"  {cycle}")
    else:
        lines.append("no ordering cycles (deadlock-free orderings observed)")
    if report["hotspots"]:
        lines.append("held-while-blocking hotspots (worst single wait):")
        for edge in report["hotspots"]:
            lines.append(
                f"  held {edge['src']} -> wanted {edge['dst']}: "
                f"max {edge['max_wait_ms']:.3f}ms over {edge['count']} "
                f"acquisitions ({edge['total_wait_ms']:.3f}ms total)"
            )
    lines.append("lockgraph: " + ("OK" if report["ok"] else "CYCLE DETECTED"))
    return "\n".join(lines)
