"""
Typed view over ``contracts.toml`` — the declared invariants the lint
rules enforce (layering arrows, jax-hazard scopes, the env-knob accessor
contract, atomic-write scopes, clock and prometheus heuristics).
"""

import ast as _ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

try:  # Python >= 3.11
    import tomllib
except ImportError:  # pragma: no cover - exercised on 3.10 images
    tomllib = None

DEFAULT_CONTRACTS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "contracts.toml"
)


@dataclass(frozen=True)
class LayeringArrow:
    """``module`` (and its subtree) may not import from ``forbidden``."""

    module: str
    forbidden: Tuple[str, ...]
    reason: str = ""


@dataclass(frozen=True)
class CowContract:
    """Attributes declared copy-on-write: replaced whole under their
    lock, read lock-free, never mutated in place. ``cls`` empty means
    every scope in ``module``."""

    module: str
    attributes: Tuple[str, ...]
    cls: str = ""
    reason: str = ""


@dataclass
class Contracts:
    """Every contract document, with file defaults where a key is absent."""

    arrows: List[LayeringArrow] = field(default_factory=list)
    jax_sync_scopes: Tuple[str, ...] = ()
    jax_sync_allowed_functions: Tuple[str, ...] = ()
    jax_stdlib_only: Tuple[str, ...] = ()
    jax_heavy_modules: Tuple[str, ...] = ()
    env_prefix: str = "GORDO_TPU_"
    env_accessor_module: str = "gordo_tpu.utils.env"
    env_accessors: Tuple[str, ...] = (
        "env_int",
        "env_float",
        "env_bool",
        "env_str",
        "env_raw",
    )
    atomic_scopes: Tuple[str, ...] = ()
    atomic_allowed_functions: Tuple[str, ...] = ()
    clock_suspect_names: str = "deadline|timeout|expir|backoff|cutoff"
    prometheus_scopes: Tuple[str, ...] = ()
    prometheus_tainted_roots: Tuple[str, ...] = ("request",)
    prometheus_suspect_loop_vars: str = "member|machine|gordo_name"
    concurrency_lock_scopes: Tuple[str, ...] = ()
    concurrency_fork_scopes: Tuple[str, ...] = ()
    concurrency_pid_sources: Tuple[str, ...] = ()
    concurrency_postfork_registrars: Tuple[str, ...] = (
        "register_postfork_reset",
        "os.register_at_fork",
    )
    concurrency_cow: Tuple[CowContract, ...] = ()


def _parse_toml_subset(text: str) -> Dict:
    """
    Minimal TOML reader for ``contracts.toml`` when ``tomllib`` is
    unavailable (Python 3.10 images; installs are off the table — the
    same shim pattern as ``utils/json_compat.py``). Supports exactly what
    the contracts file uses: ``[table]`` / ``[[array.of.tables]]``
    headers, ``key = "string"``, and ``key = [..multi-line string
    array..]``. Values are parsed with ``ast.literal_eval`` after
    normalizing the array across continuation lines.
    """
    doc: Dict = {}
    current: Dict = doc
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        i += 1
        if not line or line.startswith("#"):
            continue
        array_header = re.fullmatch(r"\[\[([\w.\-]+)\]\]", line)
        table_header = re.fullmatch(r"\[([\w.\-]+)\]", line)
        if array_header:
            parts = array_header.group(1).split(".")
            node = doc
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            entries = node.setdefault(parts[-1], [])
            current = {}
            entries.append(current)
            continue
        if table_header:
            parts = table_header.group(1).split(".")
            node = doc
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            current = node.setdefault(parts[-1], {})
            continue
        match = re.match(r"([\w\-]+)\s*=\s*(.*)$", line)
        if not match:
            raise ValueError(f"contracts.toml subset parser: bad line {line!r}")
        key, value = match.group(1), match.group(2)
        # pull in continuation lines until the array literal balances
        while value.count("[") > value.count("]"):
            if i >= len(lines):
                raise ValueError(f"unterminated array for key {key!r}")
            extra = lines[i].split("#", 1)[0].strip() if "#" in lines[i] else lines[i].strip()
            value += " " + extra
            i += 1
        value = value.strip()
        if not value.startswith("["):
            # strip trailing comments off scalar values
            value = value.split("  #", 1)[0].strip()
        current[key] = _ast.literal_eval(value.rstrip(","))
    return doc


def load_contracts(path: Optional[str] = None) -> Contracts:
    """Parse a contracts file (default: the committed ``contracts.toml``)."""
    if tomllib is not None:
        with open(path or DEFAULT_CONTRACTS_PATH, "rb") as handle:
            doc: Dict = tomllib.load(handle)
    else:
        with open(path or DEFAULT_CONTRACTS_PATH, encoding="utf-8") as handle:
            doc = _parse_toml_subset(handle.read())
    layering = doc.get("layering", {})
    arrows = [
        LayeringArrow(
            module=str(entry["module"]),
            forbidden=tuple(entry.get("forbidden", ())),
            reason=str(entry.get("reason", "")),
        )
        for entry in layering.get("arrows", ())
    ]
    jax = doc.get("jax", {})
    env = doc.get("env", {})
    atomic = doc.get("atomic", {})
    clock = doc.get("clock", {})
    prometheus = doc.get("prometheus", {})
    concurrency = doc.get("concurrency", {})
    cow = tuple(
        CowContract(
            module=str(entry.get("module", "")),
            attributes=tuple(entry.get("attributes", ())),
            cls=str(entry.get("class", "")),
            reason=str(entry.get("reason", "")),
        )
        for entry in concurrency.get("cow", ())
    )
    defaults = Contracts()
    return Contracts(
        arrows=arrows,
        jax_sync_scopes=tuple(jax.get("sync_scopes", ())),
        jax_sync_allowed_functions=tuple(jax.get("sync_allowed_functions", ())),
        jax_stdlib_only=tuple(jax.get("stdlib_only", ())),
        jax_heavy_modules=tuple(jax.get("heavy_modules", ())),
        env_prefix=str(env.get("prefix", defaults.env_prefix)),
        env_accessor_module=str(
            env.get("accessor_module", defaults.env_accessor_module)
        ),
        env_accessors=tuple(env.get("accessors", defaults.env_accessors)),
        atomic_scopes=tuple(atomic.get("scopes", ())),
        atomic_allowed_functions=tuple(atomic.get("allowed_functions", ())),
        clock_suspect_names=str(
            clock.get("suspect_names", defaults.clock_suspect_names)
        ),
        prometheus_scopes=tuple(prometheus.get("scopes", ())),
        prometheus_tainted_roots=tuple(
            prometheus.get("tainted_roots", defaults.prometheus_tainted_roots)
        ),
        prometheus_suspect_loop_vars=str(
            prometheus.get(
                "suspect_loop_vars", defaults.prometheus_suspect_loop_vars
            )
        ),
        concurrency_lock_scopes=tuple(concurrency.get("lock_scopes", ())),
        concurrency_fork_scopes=tuple(concurrency.get("fork_scopes", ())),
        concurrency_pid_sources=tuple(concurrency.get("pid_sources", ())),
        concurrency_postfork_registrars=tuple(
            concurrency.get(
                "postfork_registrars", defaults.concurrency_postfork_registrars
            )
        ),
        concurrency_cow=cow,
    )


def in_scope(module: str, scopes: Tuple[str, ...]) -> bool:
    """True when ``module`` is one of ``scopes`` or inside one."""
    return any(
        module == scope or module.startswith(scope + ".") for scope in scopes
    )
