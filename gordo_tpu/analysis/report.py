"""
Rendering for ``gordo-tpu lint``: the human table and the ``--as-json``
document (the same shape the CI annotation step consumes).
"""

from typing import Dict, List

from .baseline import BaselineEntry
from .core import Finding, LintResult


def lint_document(
    result: LintResult,
    new: List[Finding],
    baselined: List[Finding],
    stale: List[BaselineEntry],
) -> Dict:
    """The machine-readable lint outcome (``--as-json``)."""
    return {
        # mirrors the CLI gate exactly: parse errors fail the run too (a
        # file the linter cannot read is not a clean file)
        "ok": not new and not result.parse_errors,
        "counts": {
            "new": len(new),
            "baselined": len(baselined),
            "suppressed": result.suppressed,
            "stale_baseline_entries": len(stale),
            "parse_errors": len(result.parse_errors),
        },
        "findings": [
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "message": f.message,
                "fingerprint": f.fingerprint,
                "baselined": False,
            }
            for f in new
        ]
        + [
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "message": f.message,
                "fingerprint": f.fingerprint,
                "baselined": True,
            }
            for f in baselined
        ],
        "stale_baseline_entries": [
            {"rule": e.rule, "path": e.path, "fingerprint": e.fingerprint}
            for e in stale
        ],
        "parse_errors": list(result.parse_errors),
    }


def render_report(
    result: LintResult,
    new: List[Finding],
    baselined: List[Finding],
    stale: List[BaselineEntry],
) -> str:
    """The human-facing table."""
    lines: List[str] = []
    if new:
        lines.append(f"NEW findings ({len(new)}):")
        for finding in new:
            lines.append(f"  {finding.render()}")
    if baselined:
        lines.append(f"baselined ({len(baselined)} grandfathered):")
        for finding in baselined:
            lines.append(f"  {finding.render()}")
    if stale:
        lines.append(
            f"stale baseline entries ({len(stale)}) — the finding is gone; "
            "remove them:"
        )
        for entry in stale:
            lines.append(f"  {entry.rule} @ {entry.path} [{entry.fingerprint}]")
    if result.parse_errors:
        lines.append(f"parse errors ({len(result.parse_errors)}):")
        for error in result.parse_errors:
            lines.append(f"  {error}")
    # the verdict mirrors the CLI gate: new findings OR parse errors fail
    problems = []
    if new:
        problems.append(f"{len(new)} new finding(s)")
    if result.parse_errors:
        problems.append(f"{len(result.parse_errors)} unparseable file(s)")
    lines.append(
        "lint: "
        + (" + ".join(problems) if problems else "OK")
        + f" ({len(baselined)} baselined, {result.suppressed} suppressed)"
    )
    return "\n".join(lines)
