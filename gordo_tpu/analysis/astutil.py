"""
Shared AST plumbing for the lint rules: parent links, dotted-name
rendering, import resolution (absolute AND relative, module-level AND
lazy in-function), and env-knob name resolution through module-level
constants.
"""

import ast
from typing import Iterator, List, Optional, Tuple

PARENT_ATTR = "_gt_parent"


def annotate_parents(tree: ast.Module) -> ast.Module:
    """Stamp every node with a ``_gt_parent`` link (idempotent)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            setattr(child, PARENT_ATTR, node)
    return tree


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, PARENT_ATTR, None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    current = parent(node)
    while current is not None:
        yield current
        current = parent(current)


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda."""
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return anc
    return None


def enclosing_statement(node: ast.AST) -> ast.AST:
    """The nearest ancestor (or the node itself) that is a statement."""
    current: ast.AST = node
    while not isinstance(current, ast.stmt):
        up = parent(current)
        if up is None:
            return current
        current = up
    return current


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    current = node
    while isinstance(current, ast.Attribute):
        parts.append(current.attr)
        current = current.value
    if isinstance(current, ast.Name):
        parts.append(current.id)
        return ".".join(reversed(parts))
    return None


def resolve_relative(module: str, is_package: bool, level: int, target: str) -> str:
    """Absolute module named by ``from <level dots><target> import ...``.

    ``module`` is the importing module's dotted name, ``is_package``
    whether it is a package ``__init__``.
    """
    if level == 0:
        return target
    base_parts = module.split(".")
    # level 1 from a plain module strips the module segment; from a
    # package __init__ it names the package itself
    strip = level - 1 if is_package else level
    if strip:
        base_parts = base_parts[:-strip] if strip < len(base_parts) else []
    base = ".".join(base_parts)
    if target:
        return f"{base}.{target}" if base else target
    return base


def iter_imports(
    tree: ast.Module, module: str, is_package: bool
) -> Iterator[Tuple[ast.stmt, str]]:
    """Yield (import node, absolute imported-module candidate).

    ``from X import y`` yields both ``X`` and ``X.y`` — ``y`` may be a
    submodule, and a forbidden-prefix check must see it either way.
    """
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                yield node, alias.name
        elif isinstance(node, ast.ImportFrom):
            base = resolve_relative(module, is_package, node.level, node.module or "")
            yield node, base
            for alias in node.names:
                if alias.name != "*":
                    yield node, f"{base}.{alias.name}" if base else alias.name


def call_name(call: ast.Call) -> Optional[str]:
    """Dotted name of a call's callee, e.g. ``os.environ.get``."""
    return dotted_name(call.func)


def first_arg(call: ast.Call) -> Optional[ast.expr]:
    return call.args[0] if call.args else None


def resolve_string(
    node: Optional[ast.expr], local_constants: dict, global_constants: dict
) -> Optional[str]:
    """A string literal, or a Name/Attribute resolving to a module-level
    string constant (file-local table first, then the cross-file table)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    name = dotted_name(node)
    if name is None:
        return None
    if name in local_constants:
        return local_constants[name]
    if name in global_constants:
        return global_constants[name]
    # `telemetry.TRACE_DIR_ENV` where the constant is re-exported: fall
    # back to the bare trailing name (ambiguous names are dropped from
    # the table, so this can't mis-resolve to a conflicting value)
    return global_constants.get(name.rsplit(".", 1)[-1])


def module_string_constants(tree: ast.Module) -> dict:
    """Module-level ``NAME = "literal"`` assignments of this file."""
    table = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            value = node.value
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value = node.value
            targets = [node.target]
        else:
            continue
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            for target in targets:
                if isinstance(target, ast.Name):
                    table[target.id] = value.value
    return table
