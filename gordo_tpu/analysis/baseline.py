"""
The committed lint baseline: grandfathered findings that are understood,
justified, and intentionally not fixed. Matching is by (rule, path,
fingerprint) — fingerprints hash message + occurrence index, not line
numbers, so unrelated edits above a baselined finding don't un-match it.

Every entry MUST carry a non-empty ``justification``; loading a baseline
with an unjustified entry is an error (the whole point is that the
reasoning lives next to the exemption, not in a reviewer's head).
"""

import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .core import Finding

BASELINE_FILENAME = "lint_baseline.json"
BASELINE_VERSION = 1


@dataclass(frozen=True)
class BaselineEntry:
    rule: str
    path: str
    fingerprint: str
    justification: str


class BaselineError(ValueError):
    """Malformed or unjustified baseline document."""


def load_baseline(path: str) -> List[BaselineEntry]:
    """Parse and validate a baseline file; missing file = empty baseline."""
    if not os.path.exists(path):
        return []
    try:
        with open(path, encoding="utf-8") as handle:
            doc = json.load(handle)
    except ValueError as exc:
        raise BaselineError(f"unparseable baseline {path}: {exc}")
    if not isinstance(doc, dict) or doc.get("version") != BASELINE_VERSION:
        raise BaselineError(
            f"baseline {path} must be a dict with version={BASELINE_VERSION}"
        )
    entries: List[BaselineEntry] = []
    for i, raw in enumerate(doc.get("entries", ())):
        entry = BaselineEntry(
            rule=str(raw.get("rule", "")),
            path=str(raw.get("path", "")),
            fingerprint=str(raw.get("fingerprint", "")),
            justification=str(raw.get("justification", "")).strip(),
        )
        if not (entry.rule and entry.path and entry.fingerprint):
            raise BaselineError(
                f"baseline entry #{i} is missing rule/path/fingerprint"
            )
        if not entry.justification:
            raise BaselineError(
                f"baseline entry #{i} ({entry.rule} @ {entry.path}) has no "
                "justification — every grandfathered finding must say why"
            )
        entries.append(entry)
    return entries


def split_by_baseline(
    findings: List[Finding], entries: List[BaselineEntry]
) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
    """(new findings, baselined findings, stale entries)."""
    table: Dict[Tuple[str, str, str], BaselineEntry] = {
        (e.rule, e.path, e.fingerprint): e for e in entries
    }
    new: List[Finding] = []
    matched: List[Finding] = []
    used = set()
    for finding in findings:
        key = (finding.rule, finding.path, finding.fingerprint)
        if key in table:
            matched.append(finding)
            used.add(key)
        else:
            new.append(finding)
    stale = [entry for key, entry in table.items() if key not in used]
    return new, matched, stale


def write_baseline(
    path: str,
    findings: List[Finding],
    justification: str,
    existing: Optional[List[BaselineEntry]] = None,
) -> None:
    """Write a baseline covering ``findings`` (the --update-baseline
    path). Findings already present in ``existing`` KEEP their
    hand-written justifications — only genuinely new entries get the
    shared placeholder ``justification`` to hand-edit."""
    kept: Dict[Tuple[str, str, str], str] = {
        (e.rule, e.path, e.fingerprint): e.justification
        for e in (existing or [])
    }
    doc = {
        "version": BASELINE_VERSION,
        "entries": [
            {
                "rule": f.rule,
                "path": f.path,
                "fingerprint": f.fingerprint,
                "line": f.line,  # informational; matching ignores it
                "message": f.message,  # informational
                "justification": kept.get(
                    (f.rule, f.path, f.fingerprint), justification
                ),
            }
            for f in findings
        ],
    }
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as handle:
        json.dump(doc, handle, indent=1, sort_keys=True)
        handle.write("\n")
    os.replace(tmp, path)


def default_baseline_path(root: Optional[str] = None) -> str:
    return os.path.join(root or os.getcwd(), BASELINE_FILENAME)
