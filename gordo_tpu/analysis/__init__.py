"""
Project-specific static analysis (``gordo-tpu lint``): an AST rule
engine that enforces the codebase's load-bearing invariants in CI —
layering arrows, JAX dispatch hazards, the env-knob registry contract,
atomic artifact writes, monotonic-clock deadline math, Prometheus label
cardinality, and the concurrency contracts (lock-guard inference,
copy-on-write publish discipline, fork-safety, thread lifecycle) — plus
the opt-in runtime lock-order harness (``lockgraph``, the
``GORDO_TPU_LOCK_TRACE`` knob and the ``gordo-tpu lockgraph`` deadlock
gate). See ``docs/static-analysis.md`` for the rule catalog, suppression
(``# gt-lint: disable=<rule>``) and baseline semantics, and the
how-to-add-a-rule guide.
"""

from .baseline import (
    BASELINE_FILENAME,
    BaselineEntry,
    BaselineError,
    default_baseline_path,
    load_baseline,
    split_by_baseline,
    write_baseline,
)
from .contracts import Contracts, CowContract, LayeringArrow, load_contracts
from .core import Finding, LintResult, run_lint
from .lockgraph import (
    LOCK_TRACE_ENV,
    analyze as analyze_lock_graph,
    install_lock_trace,
    lock_trace_sink,
)
from .report import lint_document, render_report
from .rules import default_rules
from .sarif import sarif_document

__all__ = [
    "BASELINE_FILENAME",
    "BaselineEntry",
    "BaselineError",
    "Contracts",
    "CowContract",
    "Finding",
    "LOCK_TRACE_ENV",
    "LayeringArrow",
    "LintResult",
    "analyze_lock_graph",
    "default_baseline_path",
    "default_rules",
    "install_lock_trace",
    "lint_document",
    "load_baseline",
    "load_contracts",
    "lock_trace_sink",
    "render_report",
    "run_lint",
    "sarif_document",
    "split_by_baseline",
    "write_baseline",
]
