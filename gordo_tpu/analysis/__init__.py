"""
Project-specific static analysis (``gordo-tpu lint``): an AST rule
engine that enforces the codebase's load-bearing invariants in CI —
layering arrows, JAX dispatch hazards, the env-knob registry contract,
atomic artifact writes, monotonic-clock deadline math, and Prometheus
label cardinality. See ``docs/static-analysis.md`` for the rule catalog,
suppression (``# gt-lint: disable=<rule>``) and baseline semantics, and
the how-to-add-a-rule guide.
"""

from .baseline import (
    BASELINE_FILENAME,
    BaselineEntry,
    BaselineError,
    default_baseline_path,
    load_baseline,
    split_by_baseline,
    write_baseline,
)
from .contracts import Contracts, LayeringArrow, load_contracts
from .core import Finding, LintResult, run_lint
from .report import lint_document, render_report
from .rules import default_rules

__all__ = [
    "BASELINE_FILENAME",
    "BaselineEntry",
    "BaselineError",
    "Contracts",
    "Finding",
    "LayeringArrow",
    "LintResult",
    "default_baseline_path",
    "default_rules",
    "lint_document",
    "load_baseline",
    "load_contracts",
    "render_report",
    "run_lint",
    "split_by_baseline",
    "write_baseline",
]
