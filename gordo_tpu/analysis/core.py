"""
The lint engine: source loading, suppression comments, rule running, and
finding fingerprints.

A *rule* is a callable object with ``name``/``description`` that yields
:class:`Finding` objects for one parsed :class:`SourceFile`. The engine
parses every file once, collects module-level knob-name string constants
across the tree (rules resolve env-knob names through them), runs each
rule, and drops findings suppressed by a ``# gt-lint: disable=<rule>``
comment on the offending line (or a file-wide
``# gt-lint: file-disable=<rule>``).

Fingerprints are stable across unrelated edits: they hash (rule, path,
message, occurrence-index) — NOT the line number — so a committed
baseline entry keeps matching while code above the finding moves.
"""

import ast
import hashlib
import io
import os
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

#: the suppression comment grammar: ``# gt-lint: disable=<rule>[,<rule>]``
#: (same line or the standalone comment line directly above) and
#: ``# gt-lint: file-disable=<rule>`` (whole file). Free text after
#: `` -- `` is the human justification.
SUPPRESS_MARKER = "gt-lint:"


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str
    fingerprint: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


@dataclass
class SourceFile:
    """One parsed module plus its suppression table."""

    abspath: str
    relpath: str
    module: str  # dotted module name, e.g. ``gordo_tpu.utils.env``
    text: str
    tree: ast.Module
    is_package: bool = False  # an ``__init__.py``
    line_suppressions: Dict[int, Set[str]] = field(default_factory=dict)
    file_suppressions: Set[str] = field(default_factory=set)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions:
            return True
        return rule in self.line_suppressions.get(line, ())


@dataclass
class LintContext:
    """Cross-file state rules may consult."""

    root: str
    contracts: "object"
    #: every module-level ``NAME = "<env prefix>..."`` constant in the tree:
    #: both the bare name and its ``module.NAME`` spelling map to the value
    env_constants: Dict[str, str] = field(default_factory=dict)
    files: List[SourceFile] = field(default_factory=list)


@dataclass
class LintResult:
    findings: List[Finding]
    suppressed: int
    parse_errors: List[str]


def _parse_suppressions(
    text: str,
) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """Extract line- and file-level suppressions from comments."""
    line_rules: Dict[int, Set[str]] = {}
    file_rules: Set[str] = set()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return line_rules, file_rules
    #: physical lines that hold only a comment — their suppression applies
    #: to the next LOGICAL line (every physical line of it: rules anchor
    #: findings on the flagged node's own line, which for a wrapped
    #: statement is a continuation line)
    code_lines: Set[int] = set()
    comments: List[Tuple[int, str]] = []
    logical_ranges: List[Tuple[int, int]] = []
    logical_start: Optional[int] = None
    for tok in tokens:
        if tok.type == tokenize.COMMENT:
            comments.append((tok.start[0], tok.string))
        elif tok.type == tokenize.NEWLINE:
            if logical_start is not None:
                logical_ranges.append((logical_start, tok.end[0]))
                logical_start = None
        elif tok.type not in (
            tokenize.NL,
            tokenize.INDENT,
            tokenize.DEDENT,
            tokenize.ENCODING,
            tokenize.ENDMARKER,
        ):
            code_lines.add(tok.start[0])
            if logical_start is None:
                logical_start = tok.start[0]
    if logical_start is not None:  # EOF without trailing NEWLINE
        logical_ranges.append((logical_start, max(code_lines, default=logical_start)))
    for lineno, comment in comments:
        body = comment.lstrip("#").strip()
        if not body.startswith(SUPPRESS_MARKER):
            continue
        directive = body[len(SUPPRESS_MARKER):].strip()
        # strip the ` -- justification` tail
        directive = directive.split("--", 1)[0].strip()
        if directive.startswith("file-disable="):
            file_rules.update(
                r.strip() for r in directive[len("file-disable="):].split(",") if r.strip()
            )
            continue
        if not directive.startswith("disable="):
            continue
        rules = {
            r.strip() for r in directive[len("disable="):].split(",") if r.strip()
        }
        if lineno in code_lines:
            targets = [lineno]
        else:
            # standalone comment: guards every physical line of the
            # logical statement it sits INSIDE (a comment line within a
            # wrapped call) or, failing that, of the next one — findings
            # may anchor on any continuation line
            containing = [r for r in logical_ranges if r[0] <= lineno <= r[1]]
            following = [r for r in logical_ranges if r[0] > lineno]
            if containing:
                start, end = containing[0]
                targets = list(range(start, end + 1))
            elif following:
                start, end = min(following)
                targets = list(range(start, end + 1))
            else:
                targets = [lineno]
        for target in targets:
            line_rules.setdefault(target, set()).update(rules)
    return line_rules, file_rules


def module_name_for(root: str, abspath: str) -> str:
    rel = os.path.relpath(abspath, root)
    parts = rel.replace(os.sep, "/").split("/")
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][: -len(".py")]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(p for p in parts if p)


def load_source_file(root: str, abspath: str) -> SourceFile:
    with open(abspath, encoding="utf-8") as handle:
        text = handle.read()
    tree = ast.parse(text, filename=abspath)
    from .astutil import annotate_parents

    annotate_parents(tree)
    line_rules, file_rules = _parse_suppressions(text)
    return SourceFile(
        abspath=abspath,
        relpath=os.path.relpath(abspath, root).replace(os.sep, "/"),
        module=module_name_for(root, abspath),
        text=text,
        tree=tree,
        is_package=os.path.basename(abspath) == "__init__.py",
        line_suppressions=line_rules,
        file_suppressions=file_rules,
    )


def iter_python_files(root: str, paths: Optional[Sequence[str]] = None) -> Iterator[str]:
    """Yield .py files under ``paths`` (default: ``<root>/gordo_tpu``)."""
    targets = [os.path.join(root, p) for p in paths] if paths else [
        os.path.join(root, "gordo_tpu")
    ]
    for target in targets:
        if os.path.isfile(target):
            yield target
            continue
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def collect_env_constants(files: Iterable[SourceFile], prefix: str) -> Dict[str, str]:
    """Module-level ``NAME = "<prefix>..."`` constants across the tree.

    Both ``NAME`` and ``<module tail>.NAME`` spellings are recorded so a
    rule can resolve ``os.getenv(TRACE_DIR_ENV)`` and
    ``os.getenv(telemetry.TRACE_DIR_ENV)`` alike. A bare name claimed by
    two modules with DIFFERENT values resolves to neither (ambiguous).
    """
    table: Dict[str, str] = {}
    ambiguous: Set[str] = set()
    for file in files:
        for node in file.tree.body:
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            if not (isinstance(value, ast.Constant) and isinstance(value.value, str)):
                continue
            if not value.value.startswith(prefix):
                continue
            for target in targets:
                if not isinstance(target, ast.Name):
                    continue
                name = target.id
                # every dotted suffix of the module path too, so both
                # ``telemetry.X`` and ``recorder.X`` resolve; any key —
                # bare OR dotted — claimed with two different values is
                # ambiguous and resolves to neither
                parts = file.module.split(".")
                keys = [name] + [
                    ".".join(parts[i:] + [name]) for i in range(len(parts))
                ]
                for key in keys:
                    if key in table and table[key] != value.value:
                        ambiguous.add(key)
                    table.setdefault(key, value.value)
    for key in ambiguous:
        table.pop(key, None)
    return table


def fingerprint_findings(findings: List[Finding]) -> List[Finding]:
    """Assign occurrence-indexed stable fingerprints."""
    seen: Dict[Tuple[str, str, str], int] = {}
    out: List[Finding] = []
    for finding in findings:
        key = (finding.rule, finding.path, finding.message)
        index = seen.get(key, 0)
        seen[key] = index + 1
        digest = hashlib.sha1(
            f"{finding.rule}|{finding.path}|{finding.message}|{index}".encode()
        ).hexdigest()[:16]
        out.append(
            Finding(
                rule=finding.rule,
                path=finding.path,
                line=finding.line,
                col=finding.col,
                message=finding.message,
                fingerprint=digest,
            )
        )
    return out


def run_lint(
    root: str,
    rules: Sequence["object"],
    paths: Optional[Sequence[str]] = None,
    contracts: Optional["object"] = None,
) -> LintResult:
    """Parse, run every rule, apply suppressions, fingerprint."""
    if contracts is None:
        from .contracts import load_contracts

        contracts = load_contracts()
    files: List[SourceFile] = []
    parse_errors: List[str] = []
    for path in iter_python_files(root, paths):
        try:
            files.append(load_source_file(root, path))
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            parse_errors.append(f"{path}: {exc}")
    ctx = LintContext(root=root, contracts=contracts, files=files)
    ctx.env_constants = collect_env_constants(
        files, getattr(contracts, "env_prefix", "GORDO_TPU_")
    )
    findings: List[Finding] = []
    suppressed = 0
    for file in files:
        for rule in rules:
            for finding in rule.check(file, ctx):
                if file.suppressed(finding.rule, finding.line):
                    suppressed += 1
                else:
                    findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule, f.message))
    return LintResult(
        findings=fingerprint_findings(findings),
        suppressed=suppressed,
        parse_errors=parse_errors,
    )
