"""
``clock-discipline`` — ``time.time()`` must not feed deadline, backoff,
or queue-wait arithmetic; wall clocks jump (NTP steps, suspend/resume)
and a stepped clock turns a 2-second batch deadline into an instant
mass-shed or a never-expiring wait. ``time.monotonic()`` is the contract
for interval math; wall time is for timestamps people read.

The heuristic is statement-local: a ``time.time()`` call is flagged when
the statement it sits in also mentions a name matching the configured
suspect pattern (``deadline``/``timeout``/``expir``/``backoff``/...).
Legitimate wall-clock uses that trip it (e.g. persisted cross-restart
cutoffs) carry a suppression or a baseline entry with justification.
"""

import ast
import re
from typing import Iterator

from ..astutil import call_name, enclosing_statement
from ..core import Finding, LintContext, SourceFile


def _statement_names(stmt: ast.AST) -> Iterator[str]:
    for node in ast.walk(stmt):
        if isinstance(node, ast.Name):
            yield node.id
        elif isinstance(node, ast.Attribute):
            yield node.attr
        elif isinstance(node, ast.arg):
            yield node.arg


class ClockDisciplineRule:
    name = "clock-discipline"
    description = (
        "deadline/backoff/queue-wait arithmetic must use time.monotonic(),"
        " not time.time()"
    )

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        suspect = re.compile(ctx.contracts.clock_suspect_names, re.IGNORECASE)
        for node in ast.walk(file.tree):
            if not isinstance(node, ast.Call):
                continue
            if (call_name(node) or "") != "time.time":
                continue
            stmt = enclosing_statement(node)
            suspects = sorted(
                {name for name in _statement_names(stmt) if suspect.search(name)}
            )
            if not suspects:
                continue
            yield Finding(
                rule=self.name,
                path=file.relpath,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    "time.time() in deadline math (statement touches "
                    f"{', '.join(suspects)}) — wall clocks step; use "
                    "time.monotonic() for intervals"
                ),
            )
