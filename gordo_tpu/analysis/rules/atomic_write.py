"""
``atomic-write`` — artifact writes in the builder/lifecycle/serializer
paths must be crash-safe: either routed through
``serializer.dump_atomic`` or staged to a temp path the same function
``os.replace``/``os.rename``-s into place. A bare ``open(path, "w")``
that dies mid-write leaves a torn file exactly where the fleet store,
a ``--resume`` pass, or the lifecycle supervisor would load it.

Append-mode opens are exempt (the build journal's event overlay is an
append-only design), as are reads.
"""

import ast
from typing import Iterator, Optional

from ..astutil import call_name, enclosing_function
from ..contracts import in_scope
from ..core import Finding, LintContext, SourceFile

#: dotted-callee tails that serialize to a target
_DUMP_TAILS = ("dump", "save", "savez", "savez_compressed", "to_parquet", "to_csv")
#: roots whose .dump writes a file (pickle.dump(obj, fh) etc.)
_DUMP_ROOTS = ("json", "simplejson", "pickle", "np", "numpy", "joblib")

_RENAMERS = ("os.replace", "os.rename", "replace", "rename")


def _open_write_mode(call: ast.Call) -> Optional[str]:
    """The mode string of an ``open()`` call when it writes, else None."""
    if (call_name(call) or "").split(".")[-1] != "open":
        return None
    mode: Optional[ast.expr] = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return None  # default "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        if any(flag in mode.value for flag in ("w", "x", "+")):
            return mode.value
    return None


def _function_renames(fn: Optional[ast.AST]) -> bool:
    """Does the enclosing function atomically rename something into
    place? (The write-to-staging-then-replace idiom.)"""
    if fn is None:
        return False
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = call_name(node) or ""
            if callee in _RENAMERS or callee.split(".")[-1] in ("replace", "rename"):
                # str.replace() is not a file rename; require an `os.`
                # root or a bare name imported from os
                root = callee.split(".")[0]
                if root in ("os", "replace", "rename"):
                    return True
    return False


def _is_dump_call(call: ast.Call) -> Optional[str]:
    callee = call_name(call)
    if callee is None:
        return None
    parts = callee.split(".")
    if parts[-1] not in _DUMP_TAILS:
        return None
    if parts[-1] in ("to_parquet", "to_csv"):
        return callee
    if len(parts) >= 2 and parts[-2] in _DUMP_ROOTS:
        return callee
    return None


class AtomicWriteRule:
    name = "atomic-write"
    description = (
        "artifact writes must go through dump_atomic or a "
        "stage-then-os.replace in the same function"
    )

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        if not in_scope(file.module, ctx.contracts.atomic_scopes):
            return
        allowed = set(ctx.contracts.atomic_allowed_functions)
        for node in ast.walk(file.tree):
            if not isinstance(node, ast.Call):
                continue
            mode = _open_write_mode(node)
            dump_callee = None if mode else _is_dump_call(node)
            if mode is None and dump_callee is None:
                continue
            fn = enclosing_function(node)
            if fn is not None and getattr(fn, "name", None) in allowed:
                continue
            if _function_renames(fn):
                continue
            what = (
                f"open(..., {mode!r})" if mode is not None else f"`{dump_callee}`"
            )
            yield Finding(
                rule=self.name,
                path=file.relpath,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"{what} writes in an artifact path without "
                    "dump_atomic or a stage-then-os.replace — a crash "
                    "mid-write leaves a torn file where a loader would "
                    "pick it up"
                ),
            )
