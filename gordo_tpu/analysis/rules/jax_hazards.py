"""
``jax-*`` — the three JAX dispatch/recompile hazards this codebase has
been burned by (CHANGES.md PRs 3-5):

- ``jax-device-sync``: ``block_until_ready``/``device_get`` outside a
  ``program_span`` wrapper in the program-path packages. An unattributed
  device sync either skews the telemetry compile/run split or blocks the
  request thread where the engine expects async dispatch.
- ``jax-stdlib-only``: array/device/server imports (even lazy) inside the
  packages contracted to run stdlib-only in any process.
- ``jax-static-argnum``: ``jax.jit`` static argnums/argnames pointing at
  parameters whose defaults or annotations are unhashable — each call
  would mint a fresh program-cache signature (or TypeError at dispatch).
"""

import ast
from typing import Dict, Iterator, List, Optional, Sequence

from ..astutil import ancestors, call_name, dotted_name, enclosing_function
from ..contracts import in_scope
from ..core import Finding, LintContext, SourceFile

_SYNC_CALLS = ("block_until_ready", "device_get")

#: AST nodes that are unhashable literals when used as a default
_UNHASHABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)

#: annotations that name unhashable containers
_UNHASHABLE_ANNOTATIONS = {"list", "dict", "set", "List", "Dict", "Set", "bytearray"}


def _in_program_span(node: ast.AST) -> bool:
    """Is the node lexically under ``with ... program_span(...)``?"""
    for anc in ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    callee = call_name(expr) or ""
                    if callee.split(".")[-1] in ("program_span", "record"):
                        return True
    return False


class JaxDeviceSyncRule:
    name = "jax-device-sync"
    description = (
        "device syncs in program-path packages must run inside a "
        "program_span wrapper or a sanctioned helper"
    )

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        if not in_scope(file.module, ctx.contracts.jax_sync_scopes):
            return
        allowed = set(ctx.contracts.jax_sync_allowed_functions)
        for node in ast.walk(file.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = call_name(node)
            if callee is None or callee.split(".")[-1] not in _SYNC_CALLS:
                continue
            if _in_program_span(node):
                continue
            fn = enclosing_function(node)
            if fn is not None and getattr(fn, "name", None) in allowed:
                continue
            yield Finding(
                rule=self.name,
                path=file.relpath,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"`{callee}` outside a program_span wrapper — the "
                    "device sync is invisible to compile/run attribution"
                ),
            )


class StdlibOnlyRule:
    name = "jax-stdlib-only"
    description = (
        "contracted stdlib-only packages must not import device/array/"
        "server modules, even lazily"
    )

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        if not in_scope(file.module, ctx.contracts.jax_stdlib_only):
            return
        heavy = set(ctx.contracts.jax_heavy_modules)
        for node in ast.walk(file.tree):
            names: List[str] = []
            if isinstance(node, ast.Import):
                names = [alias.name for alias in node.names]
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                names = [node.module or ""]
            else:
                continue
            for imported in names:
                root = imported.split(".")[0]
                if root in heavy:
                    yield Finding(
                        rule=self.name,
                        path=file.relpath,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"stdlib-only package imports `{imported}` — "
                            f"{file.module.split('.')[1] if '.' in file.module else file.module} "
                            "is contracted to run in any process without "
                            "device/array/server deps"
                        ),
                    )


def _static_positions(call: ast.Call) -> "tuple":
    """Declared static argnums/argnames on a jit call, best effort."""
    nums: List[int] = []
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = _int_list(kw.value)
        elif kw.arg == "static_argnames":
            names = _str_list(kw.value)
    return nums, names


def _int_list(node: ast.expr) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            el.value
            for el in node.elts
            if isinstance(el, ast.Constant) and isinstance(el.value, int)
        ]
    return []


def _str_list(node: ast.expr) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            el.value
            for el in node.elts
            if isinstance(el, ast.Constant) and isinstance(el.value, str)
        ]
    return []


def _annotation_unhashable(annotation: Optional[ast.expr]) -> bool:
    if annotation is None:
        return False
    target = annotation
    if isinstance(target, ast.Subscript):  # list[int], Dict[str, int], ...
        target = target.value
    name = dotted_name(target)
    return bool(name) and name.split(".")[-1] in _UNHASHABLE_ANNOTATIONS


def _check_params(
    fn: ast.AST, nums: Sequence[int], names: Sequence[str]
) -> Iterator[str]:
    """Messages for unhashable static params of a function/lambda."""
    args = fn.args
    params = list(args.posonlyargs) + list(args.args)
    defaults: Dict[str, ast.expr] = {}
    if args.defaults:
        for param, default in zip(params[len(params) - len(args.defaults):], args.defaults):
            defaults[param.arg] = default
    for kwarg, default in zip(args.kwonlyargs, args.kw_defaults):
        if default is not None:
            defaults[kwarg.arg] = default
    selected = []
    for num in nums:
        if 0 <= num < len(params):
            selected.append(params[num])
    by_name = {p.arg: p for p in params + list(args.kwonlyargs)}
    for name in names:
        if name in by_name:
            selected.append(by_name[name])
    for param in selected:
        if isinstance(defaults.get(param.arg), _UNHASHABLE_LITERALS):
            yield (
                f"static argument `{param.arg}` defaults to an unhashable "
                "literal — jit would TypeError (or mint a signature per "
                "call if coerced)"
            )
        elif _annotation_unhashable(getattr(param, "annotation", None)):
            yield (
                f"static argument `{param.arg}` is annotated as an "
                "unhashable container — every distinct value mints a new "
                "program-cache signature"
            )


class JaxStaticArgnumRule:
    name = "jax-static-argnum"
    description = (
        "jit static argnums/argnames must point at hashable parameters"
    )

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        #: module-level function defs, for resolving jax.jit(fn, ...)
        functions: Dict[str, ast.AST] = {
            node.name: node
            for node in ast.walk(file.tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in ast.walk(file.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = call_name(node) or ""
            tail = callee.split(".")[-1]
            target: Optional[ast.AST] = None
            jit_call = node
            if tail in ("jit", "pmap"):
                if node.args:
                    arg0 = node.args[0]
                    if isinstance(arg0, ast.Lambda):
                        target = arg0
                    elif isinstance(arg0, ast.Name):
                        target = functions.get(arg0.id)
            elif tail == "partial" and node.args:
                inner = dotted_name(node.args[0]) or ""
                if inner.split(".")[-1] in ("jit", "pmap"):
                    # decorator form: @partial(jax.jit, static_argnums=...)
                    from ..astutil import parent

                    up = parent(node)
                    if isinstance(up, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        target = up
            if target is None:
                continue
            nums, names = _static_positions(jit_call)
            if not nums and not names:
                continue
            for message in _check_params(target, nums, names):
                yield Finding(
                    rule=self.name,
                    path=file.relpath,
                    line=jit_call.lineno,
                    col=jit_call.col_offset,
                    message=message,
                )
