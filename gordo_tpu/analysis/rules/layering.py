"""
``layering`` — import-dependency arrows from ``contracts.toml``.

Each arrow declares that one package may not import from a set of
forbidden dotted prefixes. Both module-level and lazy in-function
imports count: a lazy import still creates the dependency, it just hides
it from import-time cycle detection.
"""

from typing import Iterator

from ..astutil import iter_imports
from ..contracts import in_scope
from ..core import Finding, LintContext, SourceFile


class LayeringRule:
    name = "layering"
    description = (
        "package imports must follow the dependency arrows declared in "
        "contracts.toml"
    )

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        arrows = [
            arrow
            for arrow in ctx.contracts.arrows
            if in_scope(file.module, (arrow.module,))
        ]
        if not arrows:
            return
        seen = set()
        for node, imported in iter_imports(file.tree, file.module, file.is_package):
            for arrow in arrows:
                for forbidden in arrow.forbidden:
                    if not in_scope(imported, (forbidden,)):
                        continue
                    key = (node.lineno, forbidden)
                    if key in seen:
                        continue
                    seen.add(key)
                    why = f" ({arrow.reason})" if arrow.reason else ""
                    yield Finding(
                        rule=self.name,
                        path=file.relpath,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"{arrow.module} must not import from "
                            f"{forbidden} (imports {imported}){why}"
                        ),
                    )
