"""
The rule catalog. Each rule is a small object with ``name`` /
``description`` and a ``check(file, ctx)`` generator; ``default_rules()``
builds the shipped set (see ``docs/static-analysis.md`` for the catalog
and the how-to-add-a-rule guide).
"""

from typing import Dict, List, Optional

from .atomic_write import AtomicWriteRule
from .clock import ClockDisciplineRule
from .concurrency import (
    CowPublishRule,
    ForkSafetyRule,
    LockGuardRule,
    ThreadLifecycleRule,
)
from .env_registry import EnvRegistryRule
from .jax_hazards import JaxDeviceSyncRule, JaxStaticArgnumRule, StdlibOnlyRule
from .layering import LayeringRule
from .prometheus_cardinality import PrometheusCardinalityRule

__all__ = [
    "AtomicWriteRule",
    "ClockDisciplineRule",
    "CowPublishRule",
    "EnvRegistryRule",
    "ForkSafetyRule",
    "JaxDeviceSyncRule",
    "JaxStaticArgnumRule",
    "LockGuardRule",
    "StdlibOnlyRule",
    "LayeringRule",
    "PrometheusCardinalityRule",
    "ThreadLifecycleRule",
    "default_rules",
]


def default_rules(env_registry: Optional[Dict] = None) -> List:
    """The shipped rule set; ``env_registry`` overrides the live knob
    registry (fixture tests pass a controlled one)."""
    return [
        LayeringRule(),
        JaxDeviceSyncRule(),
        StdlibOnlyRule(),
        JaxStaticArgnumRule(),
        EnvRegistryRule(registry=env_registry),
        AtomicWriteRule(),
        ClockDisciplineRule(),
        PrometheusCardinalityRule(),
        LockGuardRule(),
        CowPublishRule(),
        ForkSafetyRule(),
        ThreadLifecycleRule(),
    ]
