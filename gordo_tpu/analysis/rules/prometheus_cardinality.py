"""
``prometheus-cardinality`` — metric label values must come from bounded
sets. A label value interpolated from a request-derived string (raw
path, query arg, regex capture that isn't collapsed back to a route
shape) mints one timeseries per distinct input: scanners and typo'd
URLs then grow the scrape set without bound — the exact failure the
server's ``{unmatched}``-collapse guards against (PR 3).

Flagged label-value shapes, per ``.labels(...)`` call in the scoped
packages:

- f-strings with interpolations, ``str.format`` calls, and string
  concatenation with non-constants — unbounded by construction;
- expressions reading ``request.*`` (the configured taint roots);
- local names assigned from ``request.*`` or from a regex
  ``.group(...)`` in the same function, unless the assignment also
  passes through an obvious collapse (a string constant result).
"""

import ast
from typing import Iterator, Optional, Set

from ..astutil import call_name, dotted_name, enclosing_function
from ..contracts import in_scope
from ..core import Finding, LintContext, SourceFile


def _iter_taint_nodes(node: ast.AST) -> Iterator[ast.AST]:
    """Walk an expression WITHOUT descending into call arguments: a
    callee owns the boundedness of its return value (`self._labels(...)`
    collapses paths to route shapes — its result is sanitized, not
    tainted by the `request` it takes). The call node itself is still
    yielded (``.group``/``.format`` taint directly), and the callee
    expression is walked so ``request.args.get(...)`` still reads as a
    direct request access."""
    stack = [node]
    while stack:
        sub = stack.pop()
        yield sub
        if isinstance(sub, ast.Call):
            stack.append(sub.func)
            continue
        stack.extend(ast.iter_child_nodes(sub))


def _is_tainted_expr(node: ast.AST, roots: Set[str], local_taint: Set[str]) -> Optional[str]:
    """Why this expression is request-derived, or None."""
    for sub in _iter_taint_nodes(node):
        if isinstance(sub, ast.JoinedStr):
            if any(isinstance(v, ast.FormattedValue) for v in sub.values):
                return "f-string interpolation"
        elif isinstance(sub, ast.Call):
            callee = call_name(sub) or ""
            tail = callee.split(".")[-1]
            if tail == "format":
                return "str.format interpolation"
            if tail == "group":
                return "regex capture"
        elif isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Add):
            sides = (sub.left, sub.right)
            if any(
                isinstance(s, ast.Constant) and isinstance(s.value, str)
                for s in sides
            ) and any(not isinstance(s, ast.Constant) for s in sides):
                return "string concatenation"
        elif isinstance(sub, (ast.Name, ast.Attribute)):
            name = dotted_name(sub)
            if name is None:
                continue
            root = name.split(".")[0]
            if name in roots or root in roots:
                return f"`{name}`"
            if isinstance(sub, ast.Name) and sub.id in local_taint:
                return f"`{sub.id}` (assigned from a request-derived value)"
    return None


def _local_tainted_names(fn: Optional[ast.AST], roots: Set[str]) -> Set[str]:
    """Names assigned from request.* or regex captures in this function."""
    tainted: Set[str] = set()
    if fn is None:
        return tainted
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        why = _is_tainted_expr(node.value, roots, set())
        if why is None:
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                tainted.add(target.id)
    return tainted


class PrometheusCardinalityRule:
    name = "prometheus-cardinality"
    description = (
        "metric label values must come from bounded sets, not "
        "request-derived strings"
    )

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        if not in_scope(file.module, ctx.contracts.prometheus_scopes):
            return
        roots = set(ctx.contracts.prometheus_tainted_roots)
        for node in ast.walk(file.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (
                isinstance(node.func, ast.Attribute) and node.func.attr == "labels"
            ):
                continue
            local_taint = _local_tainted_names(enclosing_function(node), roots)
            values = list(node.args) + [
                kw.value for kw in node.keywords if kw.arg is not None
            ]
            for value in values:
                why = _is_tainted_expr(value, roots, local_taint)
                if why is None:
                    continue
                yield Finding(
                    rule=self.name,
                    path=file.relpath,
                    line=value.lineno,
                    col=value.col_offset,
                    message=(
                        f"label value flows from {why} — unbounded label "
                        "values mint a timeseries per distinct input; "
                        "collapse to a route shape or a bounded enum first"
                    ),
                )
