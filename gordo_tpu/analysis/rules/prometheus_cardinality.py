"""
``prometheus-cardinality`` — metric label values must come from bounded
sets. A label value interpolated from a request-derived string (raw
path, query arg, regex capture that isn't collapsed back to a route
shape) mints one timeseries per distinct input: scanners and typo'd
URLs then grow the scrape set without bound — the exact failure the
server's ``{unmatched}``-collapse guards against (PR 3).

Flagged label-value shapes, per ``.labels(...)`` call in the scoped
packages:

- f-strings with interpolations, ``str.format`` calls, and string
  concatenation with non-constants — unbounded by construction;
- expressions reading ``request.*`` (the configured taint roots);
- local names assigned from ``request.*`` or from a regex
  ``.group(...)`` in the same function, unless the assignment also
  passes through an obvious collapse (a string constant result);
- **member identities** (PR 9): values whose dotted name matches the
  configured ``suspect_loop_vars`` regex (``machine.name``, ``member``,
  ``gordo_name``) or loop variables iterating a collection whose name
  matches it (``for name, loss in member_losses.items(): ...``). A
  per-fleet-member label value mints one timeseries per machine — the
  ``gordo_fleet_member_final_loss`` failure class; per-member values
  belong in the fleet health ledger (``telemetry/fleet_health.py``),
  Prometheus gets bounded aggregates.
"""

import ast
import re
from typing import Iterator, Optional, Set

from ..astutil import call_name, dotted_name, enclosing_function
from ..contracts import in_scope
from ..core import Finding, LintContext, SourceFile


def _iter_taint_nodes(node: ast.AST) -> Iterator[ast.AST]:
    """Walk an expression WITHOUT descending into call arguments: a
    callee owns the boundedness of its return value (`self._labels(...)`
    collapses paths to route shapes — its result is sanitized, not
    tainted by the `request` it takes). The call node itself is still
    yielded (``.group``/``.format`` taint directly), and the callee
    expression is walked so ``request.args.get(...)`` still reads as a
    direct request access."""
    stack = [node]
    while stack:
        sub = stack.pop()
        yield sub
        if isinstance(sub, ast.Call):
            stack.append(sub.func)
            continue
        stack.extend(ast.iter_child_nodes(sub))


def _is_tainted_expr(node: ast.AST, roots: Set[str], local_taint: Set[str]) -> Optional[str]:
    """Why this expression is request-derived, or None."""
    for sub in _iter_taint_nodes(node):
        if isinstance(sub, ast.JoinedStr):
            if any(isinstance(v, ast.FormattedValue) for v in sub.values):
                return "f-string interpolation"
        elif isinstance(sub, ast.Call):
            callee = call_name(sub) or ""
            tail = callee.split(".")[-1]
            if tail == "format":
                return "str.format interpolation"
            if tail == "group":
                return "regex capture"
        elif isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Add):
            sides = (sub.left, sub.right)
            if any(
                isinstance(s, ast.Constant) and isinstance(s.value, str)
                for s in sides
            ) and any(not isinstance(s, ast.Constant) for s in sides):
                return "string concatenation"
        elif isinstance(sub, (ast.Name, ast.Attribute)):
            name = dotted_name(sub)
            if name is None:
                continue
            root = name.split(".")[0]
            if name in roots or root in roots:
                return f"`{name}`"
            if isinstance(sub, ast.Name) and sub.id in local_taint:
                return f"`{sub.id}` (assigned from a request-derived value)"
    return None


def _suspect_loop_targets(
    fn: Optional[ast.AST], suspect: "re.Pattern"
) -> Set[str]:
    """Names bound as for-loop (or comprehension) targets whose iterated
    expression's dotted name matches the member-identity regex — e.g.
    ``name`` in ``for name, loss in member_losses.items():``. Iterating
    a bounded constant (``for stage in ("decode", "infer")``) never
    qualifies: the taint is the member COLLECTION, not loops per se."""
    targets: Set[str] = set()
    if fn is None:
        return targets
    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iter_expr, target_nodes = node.iter, [node.target]
        elif isinstance(node, ast.comprehension):
            iter_expr, target_nodes = node.iter, [node.target]
        else:
            continue
        # `members.items()` / `sorted(machines)` — look through the call
        # to the collection expression it reads
        probe = iter_expr
        while isinstance(probe, ast.Call):
            probe = (
                probe.func
                if not probe.args
                else probe.args[0]
            )
        name = dotted_name(probe) or ""
        if not suspect.search(name.lower()):
            continue
        for target in target_nodes:
            for sub in ast.walk(target):
                if isinstance(sub, ast.Name):
                    targets.add(sub.id)
    return targets


def _member_suspect(
    node: ast.AST, suspect: "re.Pattern", loop_targets: Set[str]
) -> Optional[str]:
    """Why this label value looks like a per-member identity, or None."""
    for sub in _iter_taint_nodes(node):
        if not isinstance(sub, (ast.Name, ast.Attribute)):
            continue
        name = dotted_name(sub)
        if name is None:
            continue
        if suspect.search(name.lower()):
            return f"member-identity name `{name}`"
        if isinstance(sub, ast.Name) and sub.id in loop_targets:
            return (
                f"loop variable `{sub.id}` over a member collection"
            )
    return None


def _local_tainted_names(fn: Optional[ast.AST], roots: Set[str]) -> Set[str]:
    """Names assigned from request.* or regex captures in this function."""
    tainted: Set[str] = set()
    if fn is None:
        return tainted
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        why = _is_tainted_expr(node.value, roots, set())
        if why is None:
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                tainted.add(target.id)
    return tainted


class PrometheusCardinalityRule:
    name = "prometheus-cardinality"
    description = (
        "metric label values must come from bounded sets, not "
        "request-derived strings"
    )

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        if not in_scope(file.module, ctx.contracts.prometheus_scopes):
            return
        roots = set(ctx.contracts.prometheus_tainted_roots)
        suspect = re.compile(ctx.contracts.prometheus_suspect_loop_vars)
        for node in ast.walk(file.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (
                isinstance(node.func, ast.Attribute) and node.func.attr == "labels"
            ):
                continue
            fn = enclosing_function(node)
            local_taint = _local_tainted_names(fn, roots)
            loop_targets = _suspect_loop_targets(fn, suspect)
            values = list(node.args) + [
                kw.value for kw in node.keywords if kw.arg is not None
            ]
            for value in values:
                why = _is_tainted_expr(value, roots, local_taint)
                if why is not None:
                    yield Finding(
                        rule=self.name,
                        path=file.relpath,
                        line=value.lineno,
                        col=value.col_offset,
                        message=(
                            f"label value flows from {why} — unbounded label "
                            "values mint a timeseries per distinct input; "
                            "collapse to a route shape or a bounded enum first"
                        ),
                    )
                    continue
                why = _member_suspect(value, suspect, loop_targets)
                if why is not None:
                    yield Finding(
                        rule=self.name,
                        path=file.relpath,
                        line=value.lineno,
                        col=value.col_offset,
                        message=(
                            f"label value is a {why} — one timeseries per "
                            "fleet member is unbounded cardinality (the "
                            "gordo_fleet_member_final_loss failure class); "
                            "route per-member values into the fleet health "
                            "ledger and export bounded aggregates"
                        ),
                    )
