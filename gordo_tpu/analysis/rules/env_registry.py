"""
``env-registry`` — every ``GORDO_TPU_*`` environment read must go
through the typed accessors in ``gordo_tpu/utils/env.py`` and name a
knob declared (with a doc line) in its registry.

Knob names resolve through string literals, module-level ``NAME_ENV =
"<knob name>"`` constants in the same file, and such constants anywhere
in the linted tree (``os.getenv(telemetry.TRACE_DIR_ENV)`` resolves).
Writes (``os.environ["X"] = ...``) are exempt — the CLI forwards knobs
to workers that way.
"""

import ast
from typing import Dict, Iterator, Optional

from ..astutil import (
    call_name,
    dotted_name,
    first_arg,
    module_string_constants,
    resolve_string,
)
from ..core import Finding, LintContext, SourceFile

#: dotted callee names that read the raw environment
_RAW_READERS = ("os.environ.get", "os.getenv", "environ.get", "getenv")


def _live_registry() -> Dict:
    from gordo_tpu.utils.env import KNOBS

    return KNOBS


class EnvRegistryRule:
    name = "env-registry"
    description = (
        "GORDO_TPU_* reads must use the typed utils.env accessors and "
        "name a documented registry knob"
    )

    def __init__(self, registry: Optional[Dict] = None):
        self._registry = registry

    @property
    def registry(self) -> Dict:
        if self._registry is None:
            self._registry = _live_registry()
        return self._registry

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        prefix = ctx.contracts.env_prefix
        accessors = set(ctx.contracts.env_accessors)
        local_constants = module_string_constants(file.tree)
        in_accessor_module = file.module == ctx.contracts.env_accessor_module
        for node in ast.walk(file.tree):
            knob: Optional[str] = None
            raw_read = False
            if isinstance(node, ast.Call):
                callee = call_name(node) or ""
                if callee in _RAW_READERS:
                    knob = resolve_string(
                        first_arg(node), local_constants, ctx.env_constants
                    )
                    raw_read = True
                elif callee.split(".")[-1] in accessors:
                    knob = resolve_string(
                        first_arg(node), local_constants, ctx.env_constants
                    )
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and (dotted_name(node.value) or "").endswith("environ")
            ):
                knob = resolve_string(
                    node.slice, local_constants, ctx.env_constants
                )
                raw_read = True
            if knob is None or not knob.startswith(prefix):
                continue
            line, col = node.lineno, node.col_offset
            if raw_read and not in_accessor_module:
                yield Finding(
                    rule=self.name,
                    path=file.relpath,
                    line=line,
                    col=col,
                    message=(
                        f"raw environ read of `{knob}` — route it through "
                        f"a typed accessor in "
                        f"{ctx.contracts.env_accessor_module} "
                        "(malformed values must warn and fall back, not "
                        "raise)"
                    ),
                )
            declared = self.registry.get(knob)
            if declared is None:
                yield Finding(
                    rule=self.name,
                    path=file.relpath,
                    line=line,
                    col=col,
                    message=(
                        f"undeclared knob `{knob}` — add it to the "
                        "registry in gordo_tpu/utils/env.py (name, type, "
                        "default, doc) and regenerate docs/configuration.md"
                    ),
                )
            elif not getattr(declared, "doc", ""):
                yield Finding(
                    rule=self.name,
                    path=file.relpath,
                    line=line,
                    col=col,
                    message=(
                        f"knob `{knob}` is declared without a doc line — "
                        "the generated reference table would be empty"
                    ),
                )
