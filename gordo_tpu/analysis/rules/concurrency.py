"""
The concurrency contract rules — the invariants that kept PRs 4–12's
threaded serving stack correct, machine-checked (CHANGES.md records six
of them being caught by hand: double-folded rollups, gunicorn-preload
frozen pid paths, scrape-vs-/slo read-modify-write races,
MRU-eviction-of-the-serving-fleet).

Four rules share one per-file concurrency model (:func:`scope_models` —
built once per SourceFile and cached on it):

``lock-guard``
    Per class (and per module, for module-level locks), infer which
    ``threading.Lock``/``RLock`` guards which attributes: an attribute
    ever *written* inside a ``with <lock>:`` block (outside
    ``__init__``) is guarded by that lock. Any write of a guarded
    attribute outside every guarding lock is a finding, as is
    ``return self.<guarded>`` (publishing the live mutable object to
    callers that hold no lock) — unless the attribute is declared
    copy-on-write in contracts.toml, where lock-free reads of the
    replaced-whole object are the design. Helper methods whose every
    in-scope call site holds a lock (computed to fixpoint, so
    ``submit -> _take_batch -> _ready_key`` chains resolve) count as
    lock-held, and a ``Condition(self._lock)`` aliases its underlying
    lock. Module semantics are honest Python: a bare ``NAME = ...``
    inside a function only counts as a module write under a ``global``
    declaration; ``REGISTRY[k] = ...`` counts when ``REGISTRY`` is
    module-level.

``cow-publish``
    Attributes declared copy-on-write (``[[concurrency.cow]]``) may
    only be *replaced* (whole-object assignment); any in-place mutation
    — ``.append``/``.update``/``.setdefault``/``.pop``/``.clear``,
    ``x[k] = v``, ``del x[k]``, ``+=`` — is a finding: a reader holding
    the old reference would see the dict mutate under its feet, which
    is exactly what the COW discipline exists to prevent. Attribute
    receivers (``fleet._models.update(...)``) are flagged tree-wide;
    bare-name receivers only inside the declaring module.

``fork-safety``
    A function that derives state from process identity (a declared
    ``pid_source``: ``os.getpid``, ``worker_sink_path``, …) and stores
    it in a module-level mutable registry builds the
    gunicorn-``--preload`` frozen-pid bug class: every forked worker
    inherits the parent's memoized value and clobbers one shared sink.
    Such modules must register a post-fork reset hook
    (``utils.postfork.register_postfork_reset`` /
    ``os.register_at_fork``) at import time.

``thread-lifecycle``
    Every ``threading.Thread(...)`` must be ``daemon=True`` or joined
    somewhere in its module (a non-daemon, never-joined thread turns
    SIGTERM into a hang), and every ``while True:`` loop inside a
    thread-target function must be able to stop: a ``return``/``break``
    or a stop-event check in the body.
"""

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..astutil import call_name, dotted_name, parent
from ..contracts import in_scope
from ..core import Finding, LintContext, SourceFile

#: callee tails that construct a lock-like object
_LOCK_FACTORIES = ("Lock", "RLock")
_CONDITION_FACTORIES = ("Condition",)

#: method-call tails that mutate a container in place (shared by the
#: cow-publish rule and the lock-guard write inference)
_MUTATORS = (
    "append",
    "extend",
    "insert",
    "remove",
    "add",
    "discard",
    "update",
    "setdefault",
    "pop",
    "popitem",
    "clear",
    "appendleft",
    "popleft",
    "move_to_end",
)


def _is_lock_factory(node: ast.expr) -> Optional[str]:
    """``"lock"`` / ``"condition"`` when ``node`` constructs one."""
    if not isinstance(node, ast.Call):
        return None
    tail = (call_name(node) or "").split(".")[-1]
    if tail in _LOCK_FACTORIES:
        return "lock"
    if tail in _CONDITION_FACTORIES:
        return "condition"
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """``attr`` for a ``self.attr`` access, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _write_targets(node: ast.stmt) -> List[ast.expr]:
    """The expressions a statement assigns into (plain/aug/ann/del)."""
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, ast.AugAssign):
        return [node.target]
    if isinstance(node, ast.AnnAssign):
        return [node.target]
    if isinstance(node, ast.Delete):
        return list(node.targets)
    return []


def _mutated_receiver(call: ast.Call) -> Optional[ast.expr]:
    """The receiver expression of an in-place mutator call
    (``<recv>.append(...)``, ``<recv>[k].update(...)``), else None."""
    func = call.func
    if not (isinstance(func, ast.Attribute) and func.attr in _MUTATORS):
        return None
    receiver = func.value
    while isinstance(receiver, ast.Subscript):
        receiver = receiver.value
    return receiver


class _FunctionModel:
    """One function's concurrency-relevant facts."""

    __slots__ = ("name", "node", "writes", "returns", "calls")

    def __init__(self, name: str, node: ast.AST):
        self.name = name
        self.node = node
        #: [(attr, lexically-held locks, ast node)] for scope-attr writes
        self.writes: List[Tuple[str, Set[str], ast.AST]] = []
        #: same shape, for ``return <scope attr>`` publications
        self.returns: List[Tuple[str, Set[str], ast.AST]] = []
        #: {(callee name, frozenset of lexically-held locks)}
        self.calls: Set[Tuple[str, frozenset]] = set()


class _ScopeModel:
    """The inferred lock model of one class (or the module scope)."""

    def __init__(self, label: str):
        self.label = label
        #: lock attribute -> canonical lock (Condition aliases collapse)
        self.locks: Dict[str, str] = {}
        #: attribute -> locks it is ever written under (outside __init__)
        self.guards: Dict[str, Set[str]] = {}
        self.functions: Dict[str, _FunctionModel] = {}
        #: function -> the lock-sets it may run under, propagated from
        #: its call sites to fixpoint (the `submit -> _take_batch ->
        #: _ready_key` chain); a public function always includes the
        #: empty context (external callers hold nothing)
        self.contexts: Dict[str, Set[frozenset]] = {}

    def canonical(self, lock_attr: str) -> str:
        return self.locks.get(lock_attr, lock_attr)

    def occurrence_contexts(self, fn_name: str) -> Set[frozenset]:
        return self.contexts.get(fn_name) or {frozenset()}


def _collect_locks(statements, attr_of, model: _ScopeModel, deep: bool) -> None:
    """Record lock/Condition constructions assigned to scope attributes.

    ``deep`` walks into function bodies (class ``__init__`` assigns
    ``self._lock`` there); module scope stays shallow so function-local
    ``lock = threading.Lock()`` temporaries don't pollute the model.
    """
    for top in statements:
        nodes = ast.walk(top) if deep else [top]
        for stmt in nodes:
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            value = stmt.value
            if value is None:
                continue
            kind = _is_lock_factory(value)
            if kind is None:
                continue
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            for target in targets:
                attr = attr_of(target)
                if attr is None:
                    continue
                if kind == "lock":
                    model.locks.setdefault(attr, attr)
                else:
                    # Condition(self._lock) shares its underlying lock:
                    # `with self._work:` and `with self._lock:` must
                    # count as the same guard
                    inner = attr_of(value.args[0]) if value.args else None
                    model.locks[attr] = (
                        model.canonical(inner) if inner else attr
                    )


def _held_lexically(node: ast.AST, attr_of, model: _ScopeModel) -> Set[str]:
    """Canonical locks held at ``node`` via enclosing ``with`` blocks."""
    held: Set[str] = set()
    current = parent(node)
    while current is not None:
        if isinstance(current, ast.With):
            for item in current.items:
                attr = attr_of(item.context_expr)
                if attr is not None and attr in model.locks:
                    held.add(model.canonical(attr))
        current = parent(current)
    return held


def _function_statements(fn: ast.AST):
    """Walk ``fn`` without descending into nested function defs (nested
    defs are modeled as their own functions)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


def _build_scope_model(
    label: str,
    statements,
    lock_attr_of,
    function_nodes,
    write_maps_for,
    deep_locks: bool,
) -> _ScopeModel:
    """Infer one scope's lock model.

    ``lock_attr_of`` resolves lock constructions and ``with`` targets to
    scope-attribute names. ``write_maps_for(fn)`` returns
    ``(bind_of, read_of)``: ``bind_of`` maps a plain rebind target to a
    scope attribute (module scope requires a ``global`` declaration —
    honest Python semantics), ``read_of`` maps reads/subscript bases.
    """
    model = _ScopeModel(label)
    _collect_locks(statements, lock_attr_of, model, deep=deep_locks)
    if not model.locks:
        return model

    #: in-scope callee resolution needs the function NAMES too — at
    #: module scope a bare `helper()` call is a plain Name that the
    #: write maps (rightly) don't treat as module state
    fn_names = {fn.name for fn in function_nodes}

    for fn in function_nodes:
        bind_of, read_of = write_maps_for(fn)
        fmodel = _FunctionModel(fn.name, fn)
        model.functions.setdefault(fn.name, fmodel)
        for node in _function_statements(fn):
            if isinstance(node, ast.stmt):
                for target in _write_targets(node):
                    attr = bind_of(target)
                    if attr is None and isinstance(target, ast.Subscript):
                        attr = read_of(target.value)
                    if attr is None or attr in model.locks:
                        continue
                    held = _held_lexically(node, lock_attr_of, model)
                    fmodel.writes.append((attr, held, node))
            if isinstance(node, ast.Call):
                receiver = _mutated_receiver(node)
                if receiver is not None:
                    attr = read_of(receiver)
                    if attr is not None and attr not in model.locks:
                        held = _held_lexically(node, lock_attr_of, model)
                        fmodel.writes.append((attr, held, node))
                callee = read_of(node.func)
                if callee is None and (
                    isinstance(node.func, ast.Name)
                    and node.func.id in fn_names
                ):
                    callee = node.func.id
                if callee is not None:
                    held = _held_lexically(node, lock_attr_of, model)
                    fmodel.calls.add((callee, frozenset(held)))
            if isinstance(node, ast.Return) and node.value is not None:
                attr = read_of(node.value)
                if attr is not None and attr not in model.locks:
                    held = _held_lexically(node, lock_attr_of, model)
                    fmodel.returns.append((attr, held, node))

    # call-context fixpoint: the lock-sets each function may run under.
    # Seeds: a PUBLIC function (no leading underscore) runs from outside
    # with nothing held; a private helper runs only from its in-scope
    # call sites (each contributing site-lexical locks ∪ the caller's
    # own contexts). Thread targets and other never-called privates
    # default to the empty context via occurrence_contexts().
    called_in_scope = {
        callee
        for fmodel in model.functions.values()
        for callee, _ in fmodel.calls
        if callee in model.functions
    }
    for name in model.functions:
        model.contexts[name] = set()
        if not name.startswith("_") or name not in called_in_scope:
            model.contexts[name].add(frozenset())
    changed = True
    while changed:
        changed = False
        for caller in model.functions.values():
            # propagate only contexts established so far — the
            # empty-context DEFAULT is a check-time fallback, not a real
            # context. Using occurrence_contexts() here would let a
            # not-yet-seeded private caller inject a spurious unlocked
            # context into its callees on the first sweep, and the
            # monotone accumulation would never retract it (false
            # lock-guard positives on two-level locked call chains).
            caller_contexts = model.contexts.get(caller.name) or ()
            for callee, held in caller.calls:
                if callee not in model.functions:
                    continue
                target = model.contexts[callee]
                for context in caller_contexts:
                    merged = frozenset(held | context)
                    # cap pathological growth; tiny in practice
                    if merged not in target and len(target) < 16:
                        target.add(merged)
                        changed = True

    # guard inference: an attribute is guarded by every lock any of its
    # writes can hold — lexically or via a locked call context.
    # Construction (`__init__`) is excluded: the object is unshared.
    for fmodel in model.functions.values():
        if fmodel.name in ("__init__", "__new__"):
            continue
        contexts = model.occurrence_contexts(fmodel.name)
        for attr, held, _ in fmodel.writes:
            for context in contexts:
                effective = held | context
                if effective:
                    model.guards.setdefault(attr, set()).update(effective)
    return model


def scope_models(file: SourceFile):
    """(label, :class:`_ScopeModel`) for the module scope and every
    class in ``file`` — built once and cached on the SourceFile."""
    cached = getattr(file, "_gt_concurrency_models", None)
    if cached is not None:
        return cached

    models = []

    # -- module scope -------------------------------------------------------
    module_names: Set[str] = set()
    for node in file.tree.body:
        for target in _write_targets(node) if isinstance(node, ast.stmt) else []:
            if isinstance(target, ast.Name):
                module_names.add(target.id)

    def module_lock_of(expr):
        return expr.id if isinstance(expr, ast.Name) else None

    def module_write_maps(fn):
        declared_global: Set[str] = set()
        for node in _function_statements(fn):
            if isinstance(node, ast.Global):
                declared_global.update(node.names)

        def bind_of(expr):
            if isinstance(expr, ast.Name) and expr.id in declared_global:
                return expr.id
            return None

        def read_of(expr):
            if isinstance(expr, ast.Name) and expr.id in module_names:
                return expr.id
            return None

        return bind_of, read_of

    module_functions = [
        node
        for node in ast.walk(file.tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and not any(
            isinstance(anc, ast.ClassDef) for anc in _ancestors(node)
        )
    ]
    models.append(
        (
            file.module.rsplit(".", 1)[-1],
            _build_scope_model(
                file.module,
                file.tree.body,
                module_lock_of,
                module_functions,
                module_write_maps,
                deep_locks=False,
            ),
        )
    )

    # -- class scopes -------------------------------------------------------
    def class_write_maps(_fn):
        return _self_attr, _self_attr

    for node in ast.walk(file.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        methods = [
            child
            for child in ast.walk(node)
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        models.append(
            (
                node.name,
                _build_scope_model(
                    node.name,
                    node.body,
                    _self_attr,
                    methods,
                    class_write_maps,
                    deep_locks=True,
                ),
            )
        )
    file._gt_concurrency_models = models  # type: ignore[attr-defined]
    return models


def _ancestors(node: ast.AST):
    current = parent(node)
    while current is not None:
        yield current
        current = parent(current)


def _cow_attributes_for(contracts, module: str) -> Dict[str, Set[str]]:
    """scope label -> declared COW attributes for ``module`` (entries
    with no class apply to every scope, keyed ``"*"``)."""
    table: Dict[str, Set[str]] = {}
    for entry in getattr(contracts, "concurrency_cow", ()):
        if entry.module and not (
            module == entry.module or module.startswith(entry.module + ".")
        ):
            continue
        table.setdefault(entry.cls or "*", set()).update(entry.attributes)
    return table


def _lock_names(guards: Set[str]) -> str:
    return "/".join(sorted(guards))


class LockGuardRule:
    name = "lock-guard"
    description = (
        "writes (and publishing returns) of lock-guarded attributes must "
        "hold the inferred guarding lock"
    )

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        scopes = getattr(ctx.contracts, "concurrency_lock_scopes", ())
        if scopes and not in_scope(file.module, scopes):
            return
        cow = _cow_attributes_for(ctx.contracts, file.module)
        for label, model in scope_models(file):
            if not model.locks:
                continue
            cow_attrs = cow.get(label, set()) | cow.get("*", set())
            for fmodel in model.functions.values():
                if fmodel.name in ("__init__", "__new__"):
                    continue
                contexts = model.occurrence_contexts(fmodel.name)
                # a site is a violation when some call path reaches it
                # with nothing held (lexical locks included)
                def reachable_bare(lexical):
                    return any(not (lexical | set(c)) for c in contexts)

                for attr, lexical, node in fmodel.writes:
                    guards = model.guards.get(attr)
                    if not guards or not reachable_bare(lexical):
                        continue
                    yield Finding(
                        rule=self.name,
                        path=file.relpath,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"{label}.{attr} is written under "
                            f"{_lock_names(guards)} elsewhere but written "
                            f"here with no lock held — a concurrent locked "
                            f"writer can interleave and lose this update"
                        ),
                    )
                for attr, lexical, node in fmodel.returns:
                    guards = model.guards.get(attr)
                    if not guards or attr in cow_attrs:
                        continue
                    if not reachable_bare(lexical):
                        continue
                    yield Finding(
                        rule=self.name,
                        path=file.relpath,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"{label}.{attr} (guarded by "
                            f"{_lock_names(guards)}) is returned without "
                            f"its lock — callers receive the live mutable "
                            f"object; return a copy, hold the lock, or "
                            f"declare it copy-on-write in contracts.toml"
                        ),
                    )


class CowPublishRule:
    name = "cow-publish"
    description = (
        "copy-on-write attributes may only be replaced whole under their "
        "lock, never mutated in place"
    )

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        entries = getattr(ctx.contracts, "concurrency_cow", ())
        if not entries:
            return
        #: attribute-spelled receivers (`x._models`) are flagged
        #: tree-wide; bare names only inside the declaring module (bare
        #: names are too common for a global claim)
        attr_names: Set[str] = set()
        local_names: Set[str] = set()
        for entry in entries:
            attr_names.update(entry.attributes)
            if not entry.module or in_scope(
                file.module, (entry.module,)
            ) or file.module == entry.module:
                local_names.update(entry.attributes)

        def cow_name(expr) -> Optional[str]:
            if isinstance(expr, ast.Attribute) and expr.attr in attr_names:
                return expr.attr
            if isinstance(expr, ast.Name) and expr.id in local_names:
                return expr.id
            return None

        for node in ast.walk(file.tree):
            if isinstance(node, ast.Call):
                receiver = _mutated_receiver(node)
                if receiver is None:
                    continue
                name = cow_name(receiver)
                if name is not None:
                    mutator = node.func.attr  # type: ignore[union-attr]
                    yield self._finding(file, node, name, f".{mutator}(...)")
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
                for target in _write_targets(node):
                    if isinstance(target, ast.Subscript):
                        name = cow_name(target.value)
                        if name is not None:
                            yield self._finding(
                                file, node, name, "[...] assignment"
                            )
                    elif isinstance(node, ast.AugAssign):
                        name = cow_name(target)
                        if name is not None:
                            yield self._finding(
                                file, node, name, "augmented assignment"
                            )

    def _finding(self, file, node, attr, how) -> Finding:
        return Finding(
            rule=self.name,
            path=file.relpath,
            line=node.lineno,
            col=node.col_offset,
            message=(
                f"in-place {how} on copy-on-write attribute `{attr}` — "
                "COW attributes are read lock-free; mutate a copy and "
                "replace the whole object under the lock"
            ),
        )


class ForkSafetyRule:
    name = "fork-safety"
    description = (
        "module-level registries memoizing pid-derived state need a "
        "registered post-fork reset hook"
    )

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        contracts = ctx.contracts
        scopes = getattr(contracts, "concurrency_fork_scopes", ())
        if scopes and not in_scope(file.module, scopes):
            return
        pid_sources = set(getattr(contracts, "concurrency_pid_sources", ()))
        registrars = set(
            getattr(contracts, "concurrency_postfork_registrars", ())
        )
        if not pid_sources:
            return
        pid_tails = {source.split(".")[-1] for source in pid_sources}

        # a "registry" is module-level memoized state: a mutable literal
        # (`_ledgers = {}` — AnnAssign included) or a module name some
        # function rebinds via `global` (`_recorder = SpanRecorder(...)`,
        # the memoized-singleton spelling of the same bug class)
        registries: Set[str] = set()
        module_names: Set[str] = set()
        for node in file.tree.body:
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            module_names.update(names)
            mutable = isinstance(value, (ast.Dict, ast.List, ast.Set)) or (
                isinstance(value, ast.Call)
                and (call_name(value) or "").split(".")[-1]
                in ("dict", "list", "set", "deque", "defaultdict", "OrderedDict")
            )
            if mutable:
                registries.update(names)
        for node in ast.walk(file.tree):
            if isinstance(node, ast.Global):
                registries.update(
                    name for name in node.names if name in module_names
                )
        if not registries:
            return

        for node in ast.walk(file.tree):
            if isinstance(node, ast.Call):
                callee = call_name(node) or ""
                if (
                    callee in registrars
                    or callee.split(".")[-1] in registrars
                    or callee.endswith(".register_at_fork")
                ):
                    return  # the module resets itself after fork

        for fn in ast.walk(file.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            declared_global: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    declared_global.update(node.names)
            calls_pid = False
            store_node: Optional[ast.AST] = None
            stored: Optional[str] = None
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = call_name(node) or ""
                    if callee in pid_sources or callee.split(".")[-1] in pid_tails:
                        calls_pid = True
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    for target in _write_targets(node):
                        name = None
                        if isinstance(target, ast.Subscript) and isinstance(
                            target.value, ast.Name
                        ):
                            name = target.value.id
                        elif isinstance(target, ast.Name) and (
                            target.id in declared_global
                        ):
                            # plain rebinds are module writes only under
                            # a `global` declaration (locals that shadow
                            # a registry name are just locals)
                            name = target.id
                        if name in registries:
                            stored, store_node = name, node
            if calls_pid and store_node is not None:
                yield Finding(
                    rule=self.name,
                    path=file.relpath,
                    line=store_node.lineno,
                    col=store_node.col_offset,
                    message=(
                        f"`{fn.name}` derives state from a process-identity "
                        f"source and memoizes it in module registry "
                        f"`{stored}` with no post-fork reset hook — a "
                        f"forked worker (gunicorn --preload) inherits the "
                        f"parent's pid-frozen value; register a reset via "
                        f"utils.postfork.register_postfork_reset"
                    ),
                )


def _is_thread_join(call: ast.Call) -> bool:
    """Thread.join's signature, not str/os.path join: no positional
    args, or one numeric timeout (constant or name), or only a
    ``timeout=`` keyword — ``os.path.join(a, b)`` and ``sep.join(parts)``
    must not count as shutdown evidence."""
    if any(kw.arg not in ("timeout",) for kw in call.keywords):
        return False
    if len(call.args) > 1:
        return False
    if call.args:
        arg = call.args[0]
        if isinstance(arg, ast.Constant):
            return isinstance(arg.value, (int, float)) and not isinstance(
                arg.value, bool
            )
        # a bare name only counts when it reads like a duration —
        # `thread.join(timeout)` yes, `sep.join(parts)` no
        name = (dotted_name(arg) or "").rsplit(".", 1)[-1].lower()
        return any(hint in name for hint in ("timeout", "deadline", "second", "wait"))
    return True


class ThreadLifecycleRule:
    name = "thread-lifecycle"
    description = (
        "threads must be daemon=True or joined; thread worker loops must "
        "be stoppable"
    )

    def check(self, file: SourceFile, ctx: LintContext) -> Iterator[Finding]:
        thread_targets: Set[str] = set()
        joins_anything = False
        thread_calls: List[ast.Call] = []
        for node in ast.walk(file.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = call_name(node) or ""
            tail = callee.split(".")[-1]
            if tail == "Thread" and (
                callee in ("Thread", "threading.Thread")
                or callee.endswith(".Thread")
            ):
                thread_calls.append(node)
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = dotted_name(kw.value)
                        if target:
                            thread_targets.add(target.rsplit(".", 1)[-1])
            elif tail == "join" and "." in callee and _is_thread_join(node):
                joins_anything = True

        for node in thread_calls:
            daemon = None
            for kw in node.keywords:
                if kw.arg == "daemon":
                    if isinstance(kw.value, ast.Constant):
                        daemon = bool(kw.value.value)
                    else:
                        daemon = True  # dynamic — benefit of the doubt
            if daemon:
                continue
            # non-daemon threads demand a join somewhere in the module
            # (precise reachability is the runtime lockgraph harness's
            # job; the static contract is "shutdown CAN reach it")
            if joins_anything:
                continue
            yield Finding(
                rule=self.name,
                path=file.relpath,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    "threading.Thread without daemon=True and no join() "
                    "anywhere in this module — a forgotten non-daemon "
                    "thread turns process shutdown into a hang"
                ),
            )

        for fn in ast.walk(file.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name not in thread_targets:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.While):
                    continue
                test = node.test
                if not (isinstance(test, ast.Constant) and test.value is True):
                    continue
                if self._loop_stoppable(node):
                    continue
                yield Finding(
                    rule=self.name,
                    path=file.relpath,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"`while True` worker loop in thread target "
                        f"`{fn.name}` has no reachable stop: add a "
                        f"break/return on a stop-event check so "
                        f"drain/shutdown can end it"
                    ),
                )

    @staticmethod
    def _loop_stoppable(loop: ast.While) -> bool:
        for node in ast.walk(loop):
            if isinstance(node, (ast.Break, ast.Return)):
                return True
            if isinstance(node, ast.Call):
                tail = (call_name(node) or "").split(".")[-1]
                if tail in ("is_set", "wait"):
                    return True
        return False
