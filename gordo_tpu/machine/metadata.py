"""
Build-metadata schema.

Reference parity: gordo/machine/metadata/metadata.py:16-55 — the dataclass
tree recorded per build and served from ``/metadata``:
``Metadata{user_defined, build_metadata}`` with model/dataset build records.
"""

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

try:
    from dataclasses_json import dataclass_json
except ImportError:  # container without dataclasses_json
    from ..utils.dataclasses_compat import dataclass_json


@dataclass_json
@dataclass
class CrossValidationMetaData:
    scores: Dict[str, Any] = field(default_factory=dict)
    cv_duration_sec: Optional[float] = None
    splits: Dict[str, Any] = field(default_factory=dict)


@dataclass_json
@dataclass
class TrainingSummaryMetadata:
    """Per-member training-history summary, captured from the fit's
    ``History`` carry: final/best losses, how many epochs actually ran
    vs were configured, and where early stopping cut in (``None`` when
    the fit ran to its configured epoch count)."""

    final_loss: Optional[float] = None
    best_loss: Optional[float] = None
    final_val_loss: Optional[float] = None
    best_val_loss: Optional[float] = None
    epochs_run: int = 0
    epochs_configured: int = 0
    early_stop_epoch: Optional[int] = None

    @classmethod
    def from_history(cls, history) -> "TrainingSummaryMetadata":
        """Summarize a Keras-History-shaped fit record (duck-typed:
        ``.history`` dict of loss lists, ``.params`` dict, ``.epoch``
        list) — shared by the fleet builder and the sequential
        ModelBuilder so both artifact paths carry the same fields."""
        losses = [float(l) for l in history.history.get("loss") or []]
        val = [float(l) for l in history.history.get("val_loss") or []]
        epochs_run = len(history.epoch)
        configured = int(
            history.params.get("epochs", epochs_run) or epochs_run
        )
        early = epochs_run < configured
        return cls(
            final_loss=losses[-1] if losses else None,
            best_loss=min(losses) if losses else None,
            final_val_loss=val[-1] if val else None,
            best_val_loss=min(val) if val else None,
            epochs_run=epochs_run,
            epochs_configured=configured,
            early_stop_epoch=epochs_run if early else None,
        )


@dataclass_json
@dataclass
class ModelBuildMetadata:
    model_offset: int = 0
    model_creation_date: Optional[str] = None
    model_builder_version: Optional[str] = None
    cross_validation: CrossValidationMetaData = field(
        default_factory=CrossValidationMetaData
    )
    model_training_duration_sec: Optional[float] = None
    model_meta: Dict[str, Any] = field(default_factory=dict)
    training: TrainingSummaryMetadata = field(
        default_factory=TrainingSummaryMetadata
    )


@dataclass_json
@dataclass
class DatasetBuildMetadata:
    query_duration_sec: Optional[float] = None
    dataset_meta: Dict[str, Any] = field(default_factory=dict)


@dataclass_json
@dataclass
class DriftBaselineMetadata:
    """Training-data distribution baseline the lifecycle drift monitor
    (``gordo_tpu.lifecycle.drift``) tests scored serving data against:
    per-tag means/stds of the RAW input frame (the same space serving
    requests arrive in — host transformers run after this point) plus
    the sample count behind them. Residual (reconstruction-error)
    baselines are calibrated online by the monitor from the first scored
    window, because training loss lives in the estimator's scaled space
    while serving residuals are raw-target-space mse."""

    tags: List[str] = field(default_factory=list)
    feature_means: List[float] = field(default_factory=list)
    feature_stds: List[float] = field(default_factory=list)
    n_samples: int = 0

    @classmethod
    def from_frame(cls, X) -> "DriftBaselineMetadata":
        """Baseline from a training DataFrame (raw, pre-transform).
        NaN-aware: sensor frames carry NaN rows, and a NaN mean/std
        would silently disable the monitor's feature test for that
        tag (an all-NaN column stays NaN → serialized null → the
        monitor treats the tag as unmeasurable)."""
        import warnings

        import numpy as np

        values = np.asarray(X.to_numpy(), dtype=float)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)  # all-NaN cols
            means = np.nanmean(values, axis=0)
            stds = np.nanstd(values, axis=0)
        return cls(
            tags=[str(c) for c in X.columns],
            feature_means=[round(float(v), 8) for v in means],
            feature_stds=[round(float(v), 8) for v in stds],
            n_samples=int(len(values)),
        )


@dataclass_json
@dataclass
class RobustnessMetadata:
    """Per-machine fleet-build robustness counters: diverged-member
    reseed retries, bucket bisection (split-retry) events the machine's
    members rode through, and data-fetch retry total."""

    fleet_retries: int = 0
    bucket_bisects: int = 0
    data_fetch_retries: int = 0


@dataclass_json
@dataclass
class BuildMetadata:
    model: ModelBuildMetadata = field(default_factory=ModelBuildMetadata)
    dataset: DatasetBuildMetadata = field(default_factory=DatasetBuildMetadata)
    robustness: RobustnessMetadata = field(default_factory=RobustnessMetadata)
    drift_baseline: DriftBaselineMetadata = field(
        default_factory=DriftBaselineMetadata
    )


@dataclass_json
@dataclass
class Metadata:
    user_defined: Dict[str, Any] = field(default_factory=dict)
    build_metadata: BuildMetadata = field(default_factory=BuildMetadata)


def _metadata_to_dict(self: Metadata, **_kwargs) -> Dict[str, Any]:
    """
    Snapshot of the tree as plain dicts (independent copies, like the
    dataclasses_json walk it replaces). Hand-rolled because the schema
    is fixed and small while the ``Dict[str, Any]`` leaves (CV scores,
    model_meta) hold hundreds of entries: the generic walk's
    per-value typing introspection was ~20ms per machine — a real cost
    when dumping a thousand-machine fleet's metadata.
    """
    model = self.build_metadata.model
    dataset = self.build_metadata.dataset
    robustness = self.build_metadata.robustness
    baseline = self.build_metadata.drift_baseline
    training = model.training
    return {
        "user_defined": copy.deepcopy(self.user_defined),
        "build_metadata": {
            "model": {
                "model_offset": model.model_offset,
                "model_creation_date": model.model_creation_date,
                "model_builder_version": model.model_builder_version,
                "cross_validation": {
                    "scores": copy.deepcopy(model.cross_validation.scores),
                    "cv_duration_sec": model.cross_validation.cv_duration_sec,
                    "splits": copy.deepcopy(model.cross_validation.splits),
                },
                "model_training_duration_sec": model.model_training_duration_sec,
                "model_meta": copy.deepcopy(model.model_meta),
                "training": {
                    "final_loss": training.final_loss,
                    "best_loss": training.best_loss,
                    "final_val_loss": training.final_val_loss,
                    "best_val_loss": training.best_val_loss,
                    "epochs_run": training.epochs_run,
                    "epochs_configured": training.epochs_configured,
                    "early_stop_epoch": training.early_stop_epoch,
                },
            },
            "dataset": {
                "query_duration_sec": dataset.query_duration_sec,
                "dataset_meta": copy.deepcopy(dataset.dataset_meta),
            },
            "robustness": {
                "fleet_retries": robustness.fleet_retries,
                "bucket_bisects": robustness.bucket_bisects,
                "data_fetch_retries": robustness.data_fetch_retries,
            },
            "drift_baseline": {
                "tags": list(baseline.tags),
                "feature_means": list(baseline.feature_means),
                "feature_stds": list(baseline.feature_stds),
                "n_samples": baseline.n_samples,
            },
        },
    }


# Installed AFTER decoration: @dataclass_json unconditionally assigns
# cls.to_dict = DataClassJsonMixin.to_dict, so a to_dict defined in the
# class body is silently clobbered by the decorator.
Metadata.to_dict = _metadata_to_dict  # type: ignore[method-assign]
